"""The textual command API (``client/web_interface.py`` parity).

``CommandConsole.query(text)`` implements the command language
documented at ``web_interface.py:14-55`` and dispatched at ``:133-303``.
Instead of pushing to an eel websocket, every command returns the
console lines it produced (and streams them through an optional
``write`` callback), so the same dispatcher serves the CLI REPL, tests,
and any future UI.

Differences from the reference, on purpose:

- ``scraper on/off`` actually works (background thread over the
  session's comment store; the reference stubs it, ``:228-229``),
- errors surface as ``error: ...`` lines rather than a generic
  "An error has occurred" with the traceback on stdout,
- ``auto_fetch`` runs a daemon timer thread instead of an eel sleep
  loop (``oracle_scheduler.py:163-171``).
"""

from __future__ import annotations

import json
import threading
from typing import Callable, List, Optional

from svoc_tpu.apps.session import Session
from svoc_tpu.io.chain import ChainCommitError, to_hex


def _addr_str(addr) -> str:
    """Hex for felt ints, verbatim for symbolic sim addresses."""
    return to_hex(addr) if isinstance(addr, int) else str(addr)

HELP = """Commands:
    - help / clear / exit

    - fetch
    - auto_fetch on/off (default: off)
    - auto_commit on/off (default: off, ie. fetch => commit)
    - auto_resume on/off (default: off, ie. commit => resume)
    - scraper on/off (default: off)
    - live_mode on/off (default: off; scraper + auto_fetch + auto_commit)
    - metrics [prom|trace] (throughput / latency / stage percentiles;
      'prom' dumps the Prometheus text exposition the /metrics
      endpoint serves; 'trace' lists the most recent stage spans)
    - resilience (circuit-breaker state, per-slot oracle health
      scores, quarantine set, replacement count)
    - events [N] (the flight recorder's newest N journal events;
      default 10)
    - audit [lineage] (per-block audit record — events, spans, and a
      summary joined on one lineage id; default: the last fetch)
    - slo (declarative objectives as fast/slow burn rates; with a
      fabric/serving tier attached, per-claim and serving-tier burn
      rates follow the session's)
    - claims (multi-claim fabric status: per-claim cycles, consensus
      validity, replacements, lineage — docs/FABRIC.md)
    - serving [submit <claim> <text...> | step] (continuous-batching
      serving tier status / one manual request / one manual cycle —
      docs/SERVING.md)
    - durability [snapshot] (crash-consistency status: snapshot
      freshness, commit-intent WAL health, open cycles; 'snapshot'
      forces one — docs/RESILIENCE.md)
    - costs (shape-keyed dispatch-cost ledger: warm/cold EMA seconds
      per compile key + per-stage request latency decomposition —
      docs/OBSERVABILITY.md §cost-attribution)
    - profile [start [seconds]|stop|status] (on-demand jax.profiler
      capture, bounded duration; default: status)
    - cluster [status | migrate <claim> <replica> | adopt-orphans]
      (multi-replica fleet: placement map + epoch, per-replica
      health/breakers, one operator migration, or re-adoption of
      quarantined migration slices — docs/CLUSTER.md)
    - reconfig [status | apply <plan.json> | abort] (live
      reconfiguration plane: transactional drain → re-pin →
      recover-warm under traffic — docs/RECONFIG.md)
    - fleet (fleet observability plane: hop-chain count, per-source
      observation accounting, fleet SLO alerts, recent anomalies,
      postmortem bundles — docs/OBSERVABILITY.md §fleet-plane)
    - drain (graceful teardown: stop admission, flush queues,
      snapshot, postmortem bundle — what SIGTERM does)
    - multimodal [K|auto] (mixture analysis of the last fetch;
      default K=2, 'auto' selects K by BIC)

    - contract_declaration_address
    - contract_address

    - (S) commit (send update_prediction for each oracle)

    - (S) resume
    - (S) consensus
    - (S) reliability_first_pass
    - (S) reliability

    - (S) is_consensus_active

    - (S) admin_list
    - (S) oracle_list
    - (S) dimension
    - (S) replacement_menu
    - (S) replacement_propositions

    - (S) update_proposition <caller_admin> None
    - (S) update_proposition <caller_admin> <old_oracle> <new_oracle>
    - (S) vote_for_a_proposition <caller_admin> <which_admin> yes/no

For <admin> <oracle> arguments, specify either the contract index or
the address starting with "0x".

(S) indicates a chain interaction (local simulator or Sepolia).
"""


def on_off_to_bool(x: str) -> bool:
    return x == "on"


class CommandConsole:
    """Stateful command dispatcher over a :class:`Session`."""

    def __init__(
        self,
        session: Optional[Session] = None,
        write: Optional[Callable[[str], None]] = None,
    ):
        self.session = session or Session()
        self._write = write
        #: Multi-claim fabric (docs/FABRIC.md): set by
        #: ``MultiSession.attach`` — the ``claims`` command and
        #: ``/api/state``'s ``claims`` section read it.  None = the
        #: single-claim console of PRs 1–5, unchanged.
        self.fabric = None
        #: Continuous-batching serving tier (docs/SERVING.md): set by
        #: ``ServingTier.attach`` — the ``serving`` command,
        #: ``POST /api/submit``, and ``/api/state``'s ``serving``
        #: section read it.  None = no request path (batch-only).
        self.serving = None
        #: Durability layer (docs/RESILIENCE.md §durability): set by
        #: ``RecoveryManager.attach`` / ``GracefulDrain.attach`` — the
        #: ``durability``/``drain`` commands and ``/api/state``'s
        #: durability section read them.  None = in-memory-only.
        self.durability = None
        self.drainer = None
        #: On-demand profiler (docs/OBSERVABILITY.md
        #: §cost-attribution): set by ``ProfileCapture.attach`` — the
        #: ``profile`` command and ``GET /api/profile`` read it.
        self.profiler = None
        #: Multi-replica fleet router (docs/CLUSTER.md): set by
        #: ``ClusterRouter.attach`` — the ``cluster`` command and
        #: ``/api/state``'s cluster section read it.  None = the
        #: single-replica deployments of PRs 1–17, unchanged.
        self.cluster = None
        #: Live reconfiguration plane (docs/RECONFIG.md): set by
        #: ``ReconfigController.attach`` — the ``reconfig`` command and
        #: ``/api/state``'s reconfig section read it.  None = no
        #: transactional re-pin path (static fleet config).
        self.reconfig = None
        #: Fleet observability plane (docs/OBSERVABILITY.md
        #: §fleet-plane): set by ``FleetPlane.attach`` — the ``fleet``
        #: command, ``GET /metrics/fleet``, and ``/api/state``'s
        #: fleet-obs section read it.  None = no fleet plane wired.
        self.fleetplane = None
        self._auto_fetch_thread: Optional[threading.Thread] = None
        self._scraper_stop: Optional[threading.Event] = None
        self._scraper_thread: Optional[threading.Thread] = None
        #: Serializes background-loop lifecycle (auto_fetch generation
        #: token, scraper thread handles): query() is deliberately
        #: unserialized, so racing 'auto_fetch on' / 'scraper on'
        #: commands would otherwise both pass the check-then-act and
        #: leave two loops running.
        self._bg_lock = threading.Lock()

    # -- address/index parsing (web_interface.py:71-107) -------------------

    def _make_oracle_index(self, token: str) -> int:
        if token.upper().startswith("0X"):
            return self.session.adapter.address_to_oracle_index(int(token, 16))
        return int(token)

    def _make_admin_address(self, token: str):
        if token.upper().startswith("0X"):
            return int(token, 16)
        return self.session.adapter.admin_index_to_address(int(token))

    def _make_admin_index(self, token: str) -> int:
        if token.upper().startswith("0X"):
            return self.session.adapter.address_to_admin_index(int(token, 16))
        return int(token)

    def _propositions_as_str(self, only_not_none: bool = False) -> List[str]:
        lines = []
        for index, prop in enumerate(
            self.session.adapter.call_replacement_propositions()
        ):
            if prop is None:
                if not only_not_none:
                    lines.append(f"- Admin {index} : None")
            else:
                lines.append(f"- Admin {index} :")
                lines.append(f"  - {prop[0]} -> {hex(prop[1])}")
        return lines

    # -- dispatcher (web_interface.py:133-303) ------------------------------

    def query(self, text: str) -> List[str]:
        """Dispatch one command.

        Deliberately NOT serialized here: the web UI's
        ThreadingHTTPServer handlers, the stdin console, and the
        auto_fetch loop share one session, and holding a dispatch-wide
        lock would freeze all of them behind a slow chain RPC or the
        first fetch's model build.  Safety is layered below instead —
        session field mutation under ``session.lock``, whole-fleet
        commits under the commit lock, each chain read/tx atomic under
        the adapter lock (tx-granular interleaving beyond that matches
        the real chain), and the vectorizer build double-checked
        (``Session`` docstring)."""
        out: List[str] = []

        def emit(line: str) -> None:
            out.append(line)
            if self._write:
                self._write(line)

        parts = text.split()
        if not parts:
            return out
        cmd, args = parts[0], parts[1:]
        adapter = self.session.adapter

        try:
            if cmd == "help":
                emit(HELP)
            elif cmd == "clear":
                emit("\x1b[clear]")
            elif cmd == "exit":
                self.stop()
                self.session.application_on = False
            elif cmd == "fetch":
                emit("Processing ..")
                preview = self.session.fetch()
                emit(
                    f"fetched {preview['n_comments']} comments -> "
                    f"{self.session.config.n_oracles} oracle predictions"
                )
                emit(
                    "mean   : "
                    + ", ".join(f"{x:0.3f}" for x in preview["mean"])
                )
                emit(
                    "median : "
                    + ", ".join(f"{x:0.3f}" for x in preview["median"])
                )
                suspects = [
                    str(i)
                    for i, r in enumerate(preview["normalized_ranks"])
                    if r <= 0.2  # the UI's red threshold (simulation_graphics.js:97-99)
                ]
                emit("suspected failing oracles : " + ", ".join(suspects))
            elif cmd == "auto_fetch":
                if len(args) != 1:
                    emit("Unexpected number of arguments.")
                    return out
                # set_auto_flags bumps state_version: the web UI's push
                # stream surfaces flag toggles live, not on next fetch.
                self.session.set_auto_flags(fetch=on_off_to_bool(args[0]))
                if self.session.auto_fetch:
                    emit("Auto-Fetch: ENABLED")
                    self._start_auto_fetch()
                else:
                    emit("Auto-Fetch: DISABLED")
            elif cmd == "auto_commit":
                if len(args) != 1:
                    emit("Unexpected number of arguments.")
                    return out
                self.session.set_auto_flags(commit=on_off_to_bool(args[0]))
                emit(
                    "Auto-Commit: "
                    + ("ENABLED" if self.session.auto_commit else "DISABLED")
                )
            elif cmd == "auto_resume":
                if len(args) != 1:
                    emit("Unexpected number of arguments.")
                    return out
                self.session.set_auto_flags(resume=on_off_to_bool(args[0]))
                emit(
                    "Auto-Resume: "
                    + ("ENABLED" if self.session.auto_resume else "DISABLED")
                )
            elif cmd == "commit":
                if self.session.predictions is None:
                    emit("Fetch before!")
                else:
                    emit("Commit predictions...")
                    try:
                        n = self.session.commit()
                        emit(f"Done ({n} transactions).")
                    except ChainCommitError as e:
                        # Partial commits are ON CHAIN — say exactly how
                        # far the loop got and what broke it.
                        emit(
                            f"Commit FAILED after {e.committed}/{e.total} "
                            f"transactions at oracle "
                            f"{_addr_str(e.failed_oracle)}: {e.cause}"
                        )
            elif cmd == "consensus":
                consensus = adapter.call_consensus()
                emit("consensus :\n" + ",".join(f"{x:0.2f}" for x in consensus))
            elif cmd == "reliability_first_pass":
                emit(
                    "reliability_first_pass : "
                    f"{adapter.call_first_pass_consensus_reliability()}"
                )
            elif cmd == "reliability":
                emit(
                    "reliability : "
                    f"{adapter.call_second_pass_consensus_reliability()}"
                )
                trend = adapter.rel2_trend()
                if trend["n"] >= 2:
                    emit(
                        f"trend ({trend['n']} samples): "
                        f"{trend['delta']:+.3f}"
                        + (
                            "  ⚠ falling — a coordinated-bias approach "
                            "shows as a rel₂ slide (ALGORITHM.md §5)"
                            if trend["falling"]
                            else ""
                        )
                    )
            elif cmd == "resume":
                state = adapter.resume()
                self.session.bump_state()
                emit(f"consensus_active: {state['consensus_active']}")
                emit(
                    "consensus : "
                    + ", ".join(f"{x:0.2f}" for x in state["consensus"])
                )
                emit(
                    "reliability_first_pass : "
                    f"{state['reliability_first_pass']:0.3f}"
                )
                emit(
                    "reliability_second_pass : "
                    f"{state['reliability_second_pass']:0.3f}"
                )
                emit(
                    "skewness : "
                    + ", ".join(f"{x:0.2f}" for x in state["skewness"])
                )
                emit(
                    "kurtosis : "
                    + ", ".join(f"{x:0.2f}" for x in state["kurtosis"])
                )
            elif cmd == "is_consensus_active":
                emit(f"Is consensus active: {adapter.call_consensus_active()}")
            elif cmd == "admin_list":
                emit("[Admin list]")
                for idx, admin in enumerate(adapter.call_admin_list()):
                    emit(f"Admin {idx} : {admin}")
            elif cmd == "oracle_list":
                emit("[Oracle list]")
                for idx, oracle in enumerate(adapter.call_oracle_list()):
                    emit(f"Oracle {idx} : {oracle}")
            elif cmd == "dimension":
                emit(f"Dimension: {adapter.call_dimension()}")
            elif cmd in ("replacement_propositions", "replacement_menu"):
                emit("Replacement propositions :")
                for line in self._propositions_as_str():
                    emit(line)
            elif cmd == "update_proposition":
                caller = self._make_admin_address(args[0])
                if len(args) == 2 and args[1] == "None":
                    adapter.invoke_update_proposition(caller)
                    emit("Done.")
                elif len(args) == 3:
                    old_oracle = self._make_oracle_index(args[1])
                    # New address: 0x-hex or decimal, like every other
                    # address argument (help text contract).
                    new_oracle = (
                        int(args[2], 16)
                        if args[2].upper().startswith("0X")
                        else int(args[2])
                    )
                    adapter.invoke_update_proposition(
                        caller, old_oracle, new_oracle
                    )
                    emit("Done.")
                else:
                    emit("Unexpected number of arguments.")
            elif cmd == "vote_for_a_proposition":
                if len(args) != 3:
                    emit("Unexpected number of arguments.")
                    return out
                if args[2].upper() == "YES":
                    value = True
                elif args[2].upper() == "NO":
                    value = False
                else:
                    emit("Invalid command: only yes/no accepted")
                    return out
                caller = self._make_admin_address(args[0])
                which = self._make_admin_index(args[1])
                adapter.invoke_vote_for_a_proposition(caller, which, value)
                emit("Done.")
            elif cmd == "get_oracle_value_list":
                caller = self._make_admin_address(args[0]) if args else (
                    adapter.call_admin_list()[0]
                )
                from svoc_tpu.ops.fixedpoint import wsad_to_string

                for addr, vec, enabled, reliable in (
                    adapter.call_oracle_value_list_wsad(caller)
                ):
                    # wsad_to_string rendering (utils.cairo:283-297) —
                    # truncated 3-digit decimals of the EXACT stored
                    # wsad ints (a float round trip can lose an ulp and
                    # print a wrong digit).
                    values = ", ".join(
                        wsad_to_string(v, 3) for v in vec
                    )
                    emit(
                        f"{_addr_str(addr)} : [{values}] "
                        f"enabled={enabled} reliable={reliable}"
                    )
            elif cmd == "contract_declaration_address":
                emit(
                    "Contract Declaration Address :\n"
                    f"{self.session.config.declared_address}"
                )
            elif cmd == "contract_address":
                emit(
                    f"Contract Address :\n{self.session.config.deployed_address}"
                )
            elif cmd == "scraper":
                if len(args) != 1:
                    emit("Unexpected number of arguments.")
                    return out
                if on_off_to_bool(args[0]):
                    source_name = self._start_scraper()
                    if source_name is None:
                        emit("Scraper: not started (superseded or stopped)")
                    else:
                        emit(f"Scraper: ENABLED ({source_name})")
                else:
                    self._stop_scraper()
                    emit("Scraper: DISABLED")
            elif cmd == "metrics":
                from svoc_tpu.utils.metrics import (
                    registry as _metrics,
                    sample_runtime_gauges,
                    tracer as _tracer,
                )

                if len(args) > 1 or (args and args[0] not in ("prom", "trace")):
                    emit("Usage: metrics [prom|trace]")
                    return out
                # Same on-demand device/runtime gauge sample as the
                # /metrics endpoint — console and scrape agree.
                sample_runtime_gauges(_metrics)
                if args and args[0] == "prom":
                    for line in _metrics.render_prometheus().splitlines():
                        emit(line)
                elif args and args[0] == "trace":
                    spans = _tracer.recent(20)
                    if not spans:
                        emit("no spans recorded yet")
                    for s in spans:
                        emit(
                            f"{'  ' * s.depth}{s.name}: "
                            f"{s.duration_s * 1e3:.2f}ms "
                            f"[{s.thread}]"
                        )
                else:
                    lines = _metrics.report()
                    for line in lines or ["no metrics recorded yet"]:
                        emit(line)
            elif cmd == "resilience":
                snap = self.session.resilience_snapshot()
                emit(f"breaker: {snap['breaker']}")
                health = snap["health"]
                if health:
                    emit("oracle health (slot: score):")
                    for slot in sorted(health, key=int):
                        flag = (
                            "  QUARANTINED"
                            if int(slot) in snap["quarantined"]
                            else ""
                        )
                        emit(f"  {slot}: {health[slot]:.3f}{flag}")
                else:
                    emit("no health scores yet (no supervised commits)")
                emit(f"replacements: {snap['replacements']}")
                quarantine = snap.get("input_quarantine")
                if quarantine is None:
                    emit("input quarantine: no gated fetch yet")
                elif not quarantine["quarantined"]:
                    emit(
                        "input quarantine: clean "
                        f"({quarantine['admitted']}/{quarantine['total']} "
                        "admitted)"
                    )
                else:
                    emit(
                        "input quarantine: "
                        + ", ".join(
                            f"slot {q['slot']} ({q['reason']})"
                            for q in quarantine["quarantined"]
                        )
                    )
            elif cmd == "events":
                from svoc_tpu.utils.events import journal as _journal

                if len(args) > 1:
                    emit("Usage: events [N]")
                    return out
                n = int(args[0]) if args else 10
                records = _journal.recent(n)
                if not records:
                    emit("no events recorded yet")
                for rec in records:
                    data = " ".join(
                        f"{k}={v}" for k, v in sorted(rec.data.items())
                    )
                    emit(
                        f"#{rec.seq} {rec.type}"
                        + (f" [{rec.lineage}]" if rec.lineage else "")
                        + (f" {data}" if data else "")
                    )
            elif cmd == "audit":
                if len(args) > 1:
                    emit("Usage: audit [lineage]")
                    return out
                record = self.session.audit(args[0] if args else None)
                if not record.get("found"):
                    emit(
                        "no audit record"
                        + (
                            f" for {record['lineage']}"
                            if record.get("lineage")
                            else " — run 'fetch' first"
                        )
                    )
                    return out
                emit(f"audit {record['lineage']}:")
                s = record["summary"]
                quarantined = s.get("quarantined") or {}
                emit(
                    f"  quarantined: {len(quarantined)}"
                    + (
                        " ("
                        + ", ".join(
                            f"slot {slot}: {reason}"
                            for slot, reason in sorted(quarantined.items())
                        )
                        + ")"
                        if quarantined
                        else ""
                    )
                )
                emit(
                    f"  commit: sent={s.get('commit_sent', 0)}"
                    f" skipped={s.get('commit_skipped', 0)}"
                    f" retries={s.get('commit_retries', 0)}"
                    f" failures={len(s.get('commit_failures') or [])}"
                )
                if s.get("charged"):
                    emit("  charged: " + ", ".join(s["charged"]))
                for rep in s.get("replacements") or []:
                    emit(
                        f"  replaced slot {rep.get('slot')}: "
                        f"{rep.get('old')} -> {rep.get('new')}"
                    )
                breaker_line = (
                    " -> ".join(s["breaker_transitions"])
                    if s.get("breaker_transitions")
                    else "stayed " + self.session.breaker.state()
                )
                emit(f"  breaker: {breaker_line}")
                emit(
                    f"  events: {len(record['events'])}, "
                    f"spans: {len(record['spans'])}"
                )
            elif cmd == "claims":
                # Multi-claim fabric status (docs/FABRIC.md): one line
                # per claim — cycle count, last consensus validity, the
                # claim's own replacement/quarantine accounting, and
                # its latest block lineage.
                if self.fabric is None:
                    emit(
                        "no claim fabric attached — this console serves "
                        "a single-claim session"
                    )
                    return out
                snapshot = self.fabric.snapshot()
                emit(
                    f"fabric: {snapshot['n_claims']} claims, "
                    f"{snapshot['steps']} steps, "
                    f"impl={snapshot.get('consensus_impl', 'xla')}, "
                    f"mesh={snapshot.get('mesh') or 'none'}"
                    + (" pipelined" if snapshot.get("pipelined") else "")
                )
                for claim_id in sorted(snapshot["claims"]):
                    c = snapshot["claims"][claim_id]
                    consensus = c.get("consensus") or {}
                    valid = consensus.get("interval_valid")
                    emit(
                        f"  {claim_id}: cycles={c['cycles']}"
                        + (" PAUSED" if c.get("paused") else "")
                        + f" valid={'-' if valid is None else valid}"
                        + f" admitted={consensus.get('admitted', '-')}"
                        + f" replacements={c.get('replacements', 0)}"
                        + (
                            f" quarantined={c['quarantined']}"
                            if c.get("quarantined")
                            else ""
                        )
                        + (f" block={c['lineage']}" if c.get("lineage") else "")
                    )
            elif cmd == "serving":
                # Continuous-batching serving tier (docs/SERVING.md):
                # status, one manual submit, or one manual cycle.
                if self.serving is None:
                    emit(
                        "no serving tier attached — this console serves "
                        "batch/pull mode only"
                    )
                    return out
                if args and args[0] == "submit":
                    if len(args) < 3:
                        emit("usage: serving submit <claim> <text...>")
                        return out
                    try:
                        response = self.serving.submit(
                            args[1], " ".join(args[2:])
                        )
                    except KeyError:
                        emit(f"unknown claim '{args[1]}'")
                        return out
                    emit(
                        f"{response['status']}: {response['request_id']}"
                        + (
                            f" ({response['reason']})"
                            if response["status"] == "shed"
                            else ""
                        )
                        + f" lineage={response['lineage']}"
                    )
                elif args and args[0] == "step":
                    report = self.serving.step()
                    emit(
                        f"step {report['step']}: {report['requests']} "
                        f"requests over {report['claims']} claims, "
                        f"served {len(report['served'])}"
                    )
                elif args:
                    emit("usage: serving [submit <claim> <text...> | step]")
                else:
                    snap = self.serving.snapshot()
                    emit(
                        f"serving: {snap['steps']} steps, "
                        f"submitted={snap['submitted']:g} "
                        f"admitted={snap['admitted']:g} "
                        f"cached={snap['cached']:g} "
                        f"shed={snap['shed']:g} "
                        f"completed={snap['completed']:g}"
                    )
                    cache = snap["cache"]
                    emit(
                        f"  cache: {cache['size']}/{cache['capacity']} "
                        f"entries, hit rate {cache['hit_rate']:.1%} "
                        f"({cache['hits']:g} hits, "
                        f"{cache['evictions']:g} evictions)"
                    )
                    acfg = self.serving.frontend.controller.config
                    emit(
                        f"  burn rate: {snap['burn_rate']:.2f}x "
                        f"({acfg.burn_slo} {acfg.burn_window} window)"
                    )
                    latency = snap["latency"]
                    if latency.get("count"):
                        emit(
                            f"  latency: p50 {latency['p50'] * 1e3:.1f} ms, "
                            f"p99 {latency['p99'] * 1e3:.1f} ms "
                            f"over {latency['count']:g} requests"
                        )
                    queues = snap["queues"]
                    if any(queues.values()):
                        emit(
                            "  queues: "
                            + ", ".join(
                                f"{cid}={depth}"
                                for cid, depth in sorted(queues.items())
                                if depth
                            )
                        )
            elif cmd == "durability":
                # Crash-consistency status (docs/RESILIENCE.md
                # §durability): snapshot freshness + WAL health.
                if self.durability is None:
                    emit(
                        "no durability layer attached — this session's "
                        "state is in-memory only (chain writes are still "
                        "exact within the process lifetime)"
                    )
                    return out
                if args and args[0] == "snapshot":
                    path = self.durability.snapshot()
                    emit(f"snapshot written: {path}")
                    return out
                if args:
                    emit("usage: durability [snapshot]")
                    return out
                status = self.durability.status()
                emit(
                    f"snapshot: {status['snapshot_path']}"
                    + (
                        ""
                        if status["snapshot_exists"]
                        else " (none yet)"
                    )
                    + f", {status['snapshots_this_process']} this process"
                )
                emit(
                    f"wal: {status['wal_path'] or '(none)'}, "
                    f"{status['wal_records']} records, "
                    f"{len(status['wal_open_cycles'])} open cycles"
                )
                for lin in status["wal_open_cycles"]:
                    emit(f"  OPEN {lin} — a commit is in flight (or a "
                         "crash awaits reconciliation)")
            elif cmd == "cluster":
                # Multi-replica fleet status / operator migration
                # (docs/CLUSTER.md).
                if self.cluster is None:
                    emit(
                        "no cluster attached — this is a single-replica "
                        "deployment (wire a ClusterRouter and "
                        "attach(console) — docs/CLUSTER.md)"
                    )
                    return out
                sub = args[0] if args else "status"
                if sub == "migrate":
                    if len(args) != 3:
                        emit("usage: cluster migrate <claim> <replica>")
                        return out
                    report = self.cluster.migrate(
                        args[1], args[2], reason="operator"
                    )
                    emit(
                        f"migrated {args[1]} -> {args[2]} "
                        f"(epoch {report['epoch']}, cursor "
                        f"{report['cursor']}, continuity "
                        f"{'ok' if report['continuity'] else 'BROKEN'})"
                    )
                    return out
                if sub == "adopt-orphans":
                    report = self.cluster.adopt_orphans()
                    for cid, info in sorted(report["adopted"].items()):
                        emit(
                            f"adopted {cid} -> {info['replica']} "
                            f"(cursor {info['cursor']}, continuity "
                            f"{'ok' if info['continuity'] else 'BROKEN'})"
                        )
                    for cid, reason in sorted(report["remaining"].items()):
                        emit(f"  still orphaned {cid}: {reason}")
                    if not report["adopted"] and not report["remaining"]:
                        emit("no orphaned claims")
                    return out
                if sub != "status":
                    emit(
                        "usage: cluster [status | migrate <claim> "
                        "<replica> | adopt-orphans]"
                    )
                    return out
                snap = self.cluster.snapshot()
                emit(
                    f"cluster: epoch {snap['epoch']}, "
                    f"{len(snap['replicas'])} replica(s), "
                    f"{len(snap['claims'])} claim(s)"
                    + (
                        f", retired: {', '.join(snap['retired'])}"
                        if snap["retired"]
                        else ""
                    )
                )
                for rid, rep in sorted(snap["replicas"].items()):
                    requests = rep.get("requests", {})
                    owned = sorted(
                        cid
                        for cid, owner in snap["claims"].items()
                        if owner == rid
                    )
                    emit(
                        f"  {rid}: "
                        f"{'alive' if rep.get('alive') else 'DEAD'}, "
                        f"breaker {rep.get('breaker', '?')}, "
                        f"claims [{', '.join(owned)}], "
                        f"completed {requests.get('completed', 0):.0f}"
                    )
            elif cmd == "reconfig":
                # Live reconfiguration plane (docs/RECONFIG.md):
                # transactional drain → re-pin → recover-warm.
                if self.reconfig is None:
                    emit(
                        "no reconfiguration plane attached — wire a "
                        "ReconfigController and attach(console) "
                        "(docs/RECONFIG.md)"
                    )
                    return out
                sub = args[0] if args else "status"
                if sub == "apply":
                    if len(args) != 2:
                        emit("usage: reconfig apply <plan.json>")
                        return out
                    from svoc_tpu.cluster.reconfig import ReconfigPlan

                    with open(args[1]) as f:
                        plan = ReconfigPlan.from_dict(json.load(f))
                    report = self.reconfig.apply(plan)
                    if report["status"] == "committed":
                        emit(
                            f"committed epoch {report['epoch']} "
                            f"(plan {report['plan_fingerprint'][:16]}, "
                            f"{len(report['replicas'])} replica(s) "
                            f"re-pinned, {report['deferred_released']} "
                            "deferred request(s) released)"
                        )
                    elif report["status"] == "noop":
                        emit("plan is a no-op — nothing to change")
                    else:
                        emit(
                            f"ABORTED in {report['phase']} "
                            f"({report['cause']}) — fleet rolled back "
                            "to the pre-plan state"
                        )
                    return out
                if sub == "abort":
                    report = self.reconfig.request_abort()
                    emit(
                        f"{report['status']}"
                        + (
                            f" (phase {report['phase']})"
                            if "phase" in report
                            else f": {report.get('detail', '')}"
                        )
                    )
                    return out
                if sub != "status":
                    emit("usage: reconfig [status | apply <plan.json> | abort]")
                    return out
                status = self.reconfig.status()
                emit(
                    f"reconfig: phase {status['phase']}, "
                    f"epoch {status['epoch']}, "
                    f"holding {len(status['holding'])} replica(s), "
                    f"{status['deferred']} deferred request(s)"
                )
                for entry in status["chain"]:
                    emit(
                        f"  epoch {entry['epoch']}: plan "
                        f"{entry['plan'][:16]} over {entry['pre_fleet'][:16]}"
                    )
            elif cmd == "fleet":
                # Fleet observability plane (docs/OBSERVABILITY.md
                # §fleet-plane): merged telemetry + hop chains +
                # anomaly state.
                if self.fleetplane is None:
                    emit(
                        "no fleet plane attached — wire a FleetPlane "
                        "and attach(console) (docs/OBSERVABILITY.md "
                        "§fleet-plane)"
                    )
                    return out
                snap = self.fleetplane.snapshot()
                if not snap["enabled"]:
                    emit(
                        "fleet plane DISABLED (SVOC_FLEET_PLANE / "
                        "PERF_DECISIONS.json fleet_plane — resolved at "
                        "construction, SVOC011)"
                    )
                    return out
                emit(
                    f"fleet plane: step {snap['step']}, "
                    f"{len(snap['sources'])} source(s) "
                    f"[{', '.join(snap['sources'])}], "
                    f"{snap['chains']} hop chain(s)"
                    + (
                        f", retired: {', '.join(snap['retired'])}"
                        if snap["retired"]
                        else ""
                    )
                )
                for sid, acct in sorted(snap["observations"].items()):
                    emit(
                        f"  {sid}: {acct['records']} obs record(s), "
                        f"last seq {acct['last_seq']}, "
                        f"dropped {acct['dropped']}"
                    )
                alerting = snap["slo"]["alerting"]
                emit(
                    "fleet SLOs: "
                    + (
                        "ALERTING " + ", ".join(alerting)
                        if alerting
                        else "quiet"
                    )
                )
                anomaly = snap.get("anomaly") or {}
                emit(
                    f"anomaly: {anomaly.get('series', 0)} series, "
                    f"{anomaly.get('alerts_total', 0)} alert(s)"
                )
                for a in snap["recent_anomalies"]:
                    emit(
                        f"  step {a['step']} {a['source']}/{a['family']}: "
                        f"delta {a['delta']:g} ({a['trigger']}, "
                        f"z={a['z']:.1f}, streak {a['streak']}"
                        + (", SUSTAINED)" if a["sustained"] else ")")
                    )
                for path in snap["bundles"]:
                    emit(f"  bundle: {path}")
            elif cmd == "costs":
                # Shape-keyed dispatch-cost ledger
                # (docs/OBSERVABILITY.md §cost-attribution).
                plane = getattr(self.serving, "cost_plane", None) \
                    if self.serving is not None else None
                if plane is None:
                    emit(
                        "no cost plane attached — wire a ServingTier "
                        "(docs/OBSERVABILITY.md §cost-attribution)"
                    )
                    return out
                snap = plane.snapshot()
                ledger = snap["ledger"]
                emit(
                    f"cost plane: "
                    f"{'enabled' if snap['enabled'] else 'DISABLED'} — "
                    f"{ledger['keys']} keys, {ledger['samples']} samples "
                    f"(alpha={ledger['alpha']}), "
                    f"{snap['observations']} observation records"
                )
                for key_str, entry in sorted(snap["entries"].items()):
                    cells = entry["warmth"]
                    rendered = "  ".join(
                        f"{w}: {cells[w]['ema_s'] * 1e3:.2f} ms "
                        f"({cells[w]['samples']}x)"
                        for w in ("cold", "prewarmed", "warm")
                        if w in cells
                    )
                    emit(f"  {key_str} [{entry['group']}]  {rendered}")
            elif cmd == "profile":
                # On-demand jax.profiler capture (bounded duration,
                # docs/OBSERVABILITY.md §cost-attribution).
                if self.profiler is None:
                    emit(
                        "no profiler attached — construct a "
                        "ProfileCapture and attach(console) "
                        "(docs/OBSERVABILITY.md §cost-attribution)"
                    )
                    return out
                sub = args[0] if args else "status"
                if sub == "start":
                    duration = float(args[1]) if len(args) > 1 else None
                    result = self.profiler.start(duration_s=duration)
                elif sub == "stop":
                    result = self.profiler.stop()
                elif sub == "status":
                    result = self.profiler.status()
                else:
                    emit("usage: profile [start [seconds]|stop|status]")
                    return out
                for k, v in sorted(result.items()):
                    emit(f"{k}: {v}")
            elif cmd == "drain":
                # The SIGTERM path, manually (docs/RESILIENCE.md
                # §drain): stop admission, flush, snapshot, bundle.
                if self.drainer is None:
                    emit(
                        "no drain handler attached — wire a "
                        "GracefulDrain (svoc_tpu.durability) first"
                    )
                    return out
                report = self.drainer.drain(reason="console")
                if report.get("already_drained"):
                    emit("already drained")
                    return out
                flush = report.get("flush") or {}
                emit(
                    f"drained: {flush.get('flush_steps', 0)} flush steps, "
                    f"{flush.get('deferred', 0)} requests deferred"
                )
                if report.get("snapshot"):
                    emit(f"snapshot: {report['snapshot']}")
                if report.get("bundle"):
                    emit(f"bundle: {report['bundle']}")
            elif cmd == "slo":

                def emit_burns(snapshot, detail: bool = False) -> None:
                    for name in sorted(snapshot):
                        s = snapshot[name]
                        emit(
                            f"{name} (objective {s['objective']:.0%}): "
                            f"fast burn {s['fast']['burn']:.2f}x, "
                            f"slow burn {s['slow']['burn']:.2f}x"
                            + ("  ALERTING" if s["alerting"] else "")
                        )
                        if detail:
                            emit(
                                f"  {s['description']}: "
                                f"{s['good']:g}/{s['total']:g} good"
                            )

                emit_burns(self.session.slo_snapshot(), detail=True)
                # Per-claim burn rates (docs/FABRIC.md §slo): each
                # claim's evaluator covers ITS commit/admission
                # counters, so one burning market reads as that market,
                # not as fleet-average dilution.
                if self.fabric is not None:
                    for state in self.fabric.registry.states():
                        emit_burns(state.evaluator.evaluate())
                # Serving-tier objectives (docs/SERVING.md): the
                # request_latency burn here is the SAME gauge admission
                # reads — the operator sees exactly what the controller
                # sees.
                if self.serving is not None:
                    emit_burns(self.serving.slo_snapshot())
            elif cmd == "multimodal":
                # Beyond-reference: mixture-model analysis of the LAST
                # fetched fleet (the scenario documentation/README.md:
                # 90-103 describes but provides no algorithm for) —
                # docs/ALGORITHM.md §8, svoc_tpu/sim/multimodal.py.
                if len(args) > 1:
                    emit("Unexpected number of arguments.")
                    return out
                with self.session.lock:
                    predictions = self.session.predictions
                if predictions is None:
                    emit("No predictions yet — run 'fetch' first.")
                    return out
                import jax.numpy as jnp
                import numpy as np

                from svoc_tpu.sim.multimodal import (
                    multimodal_consensus,
                    select_k,
                )

                # K capped by the fleet size: a duplicated farthest-point
                # center would split a true pole's weight across clones.
                k_max = min(8, predictions.shape[0])
                if args and args[0] == "auto":
                    k_poles, bics = select_k(
                        jnp.asarray(predictions, jnp.float32), k_max=k_max
                    )
                    emit(
                        f"BIC selects K={k_poles} "
                        f"(scores: "
                        + ", ".join(f"{b:0.1f}" for b in bics)
                        + ")"
                    )
                else:
                    k_poles = int(args[0]) if args else 2
                    if not 1 <= k_poles <= k_max:
                        emit(f"K must be in [1, {k_max}].")
                        return out

                n_failing = min(
                    self.session.config.n_failing,
                    predictions.shape[0] - 1,
                )
                res = multimodal_consensus(
                    jnp.asarray(predictions, jnp.float32),
                    k_poles,
                    n_failing,
                )
                order = np.argsort(-np.asarray(res.pole_weights))
                emit(f"mixture fit over {predictions.shape[0]} oracles, "
                     f"K={k_poles} pole(s):")
                for rank, k in enumerate(order):
                    mean = ", ".join(
                        f"{x:0.3f}" for x in np.asarray(res.pole_means[k])
                    )
                    emit(
                        f"  pole {rank} [w={float(res.pole_weights[k]):0.3f}"
                        f" sigma={float(res.pole_sigmas[k]):0.4f}] : {mean}"
                    )
                emit(
                    "essence (dominant pole) : "
                    + ", ".join(f"{x:0.3f}" for x in np.asarray(res.essence))
                )
                flagged = [
                    str(i) for i, r in enumerate(np.asarray(res.reliable))
                    if not r
                ]
                emit("flagged unreliable : " + (", ".join(flagged) or "none"))
            elif cmd == "live_mode":
                # The reference stubs this (web_interface.py:228;
                # oracle_scheduler.py:174-182 TODO).  Here it is the
                # full live pipeline: ingest + classify + commit.
                if len(args) != 1:
                    emit("Unexpected number of arguments.")
                    return out
                if on_off_to_bool(args[0]):
                    source_name = self._start_scraper() or "unchanged"
                    self.session.set_auto_flags(fetch=True, commit=True)
                    self._start_auto_fetch()
                    emit(f"Live mode: ENABLED (scraper={source_name}, "
                         "auto_fetch+auto_commit on)")
                else:
                    self.session.set_auto_flags(fetch=False, commit=False)
                    self._stop_scraper()
                    emit("Live mode: DISABLED")
            else:
                emit(f"Unknown command: {cmd} (try 'help')")
        except Exception as e:  # the dispatcher never crashes the REPL
            emit(f"error: {type(e).__name__}: {e}")
        return out

    # -- background loops ---------------------------------------------------

    def _start_auto_fetch(self) -> None:
        """simulation_mode (oracle_scheduler.py:163-171): fetch every
        ``refresh_rate_s`` while the flag holds.

        Each start bumps a generation token; a superseded loop exits at
        its next check even if off→on toggles race its wind-down, so
        exactly one loop serves the current enable.  The bump+start is
        atomic under ``_bg_lock`` — racing starts would otherwise both
        read the same token and neither loop would ever yield."""
        with self._bg_lock:
            gen = self._auto_fetch_gen = getattr(self, "_auto_fetch_gen", 0) + 1

        def loop():
            import time

            from svoc_tpu.apps.session import EmptyStoreError
            from svoc_tpu.resilience.breaker import CircuitOpenError

            while (
                gen == self._auto_fetch_gen
                and self.session.auto_fetch
                and self.session.application_on
            ):
                try:
                    # No outer lock hold: fetch/commit/bump_state lock
                    # internally and the adapter serializes per
                    # operation — a slow or hung chain RPC in this loop
                    # must never freeze the console / web UI behind the
                    # session lock.
                    self.session.fetch()
                    if self.session.auto_commit:
                        breaker_open = False
                        try:
                            # Resilient path: backoff + resume of
                            # partial fleets + breaker — a flaky chain
                            # degrades this loop, it never kills it.
                            self.session.commit_resilient()
                            if self.session.auto_resume:
                                self.session.adapter.resume()
                                self.session.bump_state()
                        except CircuitOpenError:
                            # Chain declared down: skip this cycle
                            # cheaply; the breaker half-opens after its
                            # reset window and the next cycle probes.
                            breaker_open = True
                            from svoc_tpu.utils.metrics import registry as _m

                            _m.counter("auto_fetch_breaker_skips").add(1)
                        finally:
                            # Health fold runs on every commit cycle,
                            # success or tx-level failure — quarantine
                            # decisions need BOTH kinds of evidence.
                            # Never raises (Session.supervisor_step).
                            # EXCEPT on a breaker-open skip: the step's
                            # own chain reads would hang against the
                            # very backend the breaker just declared
                            # dead, re-wedging the loop the skip freed.
                            if not breaker_open:
                                self.session.supervisor_step()
                            # Burn-rate fold (docs/OBSERVABILITY.md
                            # §slo): registry-only, no chain I/O, so it
                            # runs even on breaker-open cycles — an
                            # outage is exactly when burn rates matter.
                            self.session.slo_step()
                except EmptyStoreError:
                    # Not an error in a composite loop: live mode starts
                    # the scraper and this loop together, so early
                    # cycles legitimately find an empty store — wait for
                    # ingest instead of error-spamming.
                    from svoc_tpu.utils.metrics import registry as _m

                    _m.counter("auto_fetch_waiting").add(1)
                except Exception as e:
                    # Surface the failure (once per distinct message) and
                    # count it, instead of silently spinning.
                    msg = f"auto_fetch error: {type(e).__name__}: {e}"
                    if msg != getattr(self, "_last_auto_fetch_error", None):
                        self._last_auto_fetch_error = msg
                        if self._write:
                            self._write(msg)
                    from svoc_tpu.utils.metrics import registry as _m

                    _m.counter("auto_fetch_errors").add(1)
                time.sleep(self.session.config.refresh_rate_s)

        self._auto_fetch_thread = threading.Thread(target=loop, daemon=True)
        self._auto_fetch_thread.start()

    def _start_scraper(self) -> Optional[str]:
        """Start the ingest loop; returns the source actually used
        ("hn-live" when Selenium is available and requested, else the
        offline synthetic generator), or ``None`` when nothing was
        started (claim superseded by a newer command, or stopped before
        the commit phase).

        Claim → build → commit: the slot is claimed by a fresh stop
        event under ``_bg_lock`` (racing 'scraper on' commands would
        otherwise both pass the is-alive check and orphan one loop's
        stop event), but the SOURCE BUILD runs unlocked — a Selenium
        browser launch takes seconds (or hangs), and 'scraper off' /
        'exit' must never block behind it.  The commit phase starts the
        thread only if this claim is still the current one."""
        with self._bg_lock:
            winding_down = None
            if self._scraper_thread and self._scraper_thread.is_alive():
                if self._scraper_stop is not None and self._scraper_stop.is_set():
                    winding_down = self._scraper_thread
                else:
                    return "already running"
            stop = self._scraper_stop = threading.Event()
        if winding_down is not None:
            # A just-stopped loop is winding down — wait it out (outside
            # the lock) so the restart actually starts a fresh loop.
            winding_down.join(timeout=5)
            if winding_down.is_alive():
                # Still wedged (e.g. a hung Selenium page fetch): do NOT
                # start a second loop writing to the same store — report
                # "not started"; the user can retry once it dies
                # (ADVICE r3).  Mark our claim stopped so the retry
                # takes the winding-down path instead of "already
                # running".
                with self._bg_lock:
                    if self._scraper_stop is stop:
                        stop.set()
                return None

        from svoc_tpu.io.scraper import (
            SeleniumHNSource,
            SyntheticSource,
            run_scraper,
        )

        source, source_name = None, "synthetic"
        if self.session.config.live_scraper:
            try:
                source, source_name = SeleniumHNSource(), "hn-live"
            except RuntimeError:
                source_name = "synthetic (selenium unavailable)"
        if source is None:
            source = SyntheticSource()

        def discard() -> None:
            # Release the source (a Selenium source holds a live
            # headless Firefox that GC never quits) — on a lost claim
            # AND when the loop exits (the reference gets this for free
            # by running the scraper as a killable subprocess,
            # ``main.py:38``; a thread must quit the browser itself).
            close = getattr(source, "close", None)
            if close:
                close()

        def loop():
            try:
                run_scraper(
                    self.session.store,
                    source,
                    rate_s=self.session.config.scraper_rate_s,
                    stop_event=stop,
                    sleep=lambda s: stop.wait(s),
                )
            finally:
                discard()

        with self._bg_lock:
            if self._scraper_stop is not stop:
                discard()
                return None  # superseded by a newer scraper command
            if stop.is_set():
                # 'scraper off' landed between claim and commit — honor
                # it rather than starting a loop that exits immediately.
                discard()
                self._scraper_stop = None
                return None
            self._scraper_thread = threading.Thread(target=loop, daemon=True)
            self._scraper_thread.start()
        return source_name

    def _stop_scraper(self) -> None:
        with self._bg_lock:
            if self._scraper_stop is not None:
                self._scraper_stop.set()

    def stop(self) -> None:
        self.session.set_auto_flags(fetch=False)
        self._stop_scraper()
