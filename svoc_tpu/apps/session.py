"""Session state + the fetch/commit simulation engine.

The reference keeps everything in the mutable ``globalState`` singleton
(``client/common.py:36-77``) and spreads the fetch path over
``oracle_scheduler.py`` (``simulation_fetch`` → ``sentiment_analysis`` →
``gen_oracles_predictions`` → ``predictions_to_eel_values``).  Here the
session is an explicit object owning:

- the comment store + circular cursor (``globalState.simulation_step``),
- the sentiment vectorizer (the jitted pipeline; injectable so tests
  and the pure-synthetic mode skip transformer weights),
- the jitted bootstrap-fleet generator,
- the chain adapter (local simulator or Sepolia),
- the last fleet predictions (``globalState.predictions``).

Defaults mirror ``client/common.py:7-31``: 7 oracles, 2 failing, window
50/limit 30, bootstrap subset 10, 6 go_emotions labels, 5 s refresh.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from svoc_tpu.consensus.state import OracleConsensusContract
from svoc_tpu.io.chain import ChainAdapter, ChainCommitError, LocalChainBackend
from svoc_tpu.io.comment_store import (
    PREDICTION_WINDOW,
    SQL_FETCH_LIMIT,
    CommentStore,
)
from svoc_tpu.ops.stats import rank_array
from svoc_tpu.resilience.breaker import CircuitBreaker, CircuitOpenError
from svoc_tpu.resilience.retry import (
    CommitOutcome,
    RetryPolicy,
    commit_fleet_with_resume,
)
from svoc_tpu.resilience.supervisor import (
    FleetHealthSupervisor,
    SupervisorConfig,
)
from svoc_tpu.robustness.sanitize import (
    QuarantinedInputError,
    QuarantineGate,
    QuarantineReport,
    SanitizeConfig,
)
from svoc_tpu.sim.oracle import gen_oracle_predictions
from svoc_tpu.utils.events import audit_record, lineage_scope, mint_lineage
from svoc_tpu.utils.events import journal as event_journal
from svoc_tpu.utils.metrics import registry as metrics
from svoc_tpu.utils.metrics import stage_span, tracer


class EmptyStoreError(RuntimeError):
    """Fetch found no comments.  Interactive ``fetch`` surfaces it as an
    error (the reference's fetch on an empty DB also fails); the
    auto-fetch loop treats it as *waiting for ingest* — in live mode the
    scraper and the fetch loop start together, so the first fetch can
    legitimately race the first scrape."""


class DegenerateBlockError(RuntimeError):
    """Committing this block would panic the on-chain consensus: the
    contract's golden recompute divides by a zero standard deviation
    when every reliable prediction agrees exactly in some dimension
    (``math.cairo:320-338`` skewness over a zero-variance sample — an
    i128 division by zero, which reverts the transaction).

    A request-fed cold start produces exactly this shape (one comment →
    every honest bootstrap averages the same vector), so both commit
    paths dry-run the faithful engine on **request-fed blocks** and
    refuse pre-tx — the serving tier defers the chain write
    (``commit.deferred``) instead of stranding the last signer and
    churning the supervisor's replacement clock over deterministic
    math.  Store-driven blocks keep their exact historical commit
    semantics (partial fleets land, per-oracle failures charge the
    supervisor), which tier-1 pins."""


@dataclasses.dataclass
class SessionConfig:
    """``client/common.py:7-31`` constants, as explicit configuration."""

    n_oracles: int = 7
    n_failing: int = 2
    dimension: int = 6
    bootstrap_subset: int = 10
    window: int = PREDICTION_WINDOW
    fetch_limit: int = SQL_FETCH_LIMIT
    #: auto-fetch period (SIMULATION_REFRESH_RATE, common.py:11).
    refresh_rate_s: float = 5.0
    #: scraper period (scraper.py:21 default 600 s) — a separate cadence.
    scraper_rate_s: float = 600.0
    #: use the live Selenium HN source when available (else synthetic).
    live_scraper: bool = False
    constrained: bool = True
    max_spread: float = 0.0
    required_majority: int = 2
    n_admins: int = 3
    seed: int = 0
    #: Sequence-packed inference for the default vectorizer — several
    #: comments per fixed device row (:mod:`svoc_tpu.models.packing`),
    #: ~3× fewer forward rows on HN-shaped comments with identical
    #: results to float tolerance.  The TPU-first default.
    packed_inference: bool = True
    #: ``"int8"`` serves the default vectorizer through the W8A8
    #: dynamic-PTQ forward (:mod:`svoc_tpu.models.quant` — 2× the bf16
    #: MXU rate on v5e, results within quantization tolerance).  None
    #: keeps the float forward (default: classification drives on-chain
    #: consensus values, so precision is opt-out).
    quant_inference: Optional[str] = None
    #: Deployment info (``data/contract_info.json`` fields).
    declared_address: Optional[str] = None
    deployed_address: Optional[str] = None
    #: Resilience layer (docs/RESILIENCE.md).  The retry policy drives
    #: ``commit_resilient`` (the auto loop's commit: decorrelated-jitter
    #: backoff + resume of partial fleets); both dataclasses are frozen,
    #: so they are safe as field defaults.
    commit_retry: RetryPolicy = RetryPolicy()
    supervisor: SupervisorConfig = SupervisorConfig()
    #: Fleet health supervision in the auto loop (False = observe-only
    #: sessions: scores still accrue, no automatic replacement votes).
    supervise_fleet: bool = True
    #: Chain circuit breaker: consecutive-failure trip threshold and
    #: the open→half-open reset window.
    breaker_failure_threshold: int = 5
    breaker_reset_s: float = 30.0
    #: Input-integrity quarantine gate ahead of the commit path
    #: (docs/ROBUSTNESS.md): NaN/Inf, value-domain and wsad/felt-codec
    #: checks on every fetched fleet block.  The faithful ``commit``
    #: refuses a dirty block outright; ``commit_resilient`` skips the
    #: quarantined slots and charges them to the oracle's health.
    quarantine_gate: bool = True
    #: Multi-claim fabric (docs/FABRIC.md): the claim (market/story)
    #: this session serves.  When set, lineage ids are minted as
    #: ``blk<scope>-<claim>-<n>`` so one process journal partitions
    #: per claim, the supervisor/breaker series carry a claim label,
    #: and :class:`svoc_tpu.fabric.MultiSession` can own many such
    #: sessions side by side.  None = the single-claim sessions of
    #: PRs 1–5, byte-for-byte unchanged.
    claim: Optional[str] = None
    #: Lineage scope override (seeded fabric scenarios): replay
    #: identity needs two runs to mint IDENTICAL lineage ids, which the
    #: process-unique default ordinal deliberately prevents — only pin
    #: this together with a FRESH ``journal=`` (else two sessions'
    #: audit records merge, the exact bug the scope exists to stop).
    lineage_scope: Optional[str] = None
    #: Commit-plane mode (docs/RESILIENCE.md §batched-commits):
    #: ``"per_tx"`` keeps the reference's one-signed-tx-per-oracle
    #: loop; ``"batched"`` sends a clean fleet as ONE chain RPC (and,
    #: with a WAL attached, one fsynced intent per cycle instead of one
    #: per tx) with counted per-tx fallbacks — identical chain state
    #: and journal events either way.  None resolves env >
    #: PERF_DECISIONS.json > per_tx ONCE at construction
    #: (:func:`svoc_tpu.consensus.dispatch.resolve_commit_mode`) — the
    #: WAL record family a seeded crash replay produces depends on the
    #: mode, so it must not drift mid-run (the PR 9/11 pinning rule).
    commit_mode: Optional[str] = None


def _default_contract(cfg: SessionConfig) -> OracleConsensusContract:
    """A local contract with synthetic felt-style addresses (admins
    0xA0…, oracles 0x10…, the test fixtures' role layout)."""
    return OracleConsensusContract(
        admins=[0xA0 + i for i in range(cfg.n_admins)],
        oracles=[0x10 + i for i in range(cfg.n_oracles)],
        required_majority=cfg.required_majority,
        n_failing_oracles=cfg.n_failing,
        constrained=cfg.constrained,
        unconstrained_max_spread=cfg.max_spread,
        dimension=cfg.dimension,
    )


@partial(jax.jit, static_argnames=("n_oracles", "n_failing", "subset"))
def _fleet(key, window, n_oracles, n_failing, subset):
    return gen_oracle_predictions(key, window, n_oracles, n_failing, subset)


@jax.jit
def _preview_stats(values):
    """``predictions_to_eel_values`` math (``oracle_scheduler.py:106-134``):
    fleet mean, fleet median, and per-oracle normalized rank of deviation
    from the MEDIAN (``oracle_scheduler.py:109`` — the median, unlike the
    mean, is not dragged toward adversarial outliers; rank 0 = most
    deviant — suspected failing)."""
    mean = jnp.mean(values, axis=0)
    median = jnp.median(values, axis=0)
    dev = jnp.linalg.norm(values - median[None, :], axis=-1)
    normalized, _ranks = rank_array(dev)
    return mean, median, normalized


class Session:
    """One client session (the ``globalState`` replacement)."""

    def __init__(
        self,
        config: Optional[SessionConfig] = None,
        store: Optional[CommentStore] = None,
        vectorizer: Optional[Callable[[Sequence[str]], np.ndarray]] = None,
        adapter: Optional[ChainAdapter] = None,
        journal=None,
    ):
        self.config = config or SessionConfig()
        self.store = store or CommentStore()
        self._vectorizer = vectorizer
        self.adapter = adapter or ChainAdapter(
            LocalChainBackend(_default_contract(self.config))
        )
        #: Event journal this session emits into — the process default
        #: unless injected (the multi-claim fabric's seeded smoke runs
        #: two whole MultiSessions and asserts byte-identical per-claim
        #: fingerprints, which needs fresh journals whose seqs restart
        #: at 1; docs/FABRIC.md).
        self.journal = journal if journal is not None else event_journal
        #: Per-backend circuit breaker: the auto loop's commits consult
        #: it, so a dead chain degrades to cheap short-circuits instead
        #: of a retry storm (state lives in /metrics as
        #: ``circuit_breaker_state{backend="chain"}``; claim sessions
        #: get their own series — ``backend="chain[<claim>]"`` — so one
        #: claim's dead chain never masks its siblings' health).
        breaker_name = (
            f"chain[{self.config.claim}]" if self.config.claim else "chain"
        )
        self.breaker = CircuitBreaker(
            breaker_name,
            failure_threshold=self.config.breaker_failure_threshold,
            reset_timeout_s=self.config.breaker_reset_s,
            registry=metrics,
            journal=self.journal,
        )
        #: Fleet health supervisor: commit-failure history + on-chain
        #: reliability → hysteresis scores → automatic replacement votes
        #: (the paper's admin mechanism, driven instead of manual).
        self.supervisor = FleetHealthSupervisor(
            self.adapter,
            self.config.supervisor,
            registry=metrics,
            journal=self.journal,
            claim=self.config.claim,
        )
        #: Input-integrity gate (docs/ROBUSTNESS.md): bounds derived
        #: from the consensus model — the contract's [0,1] interval for
        #: constrained sessions, codec-window-only for unconstrained.
        self.gate = QuarantineGate(
            SanitizeConfig.for_consensus(self.config.constrained),
            registry=metrics,
            journal=self.journal,
        )
        #: Commit-intent WAL (docs/RESILIENCE.md §durability): when
        #: attached (:meth:`attach_wal`), every ``commit_resilient``
        #: cycle journals fsynced per-tx intent/landed records so a
        #: SIGKILL at any instruction leaves enough evidence to resume
        #: EXACTLY the stranded suffix on restart — zero duplicate txs.
        #: None = the in-memory-only sessions of PRs 1–7, unchanged.
        self.wal = None
        #: The resolved commit-plane mode, pinned at construction (the
        #: replay rule — see ``SessionConfig.commit_mode``).  Resolving
        #: here keeps the env/record read OFF the commit hot path
        #: (svoclint SVOC011 discipline).
        from svoc_tpu.consensus.dispatch import (
            resolve_commit_mode,
            validate_commit_mode,
        )

        self.commit_mode = (
            validate_commit_mode(self.config.commit_mode, "SessionConfig")
            if self.config.commit_mode is not None
            else resolve_commit_mode()
        )
        #: Last gate verdict over the fetched fleet (written with the
        #: predictions it describes, under the session lock).
        self.last_quarantine: Optional[QuarantineReport] = None
        #: Lineage id of the last PUBLISHED fleet block (minted per
        #: fetch claim, ``svoc_tpu.utils.events.mint_lineage``) — the
        #: key every event/span of that block carries, and what the
        #: console's ``audit`` command defaults to.  Prefixed with a
        #: process-unique session scope: several sessions share one
        #: process journal, and without the scope each would mint
        #: ``blk-000001`` for its first fetch and their audit records
        #: would merge.
        self.last_lineage: Optional[str] = None
        scope = (
            self.config.lineage_scope
            if self.config.lineage_scope is not None
            else lineage_scope()
        )
        #: ``blk<scope>`` for single-claim sessions, ``blk<scope>-<claim>``
        #: under the fabric — every lineage id this session mints starts
        #: with it, so one journal partitions cleanly per claim.
        self.lineage_prefix = (
            f"blk{scope}-{self.config.claim}"
            if self.config.claim
            else f"blk{scope}"
        )
        self.predictions: Optional[np.ndarray] = None
        self.last_preview: Optional[Dict] = None
        #: Rolling request-context window (request-driven serving,
        #: docs/SERVING.md): consensus in pull mode runs over a
        #: ``config.window``-comment store window, so the serving tier
        #: must not degrade it to "this step's requests only" — a
        #: 1-request block would make every honest bootstrap identical
        #: and the faithful commit would panic on-chain (zero-variance
        #: skewness, ``math.cairo:320-338``).  ``fetch(window=...)``
        #: appends each feed here and serves consensus over the last
        #: ``config.window`` vectors: the claim's recent sentiment plus
        #: the new requests, exactly the pull-mode window semantics.
        self._request_window: Optional[np.ndarray] = None
        #: Source of the published block: ``"store"`` (pull-mode scrape
        #: window) or ``"serving"`` (request-fed, ``fetch(window=...)``).
        #: The commit paths read it to scope the degeneracy dry-run to
        #: request-fed blocks only — store-driven commits keep their
        #: exact historical semantics (partial fleets, per-oracle
        #: failures), which tier-1 pins.
        self._block_source: str = "store"
        #: Lazy SLO evaluator (``svoc_tpu.utils.slo``) over the shared
        #: registry — built on first use so sessions that never ask for
        #: burn rates pay nothing.
        self._slo = None
        #: Bumped on every state change the UI renders (fetch, commit,
        #: resume) — the web UI's poll loop redraws only when this
        #: changes, so auto_fetch/auto_commit/auto_resume surface live
        #: (the eel UI repaints on every push, simulation_graphics.js:85).
        self.state_version: int = 0
        self.simulation_step: int = 0
        self.auto_fetch: bool = False
        #: fetch ⇒ commit (help text web_interface.py:22; unimplemented
        #: in the reference).  Functional here through
        #: :meth:`commit_resilient` — backoff + resume + breaker, so a
        #: flaky chain degrades the loop instead of killing it.
        self.auto_commit: bool = False
        #: commit ⇒ resume (help text web_interface.py:23; also
        #: unimplemented in the reference).  Toggle via
        #: :meth:`set_auto_flags` so the web UI sees the change live.
        self.auto_resume: bool = False
        self.application_on: bool = True
        #: Lazy: creating a PRNG key initializes the jax backend, which
        #: can block indefinitely when the TPU plugin's chip is
        #: unreachable — a session must come up (console, chain reads,
        #: web UI) without touching the device; only fetch pays it.
        self._key_value = None
        #: Concurrency model (the reference is single-threaded — one eel
        #: event loop over ``globalState``; here the auto_fetch loop,
        #: the stdin console, and the web UI's ThreadingHTTPServer
        #: handlers share one session), layered so no lock is ever held
        #: across unbounded chain I/O or model building:
        #:
        #: - ``lock`` (reentrant) — session field mutation: fetch's
        #:   cursor/PRNG-split/preview, state_version bumps, commit's
        #:   predictions snapshot.  Held only around in-memory /
        #:   on-device work, with ONE deliberate exception: fetch's
        #:   ``store.read_window`` (SQLite) runs under it so the cursor
        #:   advance is atomic with the read that consumed it — bounded
        #:   by ``fetch_limit`` rows against a local file, not chain
        #:   I/O (ADVICE r3).
        #: - ``_commit_lock`` — whole-fleet commit atomicity: two
        #:   concurrent commits must not interleave per-oracle txs (a
        #:   mixed fleet no fetch produced would reach consensus).
        #: - the adapter's own lock — per-operation atomicity of chain
        #:   reads/txs against the contract simulator and read cache
        #:   (tx-granular interleaving beyond that matches the real
        #:   chain).
        #: - ``_vectorizer_lock`` — single construction of the lazy
        #:   sentiment pipeline (tens of seconds of transformer init;
        #:   double-checked so only first callers pay it).
        self.lock = threading.RLock()
        self._commit_lock = threading.Lock()
        self._vectorizer_lock = threading.Lock()
        #: Fetch publish ordering: each fetch claims a monotonically
        #: increasing token with its window cursor; a slower fetch of an
        #: EARLIER window must not overwrite predictions/preview from a
        #: later one (the UI and auto_commit would regress to stale
        #: data).
        self._fetch_claim = 0
        self._fetch_published = 0

    # -- sentiment stage ----------------------------------------------------

    @property
    def vectorizer(self) -> Callable[[Sequence[str]], np.ndarray]:
        """texts → ``[B, dimension]`` vectors; the jitted RoBERTa pipeline
        by default (``gen_classifier`` equivalent), built lazily so
        sessions that never fetch don't pay transformer init.  The label
        subset is sized to ``config.dimension`` (the 6 tracked
        go_emotions labels when it is 6, the first ``dimension`` labels
        of the 28-label head otherwise) so fetch output always matches
        the contract's dimension.

        Double-checked locking on its own lock (NOT the session lock):
        racing first fetches must not both pay the build, and callers
        of other session state must not wait behind it."""
        if self._vectorizer is not None:
            return self._vectorizer
        with self._vectorizer_lock:
            if self._vectorizer is not None:  # lost the build race
                return self._vectorizer
            from svoc_tpu.models.sentiment import (
                GO_EMOTIONS_LABELS,
                TRACKED_INDICES,
                SentimentPipeline,
            )

            dim = self.config.dimension
            if dim == len(TRACKED_INDICES):
                indices = TRACKED_INDICES
            elif dim <= len(GO_EMOTIONS_LABELS):
                indices = tuple(range(dim))
            else:
                raise ValueError(
                    f"dimension {dim} exceeds the {len(GO_EMOTIONS_LABELS)}"
                    "-label head — pass an explicit vectorizer"
                )
            # Shard the vectorizer batch over all local devices when
            # there are several — the app layer rides the same
            # data-parallel path as svoc_tpu.parallel.serving.
            data_mesh = None
            n_dev = jax.device_count()
            default_batch = 32
            if n_dev > 1 and default_batch % n_dev == 0:
                from svoc_tpu.parallel.serving import serving_mesh

                data_mesh = serving_mesh()
            self._vectorizer = SentimentPipeline(
                label_indices=indices,
                batch_size=default_batch,
                data_mesh=data_mesh,
                packed=self.config.packed_inference,
                quant=self.config.quant_inference,
            )
        return self._vectorizer

    @property
    def label_names(self) -> List[str]:
        """Column names for the UI plots (``predictions_to_eel_values``
        uses ``LABELS_KEYS``, ``oracle_scheduler.py:113-118``): the 6
        tracked go_emotions labels at the reference dimension, the first
        ``dimension`` head labels otherwise."""
        from svoc_tpu.models.sentiment import GO_EMOTIONS_LABELS, TRACKED_LABELS

        dim = self.config.dimension
        if dim == len(TRACKED_LABELS):
            return list(TRACKED_LABELS)
        if dim <= len(GO_EMOTIONS_LABELS):
            return list(GO_EMOTIONS_LABELS[:dim])
        return [f"label_{i}" for i in range(dim)]

    # -- the fetch path (simulation_fetch, oracle_scheduler.py:155-161) -----

    def fetch(
        self,
        tamper: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        window: Optional[np.ndarray] = None,
    ) -> Dict:
        """One simulation step: window → sentiment → fleet → preview.

        Returns the preview dict (fleet values, mean/median, normalized
        deviation ranks, honest ground truth) and caches ``predictions``
        for ``commit``.

        ``tamper`` (scenario hook, docs/FABRIC.md): applied to the
        fleet block ``[N, M]`` BEFORE the quarantine gate and the
        preview stats — the data-plane twin of the fault injector's
        chaos wrapper, letting a seeded Byzantine oracle live inside
        one claim of a multi-claim run.  The gate's counted verdict
        therefore describes the tampered block it will actually refuse
        to commit (one verdict per block, as always).

        ``window`` (request-driven feed, docs/SERVING.md): precomputed
        ``[K, dimension]`` sentiment vectors — the serving batcher
        already tokenized and forwarded the requests in one cross-claim
        packed batch, so this path skips the store read and the
        vectorize stage entirely and feeds the vectors straight into
        the fleet bootstrap.  Lineage, the quarantine verdict, the
        journal events, and the publish ordering are identical to the
        store-driven path — a request-fed block audits exactly like a
        scraped one.
        """
        # The session lock is held only around bounded in-memory work
        # (cursor advance + claim, PRNG split, publish) — NOT around
        # the sentiment forward or the fleet/preview compute, whose
        # first calls pay pipeline construction and XLA compiles (tens
        # of seconds) and must never freeze other commands / the web UI
        # poll.  Racing fetches classify concurrently, each on the
        # distinct window its atomic cursor advance claimed; the claim
        # token keeps publishes in window order.
        with metrics.timer("fetch_latency").time(), stage_span("fetch"):
            with self.lock:
                if window is None:
                    comments, _dates, self.simulation_step = self.store.read_window(
                        self.simulation_step, self.config.window, self.config.fetch_limit
                    )
                self._fetch_claim += 1
                claim = self._fetch_claim
                step = self.simulation_step
            # The block's lineage id, minted from the claim token (so
            # seeded replays mint identical ids) and annotated onto the
            # OPEN fetch span — every child span (vectorize/tokenize/
            # forward/fleet/consensus) inherits it, and every event
            # below carries it, making the whole block auditable as one
            # record (docs/OBSERVABILITY.md §lineage).
            lineage = mint_lineage(claim, prefix=self.lineage_prefix)
            tracer.annotate_lineage(lineage)
            if window is None:
                if not comments:
                    raise EmptyStoreError(
                        "comment store is empty — run the scraper (or seed the "
                        "store) before fetching"
                    )
                n_comments = len(comments)
                self.journal.emit(
                    "block.fetched",
                    lineage=lineage,
                    n_comments=n_comments,
                    cursor=step,
                )
                # Resolved only now: an empty store must fail in
                # milliseconds, not after a transformer build.
                vectorize = self.vectorizer
                # A SentimentPipeline records its own tokenize/pack/
                # forward child spans; "vectorize" covers injected
                # vectorizers too.
                with stage_span("vectorize"):
                    window = jnp.asarray(
                        np.asarray(vectorize(comments), dtype=np.float32)
                    )
                subset = self.config.bootstrap_subset
                source = "store"
            else:
                window_np = np.asarray(window, dtype=np.float32)
                if (
                    window_np.ndim != 2
                    or window_np.shape[1] != self.config.dimension
                ):
                    raise ValueError(
                        f"request window must be [K, {self.config.dimension}]"
                        f", got {window_np.shape}"
                    )
                if window_np.shape[0] == 0:
                    raise EmptyStoreError(
                        "request-driven fetch got an empty window — the "
                        "feed should skip claims with no pending requests"
                    )
                n_comments = int(window_np.shape[0])
                # Rolling request context (docs/SERVING.md §windows):
                # consensus runs over the claim's recent sentiment PLUS
                # the new requests, capped at the pull-mode window size
                # — a lone request extends the last block's context
                # instead of forming a degenerate 1-comment block.
                with self.lock:
                    if self._request_window is not None:
                        window_np = np.concatenate(
                            [self._request_window, window_np]
                        )
                    # Cap unconditionally: a first feed larger than the
                    # pull-mode window (a flooded cold claim) must obey
                    # the same window semantics as every later one.
                    window_np = window_np[-self.config.window :]
                    self._request_window = window_np
                window_rows = int(window_np.shape[0])
                # Request windows are small and arbitrary-sized (1..the
                # batch budget), where store windows are large and
                # steady.  pow2-bucket the row count by tiling the
                # window cyclically: `_fleet` compiles O(log2 max-batch)
                # shapes (SVOC003 discipline), and the padding rows are
                # REAL comments repeated, so the bootstrap only ever
                # averages served content.  The subset stays strictly
                # under the bucket (never the configured 10 ≥ rows,
                # which would throw in `jax.random.choice` — and a
                # subset EQUAL to the bucket would make every honest
                # oracle average the whole window: identical
                # predictions, the exact zero-variance block the
                # faithful commit refuses).
                bucket = 1 << max(0, window_rows - 1).bit_length()
                if bucket > window_rows:
                    window_np = np.resize(
                        window_np, (bucket, window_np.shape[1])
                    )
                subset = min(
                    self.config.bootstrap_subset, max(1, bucket // 2)
                )
                source = "serving"
                # Same event, extra source tag: store-driven blocks keep
                # their exact historical payload (seeded smoke
                # fingerprints), request-fed blocks are distinguishable
                # in the audit record.
                self.journal.emit(
                    "block.fetched",
                    lineage=lineage,
                    n_comments=n_comments,
                    cursor=step,
                    source="serving",
                    window_rows=window_rows,
                )
                window = jnp.asarray(window_np)
            with self.lock:
                if self._key_value is None:
                    self._key_value = jax.random.PRNGKey(self.config.seed)
                self._key_value, sub = jax.random.split(self._key_value)
            with stage_span("fleet"):
                values, honest = _fleet(
                    sub,
                    window,
                    self.config.n_oracles,
                    self.config.n_failing,
                    subset,
                )
            with stage_span("consensus"):
                # The host conversions below are the existing fetch of
                # the fleet/preview results — the span times dispatch +
                # that fetch without adding any device sync of its own
                # (hence the svoclint SVOC001 suppressions: the sync IS
                # this span's documented purpose).
                predictions = np.asarray(values, dtype=np.float64)  # svoclint: disable=SVOC001
                if tamper is not None:
                    # Scenario tampering replaces the block wholesale;
                    # the preview must describe what the gate sees, so
                    # the tampered block rides back to the device.  The
                    # block is ALREADY on the host (the fetch above is
                    # this span's documented sync) — this asarray just
                    # normalizes the hook's return, no device round-trip.
                    predictions = np.asarray(  # svoclint: disable=SVOC001
                        tamper(predictions), dtype=np.float64
                    )
                    values = jnp.asarray(predictions.astype(np.float32))
                mean, median, ranks = _preview_stats(values)
                # The gate verdict travels WITH the block it describes
                # (one count-bearing inspection per fetch; commits
                # re-check their own snapshot without counting).  The
                # gate emits the block's quarantine.verdict event.
                quarantine = (
                    self.gate.inspect(predictions, lineage=lineage)
                    if self.config.quarantine_gate
                    else None
                )
                ranks_np = np.asarray(ranks)  # svoclint: disable=SVOC001
                preview = {
                    "values": predictions,
                    "mean": np.asarray(mean),  # svoclint: disable=SVOC001
                    "median": np.asarray(median),  # svoclint: disable=SVOC001
                    "normalized_ranks": ranks_np,
                    "honest": np.asarray(honest),  # svoclint: disable=SVOC001
                    "n_comments": n_comments,
                    "lineage": lineage,
                    "quarantine": (
                        quarantine.as_dict() if quarantine is not None else None
                    ),
                }
            metrics.counter("comments_processed").add(n_comments)
            admitted = (
                int(np.sum(quarantine.ok))
                if quarantine is not None
                else int(predictions.shape[0])
            )
            self.journal.emit(
                "consensus.result",
                lineage=lineage,
                n_oracles=int(predictions.shape[0]),
                admitted=admitted,
                # The gated kernel's validity bound (docs/ROBUSTNESS.md):
                # below 2 admitted oracles no interval is meaningful —
                # the postmortem monitor auto-bundles on False.
                interval_valid=admitted >= 2,
                suspects=int(np.sum(ranks_np <= 0.2)),
            )
            with self.lock:
                # Publish only if no LATER claim already did — a slow
                # fetch of an older window must not regress the state.
                if claim > self._fetch_published:
                    self._fetch_published = claim
                    self.predictions = predictions
                    self._block_source = source
                    self.last_quarantine = quarantine
                    self.last_lineage = lineage
                    self.last_preview = preview  # svoc: volatile(render cache derived from predictions; the UI rebuilds it on the next fetch/poll)
                    self.bump_state()
        return preview

    def bump_state(self) -> None:
        """Mark renderable state as changed (web UI poll redraw).
        Self-locking: the increment is a read-modify-write racing the
        auto_fetch thread against command dispatch."""
        with self.lock:
            self.state_version += 1

    # -- the commit path (contract.py:200-208) ------------------------------

    def _refuse_degenerate(self, predictions: np.ndarray, lineage) -> None:
        """Pre-tx dry-run of the faithful engine — the same
        ``two_pass_consensus`` the contract's golden recompute runs when
        the final oracle's tx lands.  A fleet whose reliable predictions
        agree exactly in some dimension panics there (zero-variance
        skewness, ``math.cairo:320-338`` — an i128 division by zero that
        reverts the tx), deterministically stranding the last signer and
        churning the supervisor over pure math.  Refusing here turns
        that churn into a typed :class:`DegenerateBlockError` BEFORE any
        tx, journaled as ``commit.deferred`` so the serving tier's defer
        is auditable on the block's lineage."""
        from svoc_tpu.consensus import wsad_engine as eng
        from svoc_tpu.ops.fixedpoint import to_wsad, to_wsad_rows

        try:
            eng.two_pass_consensus(
                # Vectorized wsad quantization (one numpy truncation,
                # bit-identical to the per-element ``to_wsad`` loop —
                # docs/PARALLELISM.md §host-overhead).
                to_wsad_rows(np.asarray(predictions)),
                constrained=self.config.constrained,
                n_failing=self.config.n_failing,
                max_spread=to_wsad(self.config.max_spread),
                strict_interval=True,
            )
        except ZeroDivisionError:
            metrics.counter("commit_deferred_degenerate").add(1)
            self.journal.emit(
                "commit.deferred", lineage=lineage, reason="degenerate"
            )
            raise DegenerateBlockError(
                "refusing to commit a zero-variance fleet block: the "
                "on-chain skewness recompute would divide by zero and "
                "revert the final oracle's tx (math.cairo:320-338) — "
                "defer until the block regains oracle diversity"
            ) from None
        except Exception:  # svoclint: disable=SVOC014 -- deliberate: every OTHER engine panic keeps its existing commit-path semantics — the txs go out and fail per-oracle with full breaker/supervisor accounting, so the degrade is counted downstream, not here
            # Every OTHER engine panic (interval error, codec range, …)
            # keeps its existing commit-path semantics: the txs are sent
            # and fail per-oracle with full breaker/supervisor
            # accounting.  Only the deterministic zero-variance revert
            # is worth refusing pre-tx.
            pass

    def commit(self) -> int:
        """Send every oracle's prediction as its own signed tx.

        On a mid-loop failure the partial tx count is still recorded
        (those transactions are on chain) before the
        :class:`ChainCommitError` propagates to the command layer.

        A fleet block the quarantine gate flagged refuses to commit AT
        ALL (:class:`QuarantinedInputError`, before any tx): the
        faithful path has no degraded mode, and sending the dirty tx
        would only trade a clear refusal for a felt-codec crash or an
        on-chain interval panic mid-fleet.
        """
        # Snapshot under the session lock, then submit under the COMMIT
        # lock only: a Sepolia RPC can stall indefinitely and must not
        # freeze the console / web UI behind the session lock, but two
        # concurrent commits must also not interleave their per-oracle
        # txs (a mixed fleet no fetch produced would reach consensus) —
        # whole-fleet atomicity lives on ``_commit_lock``.
        with self.lock:
            if self.predictions is None:
                raise RuntimeError("fetch before commit")
            predictions = self.predictions
            lineage = self.last_lineage
            source = self._block_source
        if self.config.quarantine_gate:
            report = self.gate.inspect(predictions, count=False)
            if not report.clean:
                self.journal.emit(
                    "commit.failed",
                    lineage=lineage,
                    reason="quarantined",
                    slots=report.quarantined_slots,
                )
                raise QuarantinedInputError(report)
        if source == "serving":
            # Request-fed blocks only: a serving cold start (one
            # request, no window history) deterministically produces
            # the zero-variance shape — defer instead of reverting.
            # Store-driven blocks keep their exact historical commit
            # semantics (partial fleets, per-oracle failure charges).
            self._refuse_degenerate(predictions, lineage)
        with self._commit_lock, metrics.timer("commit_latency").time():
            try:
                n = self.adapter.update_all_the_predictions(
                    predictions, lineage=lineage
                )  # svoclint: disable=SVOC010 -- deliberate: commit runs under _commit_lock end-to-end (whole-fleet atomicity); no journal subscriber re-enters the commit path (docs/OBSERVABILITY.md §events)
            except ChainCommitError as e:
                metrics.counter("chain_transactions").add(e.committed)
                metrics.counter("chain_commit_failures").add(1)
                # Interactive failures feed the health scores too — the
                # supervisor folds ALL commit-failure history.
                self.supervisor.record_commit_failure(e.failed_oracle, e.cause)
                self.journal.emit(
                    "commit.failed",
                    lineage=lineage,
                    reason="chain",
                    index=e.committed,
                    oracle=e.failed_oracle,
                    cause=str(e.cause),
                )  # svoclint: disable=SVOC010 -- deliberate: failure accounting must land before the raise unwinds the commit lock; no subscriber re-enters commit
                self.bump_state()  # partial txs changed chain state
                raise
        metrics.counter("chain_transactions").add(n)
        self.journal.emit(
            "commit.sent", lineage=lineage, sent=n, total=n, attempts=1,
            stranded=0,
        )
        self.bump_state()
        return n

    def commit_resilient(self) -> CommitOutcome:
        """The auto loop's commit: retry with decorrelated-jitter
        backoff, RESUME partial fleets (re-send only the stranded
        suffix — ``ChainCommitError.committed`` accounting), consult
        the circuit breaker per attempt, and report every per-oracle
        failure to the health supervisor.

        Same locking shape as :meth:`commit` (snapshot under the
        session lock, submit under ``_commit_lock`` only) — the retry
        loop runs INSIDE the whole-fleet atomicity, so two concurrent
        resilient commits still cannot interleave their txs.

        Returns the :class:`CommitOutcome`; a degraded cycle (some
        oracles stranded after their attempt budget) is a *successful
        return* with ``outcome.stranded`` non-empty — the loop stays
        alive and the supervisor owns the replacement decision.  Raises
        :class:`CircuitOpenError` when the breaker short-circuits and
        :class:`ChainCommitError` only when the overall retry deadline
        expires mid-fleet.
        """
        with self.lock:
            if self.predictions is None:
                raise RuntimeError("fetch before commit")
            predictions = self.predictions
            lineage = self.last_lineage
            source = self._block_source
        if self.wal is not None and lineage is not None:
            # Under the commit lock: the reconciler resends chain txs,
            # and two concurrent commits racing this guard would both
            # classify the same slot stranded and double-send it — the
            # exact duplicate the guard exists to prevent.  Re-checked
            # inside the lock; the block completes (and releases)
            # before the commit section re-acquires below, and a loser
            # of the race then sees the cycle closed.
            with self._commit_lock:
                open_here = lineage in self.wal.open_lineages()
                if open_here:
                    from svoc_tpu.durability.reconcile import reconcile_wal

                    # An OPEN cycle for this lineage: a previous life
                    # died mid-commit and the recovery reconcile could
                    # not close it (a faulted resend, missing
                    # evidence).  Its txs may be durably on chain —
                    # blind re-execution would double-send them (the
                    # fuzzer capture behind tests/fixtures/chaos_corpus
                    # /duplicate-txs-reconcile-error.json), so resolve
                    # the cycle through the reconciler's evidence
                    # columns instead; on success the replayed-lineage
                    # path below dedups exactly as for a cleanly-closed
                    # cycle.
                    reconcile_wal(
                        self.wal,
                        lambda _claim: self.adapter,
                        journal=self.journal,
                        lineages={lineage},
                    )  # svoclint: disable=SVOC010 -- deliberate: the reconciler journals its per-cycle verdicts inside the whole-fleet atomicity this guard shares with the commit path; no subscriber re-enters commit
            if open_here and lineage not in self.wal.completed_lineages():
                metrics.counter("chain_commit_failures").add(1)
                self.journal.emit(
                    "commit.failed",
                    lineage=lineage,
                    reason="open_cycle_unresolved",
                    sent=0,
                )
                raise ChainCommitError(
                    committed=0,
                    total=len(predictions),
                    failed_oracle=None,
                    cause=RuntimeError(
                        "open WAL cycle unresolved — refusing to "
                        "blind re-commit a lineage whose txs may "
                        "already be on chain"
                    ),
                    sent_count=0,
                )
        if (
            self.wal is not None
            and lineage is not None
            and lineage in self.wal.completed_lineages()
        ):
            # Snapshot-replay re-execution (docs/RESILIENCE.md
            # §durability): a restart resumes from its snapshot and
            # re-runs the steps after it; this block's commit cycle
            # already CLOSED in a previous life (its txs are on chain,
            # witnessed by the WAL's done record), so the chain writes
            # — and the supervisor/SLO charges the original run
            # already made — must not happen twice.
            done = next(
                (
                    r
                    for r in reversed(self.wal.records())
                    if r.get("kind") == "done"
                    and r.get("lineage") == lineage
                ),
                {},
            )
            sent = int(done.get("sent", 0))
            self.journal.emit(
                "commit.sent",
                lineage=lineage,
                sent=sent,
                total=sent,
                attempts=0,
                stranded=0,
                replayed=True,
            )
            return CommitOutcome(sent=sent, total=sent, attempts=0)
        # Quarantine gate (docs/ROBUSTNESS.md): refused slots never
        # produce a tx; each refusal charges the slot's oracle exactly
        # like a commit failure, so a persistent garbage emitter walks
        # the same health→quarantine→replacement path as a dead signer.
        skip: tuple = ()
        if self.config.quarantine_gate:
            report = self.gate.inspect(predictions, count=False)
            if not report.clean:
                skip = tuple(report.quarantined_slots)
                oracles = self.adapter.call_oracle_list()
                for slot in report.quarantined_slots:
                    if slot < len(oracles):
                        # The charge event carries the block lineage —
                        # the audit link from this verdict to the
                        # replacement clock it advanced.
                        self.supervisor.record_quarantine(
                            oracles[slot], report.reasons[slot],
                            lineage=lineage,
                        )
                metrics.counter("commit_skipped_quarantined").add(len(skip))
        if source == "serving" and not skip:
            # Request-fed blocks only (store-driven commits keep their
            # exact historical semantics, which tier-1 pins).  With
            # skipped slots the on-chain block the LAST tx activates
            # keeps the skipped oracles' previous values, so a
            # full-predictions dry-run would not be exact — and a
            # partially-skipped fleet never reproduces the cold-start
            # all-identical shape this guard exists for.
            self._refuse_degenerate(predictions, lineage)
        with self._commit_lock, metrics.timer("commit_latency").time():
            wal_cycle = None
            if self.wal is not None:
                # The cycle-open needs the oracle list (one chain RPC)
                # BEFORE commit_fleet_with_resume's own breaker
                # machinery runs — so the breaker contract must be
                # honored here too: an OPEN breaker short-circuits
                # before paying the RPC + payload fsyncs, and a
                # transport failure on the read records a breaker
                # failure exactly like the loop's first-RPC failure
                # would (otherwise an outage with a WAL attached would
                # never trip the breaker).
                retry_after = self.breaker.retry_after_s()
                if retry_after > 0:
                    metrics.counter("commit_short_circuits").add(1)
                    self.journal.emit(
                        "commit.failed",
                        lineage=lineage,
                        reason="circuit_open",
                        backend=self.breaker.name,
                        sent=0,
                    )  # svoclint: disable=SVOC010 -- deliberate: short-circuit accounting under the commit lock; no subscriber re-enters commit
                    raise CircuitOpenError(
                        self.breaker.name, retry_after, sent=0
                    )
                try:
                    oracles = self.adapter.call_oracle_list()
                except Exception:
                    self.breaker.record_failure()  # svoclint: disable=SVOC010 -- deliberate: breaker flushes its queued transition events on THIS thread after releasing its own lock; only the commit lock is held and no subscriber re-enters commit
                    metrics.counter("chain_commit_failures").add(1)
                    self.journal.emit(
                        "commit.failed",
                        lineage=lineage,
                        reason="transport",
                        sent=0,
                    )  # svoclint: disable=SVOC010 -- deliberate: transport-failure accounting before the raise; no subscriber re-enters commit
                    raise
                wal_cycle = self._open_wal_cycle(
                    predictions, lineage, skip, oracles
                )
            try:
                outcome = commit_fleet_with_resume(
                    self.adapter,
                    predictions,
                    self.config.commit_retry,
                    breaker=self.breaker,
                    skip=skip,
                    on_oracle_failure=self.supervisor.record_commit_failure,
                    journal=self.journal,
                    lineage=lineage,
                    wal=wal_cycle,
                    commit_mode=self.commit_mode,
                )  # svoclint: disable=SVOC010 -- deliberate: the retry/resume loop journals per-attempt outcomes INSIDE the whole-fleet atomicity the commit lock provides; no journal subscriber re-enters the commit path
            except ChainCommitError as e:
                # resilient_sent is the TRUE landed-tx count (committed
                # is a fleet index that counts skipped/stranded slots).
                metrics.counter("chain_transactions").add(
                    getattr(e, "resilient_sent", e.committed)
                )
                metrics.counter("chain_commit_failures").add(1)
                self.bump_state()
                raise
            except CircuitOpenError as e:
                metrics.counter("chain_transactions").add(e.sent)
                metrics.counter("commit_short_circuits").add(1)
                if e.sent:
                    self.bump_state()
                raise
        metrics.counter("chain_transactions").add(outcome.sent)
        if outcome.stranded:
            # The cycle landed degraded — count it like the single-shot
            # path counts its failures, so soak accounting stays one
            # series.
            metrics.counter("chain_commit_failures").add(1)
        self.bump_state()
        return outcome

    def attach_wal(self, wal) -> None:
        """Wire a :class:`svoc_tpu.durability.wal.CommitIntentWAL` into
        the resilient commit path (docs/RESILIENCE.md §durability)."""
        self.wal = wal

    def _open_wal_cycle(self, predictions, lineage, skip, oracles):
        """The cycle-open record: the full felt payload matrix ahead of
        any tx, so a restart can classify AND resend every slot.  A
        slot whose payload cannot encode (garbage the gate somehow
        missed) records ``None`` — the commit loop will fail that tx
        with its usual codec semantics, and the reconciler treats the
        slot like a skip.  The encode here is deliberately repeated by
        the commit plane (digest parity REQUIRES the WAL payload and
        the wire payload to be the same encoding; the cost is
        microseconds against a signed tx) — both sides now route
        through the same vectorized
        :func:`svoc_tpu.ops.fixedpoint.encode_matrix`, the per-element
        ``encode_vector`` loop's bit-identical replacement
        (docs/PARALLELISM.md §host-overhead).  WAL append failures
        propagate unwrapped — "no durable intent, no tx", and a disk
        problem must not feed the CHAIN breaker."""
        from svoc_tpu.ops.fixedpoint import encode_matrix

        skip_set = frozenset(int(i) for i in skip)
        encoded = encode_matrix(
            np.asarray(predictions, dtype=np.float64), on_error="none"
        )
        payloads = [
            None if i in skip_set else row for i, row in enumerate(encoded)
        ]
        return self.wal.cycle(
            lineage,
            claim=self.config.claim,
            oracles=oracles[: len(payloads)],
            payloads=payloads,
            skip=sorted(skip_set),
        )

    def supervisor_step(self) -> Optional[Dict]:
        """One fleet-health fold (auto loop cadence).  Never raises —
        a supervisor problem (faulted chain read mid-chaos, vote race)
        must not take down the serving loop."""
        if not self.config.supervise_fleet:
            return None
        try:
            # The fold's events carry the lineage of the block whose
            # commit cycle drove it — the replacement-vote leg of that
            # block's audit record.
            with self.lock:
                lineage = self.last_lineage
            report = self.supervisor.step(lineage=lineage)
        except Exception:
            metrics.counter("supervisor_errors").add(1)
            return None
        if report.get("replaced"):
            self.bump_state()  # the fleet roster changed
        return report

    def set_auto_flags(
        self,
        *,
        fetch: Optional[bool] = None,
        commit: Optional[bool] = None,
        resume: Optional[bool] = None,
    ) -> None:
        """Toggle the auto flags and bump ``state_version`` so the web
        UI surfaces them live (the reference documents the flags but
        never implements them, ``web_interface.py:22-23``)."""
        with self.lock:
            if fetch is not None:
                self.auto_fetch = fetch
            if commit is not None:
                self.auto_commit = commit
            if resume is not None:
                self.auto_resume = resume
            self.state_version += 1

    def resilience_snapshot(self) -> Dict:
        """Breaker + fleet-health state for the UI and soak artifacts.
        Cheap: no chain I/O (the supervisor reads its cached scores)."""
        with self.lock:
            quarantine = self.last_quarantine
            lineage = self.last_lineage
        return {
            "breaker": self.breaker.state(),
            "health": self.supervisor.health_snapshot(),
            "quarantined": self.supervisor.quarantined_slots(),
            "replacements": len(self.supervisor.replacements),
            # Input-integrity gate verdict over the LAST fetched fleet
            # (docs/ROBUSTNESS.md) — None until the first gated fetch.
            "input_quarantine": (
                quarantine.as_dict() if quarantine is not None else None
            ),
            # The last published block's lineage id — the key for
            # ``GET /api/audit/<block>`` / the console's ``audit``.
            "lineage": lineage,
        }

    # -- flight recorder views (docs/OBSERVABILITY.md §events) --------------

    def audit(self, lineage: Optional[str] = None) -> Dict:
        """The per-block audit record (events + spans + summary) for
        ``lineage`` — default: the last published block."""
        if lineage is None:
            with self.lock:
                lineage = self.last_lineage
        if lineage is None:
            return {"lineage": None, "found": False, "events": [],
                    "spans": [], "summary": {}}
        return audit_record(lineage, journal=self.journal)

    def _slo_evaluator(self):
        if self._slo is None:
            from svoc_tpu.utils.slo import SLOEvaluator, default_slos

            self._slo = SLOEvaluator(
                default_slos(metrics), registry=metrics, journal=self.journal
            )
        return self._slo

    def slo_snapshot(self) -> Dict:
        """Evaluate the declarative SLOs (commit success ratio, p99
        consensus latency, quarantine admission) as fast/slow burn
        rates; exports the ``slo_burn_rate`` gauges and emits
        ``slo.alert`` events on threshold crossings."""
        return self._slo_evaluator().evaluate()

    def slo_step(self) -> Optional[Dict]:
        """The auto loop's SLO fold — never raises (a broken evaluator
        must not take down serving)."""
        try:
            return self.slo_snapshot()
        except Exception:
            metrics.counter("slo_errors").add(1)
            return None
