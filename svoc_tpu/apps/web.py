"""Minimal web UI (reference L7 parity, dependency-free).

The reference serves an ``eel`` app — Chart.js scatter plots per label
pair, reliability progress bars, a console wired to ``query()``, and an
oracle-replacement menu (``client/web/``, SURVEY.md §2.3).  This module
reproduces that surface with the standard library only (the image has
no ``eel``/CDN access): an ``http.server`` serving one self-contained
HTML page (hand-rolled canvas scatter plots) plus two JSON endpoints:

- ``POST /api/query`` — body = command text, response = console lines
  (the same :class:`svoc_tpu.apps.commands.CommandConsole` dispatcher
  the CLI uses; SURVEY's eel-websocket boundary becomes plain HTTP),
- ``GET /api/state`` — the last fetch preview + cached chain state,
  driving the plots and progress bars,
- ``GET /api/events`` — server-sent-events stream pushing
  ``{"state_version": N}`` the moment the session state changes (the
  eel-websocket push parity the reference gets from
  ``eel.expose``/``main.js:26``; VERDICT r4 "missing" item 5).  The
  page is push-first with the poll loop demoted to a slow reconnect
  fallback.  Concurrent streams are capped (``MAX_SSE_STREAMS``) —
  each holds one ThreadingHTTPServer thread, and an abandoned tab
  must not exhaust the thread pool,
- ``GET /metrics`` — Prometheus text exposition of the shared
  observability registry (stage-span histograms, counters, timers,
  device gauges sampled on demand; docs/OBSERVABILITY.md),
- ``POST /api/submit`` — the serving tier's request path
  (docs/SERVING.md): ``{"claim": ..., "text": ...}`` through cache /
  admission; 200 = served (``admitted``/``cached``), 429 = shed, 404 =
  unknown claim, 503 = no serving tier attached.

Start with ``python -m svoc_tpu.apps.web`` or ``serve(console)``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from svoc_tpu.apps.commands import CommandConsole

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>svoc console</title>
<style>
 body { font-family: monospace; background: #111; color: #ddd; margin: 1rem; }
 h2 { color: #8cf; }
 #plots { display: flex; flex-wrap: wrap; gap: 1rem; }
 canvas { background: #1a1a2a; border: 1px solid #345; }
 #console { background: #000; padding: .5rem; height: 14rem; overflow-y: scroll;
            white-space: pre-wrap; border: 1px solid #345; }
 #cmd { width: 100%; background: #222; color: #ddd; border: 1px solid #345;
        font-family: monospace; padding: .3rem; }
 .bar { background: #333; height: 1rem; width: 20rem; }
 .bar > div { height: 100%; background: #4c4; }
 .bar.low > div { background: #c44; }
</style></head>
<body>
<h2>svoc — stochastic vector oracle consensus</h2>
<div>reliability first pass <div class="bar" id="rel1"><div style="width:0"></div></div>
     reliability second pass <div class="bar" id="rel2"><div style="width:0"></div></div>
     trend <canvas id="rel2spark" width="160" height="16"
            style="vertical-align:middle"></canvas>
     <span id="rel2warn" style="color:#e55"></span></div>
<div id="resil" style="color:#9ab; margin:.3rem 0"></div>
<div id="plots"></div>
<button id="replace-btn">Oracle Replacement</button>
<div id="replace-menu" style="display:none; border:1px solid #345; padding:.5rem; margin:.5rem 0">
  <h3>propose replacement</h3>
  as admin <select id="rp-admin"></select>
  replace oracle <select id="rp-old"></select>
  with address <input id="rp-new" placeholder="0x...">
  <button id="rp-send">propose</button>
  <button id="rp-clear">clear my proposition</button>
  <h3>vote</h3>
  as admin <select id="vt-admin"></select>
  on proposition of admin <select id="vt-which"></select>
  <button id="vt-yes">yes</button> <button id="vt-no">no</button>
  <div id="rp-props" style="white-space: pre-line"></div>
</div>
<div id="console"></div>
<input id="cmd" placeholder="command ('help' to list)" autofocus>
<script>
const consoleEl = document.getElementById('console');
function writeLines(lines) {
  for (const l of lines) {
    if (l === '\\x1b[clear]') { consoleEl.textContent = ''; continue; }
    consoleEl.textContent += l + '\\n';
  }
  consoleEl.scrollTop = consoleEl.scrollHeight;
}
async function query(text) {
  const r = await fetch('/api/query', {method: 'POST', body: text});
  writeLines(await r.json());
  refresh();
}
document.getElementById('cmd').addEventListener('keydown', e => {
  if (e.key === 'Enter') { query(e.target.value); e.target.value = ''; }
});
function drawScatter(canvas, pts, colors, mean, median, names) {
  const ctx = canvas.getContext('2d');
  ctx.clearRect(0, 0, canvas.width, canvas.height);
  const pad = 20, w = canvas.width - 2 * pad, h = canvas.height - 2 * pad;
  // axis label names per pair (reference columnNames,
  // oracle_scheduler.py:113-118 / simulation_graphics.js:8-80)
  ctx.fillStyle = '#89a';
  ctx.font = '11px monospace';
  ctx.fillText(names[0], canvas.width / 2 - 4 * names[0].length, canvas.height - 4);
  ctx.save();
  ctx.translate(10, canvas.height / 2 + 4 * names[1].length);
  ctx.rotate(-Math.PI / 2);
  ctx.fillText(names[1], 0, 0);
  ctx.restore();
  const xs = pts.map(p => p[0]).concat([mean[0], median[0]]);
  const ys = pts.map(p => p[1]).concat([mean[1], median[1]]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs);
  const y0 = Math.min(...ys), y1 = Math.max(...ys);
  const sx = v => pad + w * (v - x0) / (x1 - x0 + 1e-9);
  const sy = v => pad + h * (1 - (v - y0) / (y1 - y0 + 1e-9));
  pts.forEach((p, i) => {
    ctx.fillStyle = colors[i];
    ctx.beginPath(); ctx.arc(sx(p[0]), sy(p[1]), 4, 0, 7); ctx.fill();
  });
  ctx.fillStyle = '#8cf';
  ctx.fillRect(sx(mean[0]) - 3, sy(mean[1]) - 3, 6, 6);
  ctx.fillStyle = '#fc3';
  ctx.fillRect(sx(median[0]) - 3, sy(median[1]) - 3, 6, 6);
}
function fillSelect(el, items) {
  const prev = el.value;  // keep the operator's pick across refresh()
  el.innerHTML = '';
  items.forEach((label, i) => {
    const o = document.createElement('option');
    o.value = i; o.textContent = i + ': ' + label;
    el.appendChild(o);
  });
  if (prev !== '' && Number(prev) < items.length) el.value = prev;
}
function updateReplacementMenu(s) {
  // reference modal: admin/oracle selectors populated from chain
  // state (oracle_management.js:23-62, index.html:10-71)
  const admins = s.admin_list || [], oracles = s.oracle_list || [];
  for (const id of ['rp-admin', 'vt-admin', 'vt-which'])
    fillSelect(document.getElementById(id), admins);
  fillSelect(document.getElementById('rp-old'), oracles);
  const props = document.getElementById('rp-props');
  props.textContent = (s.replacement_propositions || [])
    .map((p, i) => 'admin ' + i + ': ' + (p === null ? 'None' : JSON.stringify(p)))
    .join('\\n');
}
let lastVersion = null;
async function refresh(s) {
  if (!s) {  // poll loop passes the state it already fetched
    const r = await fetch('/api/state');
    s = await r.json();
  }
  lastVersion = s.state_version;
  for (const [id, v] of [['rel1', s.reliability_first_pass],
                         ['rel2', s.reliability_second_pass]]) {
    const bar = document.getElementById(id);
    const pct = Math.max(0, Math.min(100, (v || 0) * 100));
    bar.firstElementChild.style.width = pct + '%';
    bar.classList.toggle('low', pct < 50);  // sepolia_graphics.js:53-69
  }
  // rel2 TRAJECTORY sparkline: a capture approach shows as a slide,
  // not a low level (docs/ALGORITHM.md section 5 security note).
  const spark = document.getElementById('rel2spark');
  const sctx = spark.getContext('2d');
  sctx.clearRect(0, 0, spark.width, spark.height);
  const hist = s.rel2_history || [];
  if (hist.length >= 2) {
    // y normalized to the window's own range: the alarm slide is a few
    // percent absolute and would be sub-pixel on a [0,1] scale.
    const lo = Math.min(...hist), hi = Math.max(...hist);
    const span = Math.max(hi - lo, 1e-6);
    sctx.strokeStyle = s.rel2_falling ? '#e55' : '#5b5';
    sctx.beginPath();
    hist.forEach((v, i) => {
      const x = i * (spark.width - 2) / (hist.length - 1) + 1;
      const y = spark.height - 1 - ((v - lo) / span) * (spark.height - 2);
      i ? sctx.lineTo(x, y) : sctx.moveTo(x, y);
    });
    sctx.stroke();
  }
  document.getElementById('rel2warn').textContent =
    s.rel2_falling ? '⚠ falling' : '';
  // Resilience status line: auto flags, breaker state, fleet health
  // (docs/RESILIENCE.md) — toggling a flag bumps state_version, so
  // this repaints live through the same push channel as everything.
  const rs = s.resilience || {};
  const onoff = v => v ? 'on' : 'off';
  const quarantined = (rs.quarantined || []).join(',');
  document.getElementById('resil').textContent =
    'auto fetch:' + onoff(s.auto_fetch)
    + ' commit:' + onoff(s.auto_commit)
    + ' resume:' + onoff(s.auto_resume)
    + ' · breaker: ' + (rs.breaker || 'n/a')
    + ' · replacements: ' + (rs.replacements || 0)
    + (quarantined ? ' · quarantined slots: ' + quarantined : '')
    + (rs.lineage ? ' · block: ' + rs.lineage : '');
  updateReplacementMenu(s);
  const plots = document.getElementById('plots');
  plots.innerHTML = '';
  if (!s.preview) return;
  const vals = s.preview.values, ranks = s.preview.normalized_ranks;
  const labels = s.labels || [];
  const dim = vals[0].length;
  for (let c = 0; c + 1 < dim; c += 2) {  // one plot per label pair
    const canvas = document.createElement('canvas');
    canvas.width = 260; canvas.height = 220;
    plots.appendChild(canvas);
    const pts = vals.map(v => [v[c], v[c + 1]]);
    // red when normalized rank <= 0.2 (simulation_graphics.js:97-99)
    const colors = ranks.map(r => r <= 0.2 ? '#e55' : '#5b5');
    drawScatter(canvas, pts,
      colors,
      [s.preview.mean[c], s.preview.mean[c + 1]],
      [s.preview.median[c], s.preview.median[c + 1]],
      [labels[c] || ('dim ' + c), labels[c + 1] || ('dim ' + (c + 1))]);
  }
}
document.getElementById('replace-btn').addEventListener('click', () => {
  const m = document.getElementById('replace-menu');
  m.style.display = m.style.display === 'none' ? 'block' : 'none';
});
document.getElementById('rp-send').addEventListener('click', () => {
  query('update_proposition ' + document.getElementById('rp-admin').value
    + ' ' + document.getElementById('rp-old').value
    + ' ' + document.getElementById('rp-new').value);
});
document.getElementById('rp-clear').addEventListener('click', () => {
  query('update_proposition ' + document.getElementById('rp-admin').value + ' None');
});
for (const [id, ans] of [['vt-yes', 'yes'], ['vt-no', 'no']])
  document.getElementById(id).addEventListener('click', () => {
    query('vote_for_a_proposition ' + document.getElementById('vt-admin').value
      + ' ' + document.getElementById('vt-which').value + ' ' + ans);
  });
query('help');  // boot with the command list (main.js:45); its
                // completion handler performs the initial refresh()
// Live refresh, PUSH-FIRST (reference eel parity: the UI repaints on
// every fetch push, simulation_graphics.js:85): /api/events streams a
// state_version the moment the session changes; each push triggers one
// /api/state fetch + redraw.  The poll loop stays only as a slow
// fallback while the event stream is down (server restarting) —
// EventSource auto-reconnects.  ?journal=1 opts this stream into the
// flight recorder's TYPED frames too (docs/OBSERVABILITY.md §events);
// the unnamed state_version frames below are unchanged, and the named
// 'journal' frames land in their own listener.
let pushAlive = false;
let pushedVersion = null, pushRefreshing = false;
const events = new EventSource('/api/events?journal=1');
// Alert-class journal events surface in the console as they happen —
// the 2 a.m. story (quarantine → breaker → replacement → SLO burn)
// narrates itself instead of hiding in aggregate bars.
const alertTypes = ['slo.alert', 'breaker.transition',
                    'supervisor.replacement', 'quarantine.verdict',
                    'postmortem.bundle'];
events.addEventListener('journal', ev => {
  const e = JSON.parse(ev.data);
  if (!alertTypes.includes(e.type)) return;
  // Clean verdicts are per-block routine; only refusals narrate.
  if (e.type === 'quarantine.verdict'
      && !(e.data && e.data.reasons && Object.keys(e.data.reasons).length))
    return;
  writeLines(['⚠ ' + e.type + (e.lineage ? ' [' + e.lineage + ']' : '')
              + ' ' + JSON.stringify(e.data)]);
});
// Reconnect resets the catch-up target: a pushed version from the
// PREVIOUS server process is not comparable to the new process's
// versions (a restarted server counts from 0 again, so a stale high
// target would spin the catch-up loop forever against a server that
// can never reach it).
events.onopen = () => { pushAlive = true; pushedVersion = null; };
events.onerror = () => { pushAlive = false; pushedVersion = null; };
events.onmessage = async (ev) => {
  pushAlive = true;
  pushedVersion = JSON.parse(ev.data).state_version;
  if (pushRefreshing) return;  // serialized: out-of-order /api/state
  pushRefreshing = true;       // responses could paint stale state
  try {
    // catch up to at least the pushed version; versions are monotonic,
    // so a fetch that returns NEWER than the push exits immediately
    // (no spin), a transient fetch failure retries after a pause, and
    // a SUCCESSFUL fetch that still trails the target (rapid pushes,
    // or a version skew after restart) paces itself instead of
    // hammering /api/state in a busy-loop.
    while (pushedVersion !== null && pushedVersion > lastVersion) {
      try { await refresh(); }
      catch (e) { await new Promise(res => setTimeout(res, 500)); }
      if (pushedVersion !== null && pushedVersion > lastVersion)
        await new Promise(res => setTimeout(res, 250));
    }
  } finally { pushRefreshing = false; }
};
let polling = false;
setInterval(async () => {
  if (pushAlive || polling) return;  // fallback only; never stack polls
  polling = true;
  try {
    const r = await fetch('/api/state');
    const s = await r.json();
    if (s.state_version !== lastVersion) await refresh(s);
  } catch (e) { /* server restarting; retry next tick */ }
  polling = false;
}, 5000);
</script></body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    console: CommandConsole  # set by serve()

    #: Concurrent /api/events streams allowed — each parks one
    #: ThreadingHTTPServer thread in the push loop, so without a cap a
    #: handful of abandoned tabs (or a reconnect storm) would starve
    #: the query/state handlers of threads.  Excess clients get 503 +
    #: Retry-After and fall back to the page's poll loop.
    MAX_SSE_STREAMS = 16

    def _host_ok(self) -> bool:
        """DNS-rebinding guard for loopback serving: the Host header
        must name the bound address (a rebound evil.example resolving
        to 127.0.0.1 sends its own name).  Wildcard binds opted into
        remote clients (serve() warned), so any Host is accepted."""
        bound = self.server.server_address[0]
        if bound in ("0.0.0.0", "::"):
            return True
        host = self.headers.get("Host", "")
        hostname = (
            host.split("]")[0] + "]" if host.startswith("[") else host.rsplit(":", 1)[0]
        )
        return hostname in {"127.0.0.1", "localhost", "[::1]", bound}

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib API)
        if not self._host_ok():
            self._send(403, b"unexpected Host header", "text/plain")
            return
        if self.path == "/":
            self._send(200, _PAGE.encode(), "text/html; charset=utf-8")
        elif self.path == "/api/state":
            session = self.console.session
            # Consistent snapshots: the adapter lock guards the cache
            # against a concurrent 'resume' rehydrating it key-by-key
            # ("dictionary changed size during iteration"); the session
            # lock pairs preview with its state_version.  ORDER MATTERS:
            # the version is read BEFORE the cache, so data can only be
            # fresher than its label — a stale-cache/new-version pairing
            # would make the browser's version comparison skip the next
            # poll's fresh redraw.
            with session.lock:
                preview = session.last_preview
                state_version = session.state_version
            state = session.adapter.cache_snapshot()

            def fmt(x):
                """Addresses as the reference displays them
                (hex for ints, contract.py to_hex)."""
                from svoc_tpu.io.chain import to_hex

                return to_hex(x) if isinstance(x, int) else str(x)

            trend = session.adapter.rel2_trend()
            payload = {
                "state_version": state_version,
                "auto_fetch": session.auto_fetch,
                "auto_commit": session.auto_commit,
                "auto_resume": session.auto_resume,
                # breaker / fleet-health state (docs/RESILIENCE.md);
                # cheap — no chain I/O behind it.
                "resilience": session.resilience_snapshot(),
                "reliability_first_pass": state.get("reliability_first_pass"),
                "reliability_second_pass": state.get("reliability_second_pass"),
                # trajectory, not just level: capture is invisible in
                # the level (docs/ALGORITHM.md §5 security note).  The
                # FULL trend window ships (≤256 floats) so the warn
                # flag and the sparkline always describe the same data.
                "rel2_history": trend["history"],
                "rel2_falling": trend["falling"],
                "consensus": state.get("consensus"),
                "consensus_active": state.get("consensus_active"),
                "labels": session.label_names,
                "admin_list": [fmt(a) for a in state.get("admin_list") or []],
                "oracle_list": [fmt(o) for o in state.get("oracle_list") or []],
                "replacement_propositions": [
                    None if p is None else [p[0], fmt(p[1])]
                    for p in state.get("replacement_propositions") or []
                ],
                "preview": None
                if preview is None
                else {
                    "values": preview["values"].tolist(),
                    "mean": preview["mean"].tolist(),
                    "median": preview["median"].tolist(),
                    "normalized_ranks": preview["normalized_ranks"].tolist(),
                },
            }
            # Multi-claim fabric (docs/FABRIC.md): when a MultiSession
            # is attached to the console, /api/state carries every
            # claim's snapshot — per-claim consensus slice, commit
            # outcome, supervisor health, and block lineage.
            fabric = getattr(self.console, "fabric", None)
            if fabric is not None:
                # One snapshot serves both sections: the per-claim map
                # and the fabric's pinned dispatch routing
                # (docs/FABRIC.md §mesh — consensus_impl, claim mesh,
                # pipelining), so a pull-mode deployment surfaces the
                # routing even without a serving tier attached and a
                # future snapshot field never needs a second edit here.
                fabric_snapshot = fabric.snapshot()
                payload["claims"] = fabric_snapshot.pop("claims")
                payload["fabric"] = fabric_snapshot
            # Serving tier (docs/SERVING.md): queues, admission
            # accounting, cache stats, live burn rate, and the
            # request-latency percentiles — the operator's saturation
            # view, refreshed with every state poll.
            serving = getattr(self.console, "serving", None)
            if serving is not None:
                payload["serving"] = serving.snapshot()
            # Durability layer (docs/RESILIENCE.md §durability):
            # snapshot freshness + commit-intent WAL health, so an
            # operator can see at a glance whether a restart would
            # recover warm (and whether a cycle is awaiting
            # reconciliation).
            durability = getattr(self.console, "durability", None)
            if durability is not None:
                payload["durability"] = durability.status()
            # Cluster plane (docs/CLUSTER.md): placement map + epoch,
            # per-replica liveness/accounting, and the migration/
            # failover counters — the fleet operator's routing view.
            cluster = getattr(self.console, "cluster", None)
            if cluster is not None:
                payload["cluster"] = cluster.snapshot()
            # Reconfiguration plane (docs/RECONFIG.md): transition
            # phase, fleet epoch, holds/deferred depth, and the tail
            # of the committed epoch chain.
            reconfig = getattr(self.console, "reconfig", None)
            if reconfig is not None:
                payload["reconfig"] = reconfig.status()
            # Fleet observability plane (docs/OBSERVABILITY.md
            # §fleet-plane): source roster, hop-chain count, per-source
            # observation accounting, fleet SLO alerts, anomalies.
            fleetplane = getattr(self.console, "fleetplane", None)
            if fleetplane is not None:
                payload["fleet_obs"] = fleetplane.snapshot()
            self._send(200, json.dumps(payload).encode(), "application/json")
        elif self.path == "/api/events" or self.path.startswith("/api/events?"):
            self._serve_events()
        elif self.path == "/api/profile" or self.path.startswith("/api/profile?"):
            self._serve_profile()
        elif self.path.startswith("/api/audit/"):
            # Per-block audit record (docs/OBSERVABILITY.md §lineage):
            # events + spans + summary joined on one lineage id.
            lineage = self.path[len("/api/audit/") :].split("?", 1)[0]
            record = self.console.session.audit(lineage or None)
            if not record.get("found"):
                self._send(
                    404,
                    json.dumps(record).encode(),
                    "application/json",
                )
            else:
                self._send(
                    200, json.dumps(record).encode(), "application/json"
                )
        elif self.path == "/metrics/fleet":
            # Merged fleet exposition (docs/OBSERVABILITY.md
            # §fleet-plane): counters summed across sources + the
            # retired ledger, gauges replica-labeled.  404-typed when
            # no plane is attached or it is disabled — a scraper must
            # be able to tell "off" from "empty fleet".
            fleetplane = getattr(self.console, "fleetplane", None)
            if fleetplane is None or not fleetplane.enabled:
                self._send(
                    404,
                    json.dumps({"error": "fleet plane not enabled"}).encode(),
                    "application/json",
                )
            else:
                self._send(
                    200,
                    fleetplane.render_prometheus_fleet().encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
        elif self.path == "/metrics":
            # Prometheus text exposition of the shared registry.  The
            # runtime gauges (live-array bytes per device, compile
            # counts) are sampled here, on demand — never on the hot
            # path, and a no-op before the first device touch (the
            # lazy-backend design of apps/session.py).
            from svoc_tpu.utils.metrics import registry, sample_runtime_gauges

            sample_runtime_gauges(registry)
            self._send(
                200,
                registry.render_prometheus().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        else:
            self._send(404, b"not found", "text/plain")

    def _serve_events(self) -> None:
        """Server-sent-events push channel: one tiny ``data:`` frame per
        session state change (the reference's eel-websocket push,
        ``main.js:26``, on a stdlib transport).  Each open stream holds
        one ThreadingHTTPServer thread; the loop exits on client
        disconnect (write fails) or server shutdown (the ``__shutdown``
        flag ``serve``'s closer sets), a 15 s heartbeat comment bounds
        how long a silent dead connection lingers, and concurrent
        streams are capped at ``MAX_SSE_STREAMS`` (503 + Retry-After
        beyond it — the page's poll fallback covers rejected clients).

        ``?journal=1`` opts the stream into TYPED event frames: every
        new flight-recorder event (``svoc_tpu.utils.events``) arrives
        as a named ``event: journal`` SSE frame alongside the unnamed
        ``state_version`` frames (which are unchanged — the page's
        ``onmessage`` handler and old clients never see named frames).
        Frames per tick are capped so a journal burst cannot wedge the
        write loop."""
        import time as _time

        want_journal = "journal=1" in (
            self.path.split("?", 1)[1] if "?" in self.path else ""
        )

        # Admission under the server-wide lock: racing opens must not
        # both pass the check and overshoot the cap.
        with self.server.svoc_sse_lock:
            if self.server.svoc_sse_streams >= self.MAX_SSE_STREAMS:
                self.send_response(503)
                self.send_header("Retry-After", "5")
                body = b"too many event streams"
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                from svoc_tpu.utils.metrics import registry as _metrics

                _metrics.counter("sse_rejected").add(1)
                return
            self.server.svoc_sse_streams += 1
        session = self.console.session
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            last_version = None
            last_write = 0.0
            last_seq = 0
            if want_journal:
                from svoc_tpu.utils.events import journal as _journal

                # Stream only NEW events — a reconnecting tab must not
                # replay the whole ring through its own frames.
                last_seq = _journal.last_seq()
            while not getattr(self.server, "svoc_shutting_down", False):
                with session.lock:
                    version = session.state_version
                now = _time.monotonic()
                if version != last_version:
                    frame = json.dumps({"state_version": version})
                    self.wfile.write(f"data: {frame}\n\n".encode())
                    self.wfile.flush()
                    last_version, last_write = version, now
                elif now - last_write > 15.0:
                    self.wfile.write(b": keepalive\n\n")  # SSE comment
                    self.wfile.flush()
                    last_write = now
                if want_journal:
                    # ≤ 50 typed frames per tick: a journal burst drains
                    # over a few ticks instead of wedging this write
                    # loop (the busy-loop guard the cap test pins).
                    # Truncation is VISIBLE, not silent
                    # (docs/OBSERVABILITY.md §events): a capped tick
                    # marks its LAST frame ``truncated: true`` and
                    # counts the backlog it deferred in
                    # ``sse_frames_dropped{stream="journal"}`` — a
                    # consumer can tell "caught up" from "drinking from
                    # a burst through a straw".
                    batch = _journal.since(last_seq, limit=50)
                    if batch:
                        backlog = _journal.last_seq() - batch[-1].seq
                        for i, rec in enumerate(batch):
                            if backlog > 0 and i == len(batch) - 1:
                                payload = rec.as_dict()
                                payload["truncated"] = True
                                data = json.dumps(payload, sort_keys=True)
                            else:
                                data = rec.to_json()
                            self.wfile.write(
                                f"event: journal\ndata: {data}\n\n".encode()
                            )
                            last_seq = rec.seq
                        if backlog > 0:
                            from svoc_tpu.utils.metrics import (
                                registry as _metrics,
                            )

                            _metrics.counter(
                                "sse_frames_dropped",
                                labels={"stream": "journal"},
                            ).add(backlog)
                        self.wfile.flush()
                        last_write = now
                _time.sleep(0.25)
        except (OSError, ValueError):
            # Client went away (BrokenPipe/Reset) or the handler's
            # wfile was torn down mid-write ("I/O operation on closed
            # file" surfaces as ValueError) — either way this stream is
            # done; the slot release below is what matters.
            return
        finally:
            with self.server.svoc_sse_lock:
                self.server.svoc_sse_streams -= 1

    def _serve_profile(self) -> None:
        """``GET /api/profile`` — on-demand profiler control
        (docs/OBSERVABILITY.md §cost-attribution).  ``?action=start``
        (optional ``&duration_s=``), ``?action=stop``, or
        ``?action=status`` (default).  503 when no profiler is
        attached; the profiler itself never raises — a capture error
        comes back as its status dict (500)."""
        profiler = getattr(self.console, "profiler", None)
        if profiler is None:
            self._send(
                503,
                json.dumps({"error": "no profiler attached"}).encode(),
                "application/json",
            )
            return
        query = self.path.split("?", 1)[1] if "?" in self.path else ""
        params = dict(
            p.split("=", 1) for p in query.split("&") if "=" in p
        )
        action = params.get("action", "status")
        if action == "start":
            try:
                duration_s = (
                    float(params["duration_s"])
                    if "duration_s" in params
                    else None
                )
            except ValueError:
                self._send(
                    400,
                    json.dumps({"error": "duration_s must be a number"}).encode(),
                    "application/json",
                )
                return
            result = profiler.start(duration_s=duration_s)
        elif action == "stop":
            result = profiler.stop()
        elif action == "status":
            result = profiler.status()
        else:
            self._send(
                400,
                json.dumps({"error": f"unknown action {action!r}"}).encode(),
                "application/json",
            )
            return
        code = 500 if result.get("status") == "error" else 200
        self._send(code, json.dumps(result).encode(), "application/json")

    def do_POST(self):  # noqa: N802
        if self.path not in ("/api/query", "/api/submit"):
            self._send(404, b"not found", "text/plain")
            return
        # CSRF guard: a text/plain POST is a "simple request", so any
        # page open in a local browser could otherwise drive the session
        # (incl. chain transactions and 'exit').  Browsers always attach
        # Origin to cross-origin POSTs — reject when it names another
        # host; header-free clients (curl, tests) pass.  _host_ok()
        # additionally blocks DNS rebinding, where Origin and Host match
        # each other but name the attacker's domain.
        if not self._host_ok():
            self._send(403, b"unexpected Host header", "text/plain")
            return
        origin = self.headers.get("Origin")
        if origin is not None and origin.split("://", 1)[-1] != self.headers.get(
            "Host", ""
        ):
            self._send(403, b"cross-origin request rejected", "text/plain")
            return
        length = int(self.headers.get("Content-Length", "0"))
        text = self.rfile.read(length).decode("utf-8", "replace")
        if self.path == "/api/submit":
            self._serve_submit(text)
            return
        lines = self.console.query(text)
        self._send(200, json.dumps(lines).encode(), "application/json")

    def _serve_submit(self, body: str) -> None:
        """``POST /api/submit`` — the serving tier's ingestion edge
        (docs/SERVING.md §api).  Body: ``{"claim": ..., "text": ...}``.
        Status codes carry the admission verdict: 200 for served
        (``admitted``/``cached``), **429** for ``shed`` (the standard
        shed-load response — well-behaved clients back off, which is
        the point of admission control), 404 for an unknown claim, 503
        when no serving tier is attached."""
        serving = getattr(self.console, "serving", None)
        if serving is None:
            self._send(
                503,
                json.dumps({"error": "no serving tier attached"}).encode(),
                "application/json",
            )
            return
        try:
            request = json.loads(body)
            claim = request["claim"]
            text = request["text"]
        except (ValueError, TypeError, KeyError):
            self._send(
                400,
                json.dumps(
                    {"error": 'body must be {"claim": ..., "text": ...}'}
                ).encode(),
                "application/json",
            )
            return
        if not isinstance(claim, str) or not isinstance(text, str):
            self._send(
                400,
                json.dumps({"error": "claim and text must be strings"}).encode(),
                "application/json",
            )
            return
        try:
            response = serving.submit(claim, text)
        except KeyError:
            self._send(
                404,
                json.dumps({"error": f"unknown claim {claim!r}"}).encode(),
                "application/json",
            )
            return
        code = 429 if response["status"] == "shed" else 200
        self._send(code, json.dumps(response).encode(), "application/json")

    def log_message(self, *args):  # silence request logging
        pass


def serve(
    console: CommandConsole,
    host: str = "127.0.0.1",
    port: int = 8100,
    block: bool = True,
) -> Tuple[ThreadingHTTPServer, Optional[threading.Thread]]:
    """Start the UI server.  ``block=False`` runs it on a daemon thread
    and returns ``(server, thread)`` (the test/embedding mode; the
    reference's ``eel.start(block=False)``, ``web_interface.py:61-67``)."""
    handler = type("BoundHandler", (_Handler,), {"console": console})
    if host not in ("127.0.0.1", "localhost", "::1"):
        import warnings

        warnings.warn(
            f"svoc web UI binding to non-loopback host {host!r}: the "
            "query endpoint executes console commands (incl. chain "
            "transactions) for anyone who can reach it",
            stacklevel=2,
        )
    server = ThreadingHTTPServer((host, port), handler)
    # Cooperative stop flag for the long-lived /api/events streams
    # (daemon threads — this bounds their lifetime under test servers
    # that start and stop within one process).
    server.svoc_shutting_down = False
    # Live SSE stream accounting (the MAX_SSE_STREAMS cap).
    server.svoc_sse_streams = 0
    server.svoc_sse_lock = threading.Lock()
    orig_shutdown = server.shutdown

    def shutdown():
        server.svoc_shutting_down = True
        orig_shutdown()

    server.shutdown = shutdown
    if block:  # pragma: no cover — interactive mode
        server.serve_forever()
        return server, None
    # Tight poll interval: shutdown() blocks a full poll period, and
    # embedded/test servers start and stop constantly — the stdlib
    # default of 0.5 s turns every teardown into half a second.
    thread = threading.Thread(
        target=lambda: server.serve_forever(poll_interval=0.05), daemon=True
    )
    thread.start()
    return server, thread


def main(argv=None) -> int:  # pragma: no cover — interactive entry
    import argparse

    from svoc_tpu.apps.cli import build_parser

    parser = build_parser()
    parser.add_argument("--port", type=int, default=8100)
    parser.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args(argv)

    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource

    store = CommentStore(args.db)
    if store.count() == 0 and args.seed_comments:
        store.save(SyntheticSource(batch=args.seed_comments)())
    from svoc_tpu.apps.cli import build_adapter

    session = Session(
        config=SessionConfig(
            n_oracles=args.n_oracles,
            n_failing=args.n_failing,
            dimension=args.dimension,
            refresh_rate_s=args.refresh,
            scraper_rate_s=args.rate,
            live_scraper=args.live_scraper,
        ),
        store=store,
        adapter=build_adapter(args),
    )
    console = CommandConsole(session, write=print)
    # Startup resume+fetch (reference main.py:51-54).  fetch is the
    # only stage that touches the device; a failure is reported by the
    # console itself (CommandConsole.query catches and emits errors)
    # and does not prevent the server from starting.  Pass
    # --disable_startup_fetch for fully device-free startup.
    console.query("resume")
    if not args.disable_startup_fetch:
        console.query("fetch")
    print(f"svoc web UI on http://{args.host}:{args.port}")
    serve(console, host=args.host, port=args.port, block=True)
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
