"""Minimal web UI (reference L7 parity, dependency-free).

The reference serves an ``eel`` app — Chart.js scatter plots per label
pair, reliability progress bars, a console wired to ``query()``, and an
oracle-replacement menu (``client/web/``, SURVEY.md §2.3).  This module
reproduces that surface with the standard library only (the image has
no ``eel``/CDN access): an ``http.server`` serving one self-contained
HTML page (hand-rolled canvas scatter plots) plus two JSON endpoints:

- ``POST /api/query`` — body = command text, response = console lines
  (the same :class:`svoc_tpu.apps.commands.CommandConsole` dispatcher
  the CLI uses; SURVEY's eel-websocket boundary becomes plain HTTP),
- ``GET /api/state`` — the last fetch preview + cached chain state,
  driving the plots and progress bars.

Start with ``python -m svoc_tpu.apps.web`` or ``serve(console)``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from svoc_tpu.apps.commands import CommandConsole

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>svoc console</title>
<style>
 body { font-family: monospace; background: #111; color: #ddd; margin: 1rem; }
 h2 { color: #8cf; }
 #plots { display: flex; flex-wrap: wrap; gap: 1rem; }
 canvas { background: #1a1a2a; border: 1px solid #345; }
 #console { background: #000; padding: .5rem; height: 14rem; overflow-y: scroll;
            white-space: pre-wrap; border: 1px solid #345; }
 #cmd { width: 100%; background: #222; color: #ddd; border: 1px solid #345;
        font-family: monospace; padding: .3rem; }
 .bar { background: #333; height: 1rem; width: 20rem; }
 .bar > div { height: 100%; background: #4c4; }
 .bar.low > div { background: #c44; }
</style></head>
<body>
<h2>svoc — stochastic vector oracle consensus</h2>
<div>reliability first pass <div class="bar" id="rel1"><div style="width:0"></div></div>
     reliability second pass <div class="bar" id="rel2"><div style="width:0"></div></div></div>
<div id="plots"></div>
<div id="console"></div>
<input id="cmd" placeholder="command ('help' to list)" autofocus>
<script>
const consoleEl = document.getElementById('console');
function writeLines(lines) {
  for (const l of lines) {
    if (l === '\\x1b[clear]') { consoleEl.textContent = ''; continue; }
    consoleEl.textContent += l + '\\n';
  }
  consoleEl.scrollTop = consoleEl.scrollHeight;
}
async function query(text) {
  const r = await fetch('/api/query', {method: 'POST', body: text});
  writeLines(await r.json());
  refresh();
}
document.getElementById('cmd').addEventListener('keydown', e => {
  if (e.key === 'Enter') { query(e.target.value); e.target.value = ''; }
});
function drawScatter(canvas, pts, colors, mean, median) {
  const ctx = canvas.getContext('2d');
  ctx.clearRect(0, 0, canvas.width, canvas.height);
  const pad = 20, w = canvas.width - 2 * pad, h = canvas.height - 2 * pad;
  const xs = pts.map(p => p[0]).concat([mean[0], median[0]]);
  const ys = pts.map(p => p[1]).concat([mean[1], median[1]]);
  const x0 = Math.min(...xs), x1 = Math.max(...xs);
  const y0 = Math.min(...ys), y1 = Math.max(...ys);
  const sx = v => pad + w * (v - x0) / (x1 - x0 + 1e-9);
  const sy = v => pad + h * (1 - (v - y0) / (y1 - y0 + 1e-9));
  pts.forEach((p, i) => {
    ctx.fillStyle = colors[i];
    ctx.beginPath(); ctx.arc(sx(p[0]), sy(p[1]), 4, 0, 7); ctx.fill();
  });
  ctx.fillStyle = '#8cf';
  ctx.fillRect(sx(mean[0]) - 3, sy(mean[1]) - 3, 6, 6);
  ctx.fillStyle = '#fc3';
  ctx.fillRect(sx(median[0]) - 3, sy(median[1]) - 3, 6, 6);
}
async function refresh() {
  const r = await fetch('/api/state');
  const s = await r.json();
  for (const [id, v] of [['rel1', s.reliability_first_pass],
                         ['rel2', s.reliability_second_pass]]) {
    const bar = document.getElementById(id);
    const pct = Math.max(0, Math.min(100, (v || 0) * 100));
    bar.firstElementChild.style.width = pct + '%';
    bar.classList.toggle('low', pct < 50);  // sepolia_graphics.js:53-69
  }
  const plots = document.getElementById('plots');
  plots.innerHTML = '';
  if (!s.preview) return;
  const vals = s.preview.values, ranks = s.preview.normalized_ranks;
  const dim = vals[0].length;
  for (let c = 0; c + 1 < dim; c += 2) {  // one plot per label pair
    const canvas = document.createElement('canvas');
    canvas.width = 260; canvas.height = 220;
    plots.appendChild(canvas);
    const pts = vals.map(v => [v[c], v[c + 1]]);
    // red when normalized rank <= 0.2 (simulation_graphics.js:97-99)
    const colors = ranks.map(r => r <= 0.2 ? '#e55' : '#5b5');
    drawScatter(canvas, pts,
      colors,
      [s.preview.mean[c], s.preview.mean[c + 1]],
      [s.preview.median[c], s.preview.median[c + 1]]);
  }
}
refresh();
</script></body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    console: CommandConsole  # set by serve()

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib API)
        if self.path == "/":
            self._send(200, _PAGE.encode(), "text/html; charset=utf-8")
        elif self.path == "/api/state":
            session = self.console.session
            state = dict(session.adapter.cache)
            preview = session.last_preview
            payload = {
                "reliability_first_pass": state.get("reliability_first_pass"),
                "reliability_second_pass": state.get("reliability_second_pass"),
                "consensus": state.get("consensus"),
                "consensus_active": state.get("consensus_active"),
                "preview": None
                if preview is None
                else {
                    "values": preview["values"].tolist(),
                    "mean": preview["mean"].tolist(),
                    "median": preview["median"].tolist(),
                    "normalized_ranks": preview["normalized_ranks"].tolist(),
                },
            }
            self._send(200, json.dumps(payload).encode(), "application/json")
        else:
            self._send(404, b"not found", "text/plain")

    def do_POST(self):  # noqa: N802
        if self.path != "/api/query":
            self._send(404, b"not found", "text/plain")
            return
        length = int(self.headers.get("Content-Length", "0"))
        text = self.rfile.read(length).decode("utf-8", "replace")
        lines = self.console.query(text)
        self._send(200, json.dumps(lines).encode(), "application/json")

    def log_message(self, *args):  # silence request logging
        pass


def serve(
    console: CommandConsole,
    host: str = "127.0.0.1",
    port: int = 8100,
    block: bool = True,
) -> Tuple[ThreadingHTTPServer, Optional[threading.Thread]]:
    """Start the UI server.  ``block=False`` runs it on a daemon thread
    and returns ``(server, thread)`` (the test/embedding mode; the
    reference's ``eel.start(block=False)``, ``web_interface.py:61-67``)."""
    handler = type("BoundHandler", (_Handler,), {"console": console})
    server = ThreadingHTTPServer((host, port), handler)
    if block:  # pragma: no cover — interactive mode
        server.serve_forever()
        return server, None
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, thread


def main(argv=None) -> int:  # pragma: no cover — interactive entry
    import argparse

    from svoc_tpu.apps.cli import build_parser

    parser = build_parser()
    parser.add_argument("--port", type=int, default=8100)
    parser.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args(argv)

    from svoc_tpu.apps.session import Session, SessionConfig
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource

    store = CommentStore(args.db)
    if store.count() == 0 and args.seed_comments:
        store.save(SyntheticSource(batch=args.seed_comments)())
    session = Session(
        config=SessionConfig(
            n_oracles=args.n_oracles,
            n_failing=args.n_failing,
            dimension=args.dimension,
            refresh_rate_s=args.refresh,
            scraper_rate_s=args.rate,
            live_scraper=args.live_scraper,
        ),
        store=store,
    )
    console = CommandConsole(session, write=print)
    print(f"svoc web UI on http://{args.host}:{args.port}")
    serve(console, host=args.host, port=args.port, block=True)
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
