"""The serving tier facade: submit → micro-batch → fabric → respond.

One object gives the PR 6 fabric a request path (docs/SERVING.md):

- :meth:`ServingTier.submit` is the ingestion edge (the web layer's
  ``POST /api/submit`` and the console's ``serving submit`` both call
  it): cache / admission / bounded queues via
  :class:`~svoc_tpu.serving.frontend.ServingFrontend`.
- :meth:`ServingTier.step` is one continuous-batching cycle: the
  :class:`~svoc_tpu.serving.batcher.MicroBatcher` assembles a fair
  cross-claim micro-batch, one packed forward vectorizes every cache
  miss, results fill the dedup cache, and the per-claim vector groups
  feed the request-driven fabric cycle
  (``MultiSession.step(feeds=...)`` → fused sanitized claim-cube
  consensus → per-claim resilient commit).  Completion observes each
  request's end-to-end latency into ``request_latency_seconds`` — the
  histogram behind the ``request_latency`` SLO whose burn rate closes
  the admission loop.
- The clock is injectable: seeded scenarios
  (:mod:`svoc_tpu.serving.scenario`) drive virtual time so latencies,
  burn rates, and shed decisions replay byte-identically.

The tier never owns a thread itself — ``step()`` is driven by the
caller (``run_loop`` offers the daemon-thread convenience), the same
inversion the router uses, so tests and seeded replays control the
cadence exactly.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from svoc_tpu.obsplane.plane import resolve_cost_plane
from svoc_tpu.serving.batcher import MicroBatcher
from svoc_tpu.serving.cache import ResultCache
from svoc_tpu.serving.frontend import AdmissionConfig, ServingFrontend
from svoc_tpu.utils.metrics import stage_span
from svoc_tpu.utils.slo import REQUEST_LATENCY_HISTOGRAM, serving_slos


class ServingTier:
    """Continuous-batching serving over a
    :class:`~svoc_tpu.fabric.session.MultiSession`."""

    def __init__(
        self,
        multi,
        *,
        vectorizer=None,
        admission: Optional[AdmissionConfig] = None,
        cache: Optional[ResultCache] = None,
        cache_capacity: int = 4096,
        max_requests_per_step: int = 64,
        max_segments: int = 8,
        clock=None,
        slos: Optional[Sequence] = None,
        slo_clock=None,
        prewarmer=None,
        cost_plane=None,
    ):
        from svoc_tpu.fabric.router import resolve_journal
        from svoc_tpu.utils.slo import SLOEvaluator

        self.multi = multi
        self._metrics = multi.metrics
        self._journal = resolve_journal(multi.journal)
        self._clock = clock if clock is not None else time.monotonic
        if cache is None:
            cache = ResultCache(cache_capacity, metrics=self._metrics)
        #: The compile-plane worker gating cold-shape deferrals
        #: (docs/SERVING.md §cold-start).  None (or an attached router
        #: prewarmer) falls back to ``multi.router.prewarmer`` so one
        #: ``MultiSession.start_prewarm()`` wires both the warmth
        #: accounting and the defer gate.
        self._prewarmer = prewarmer
        #: The cost-attribution plane (docs/OBSERVABILITY.md
        #: §cost-attribution).  Routing resolves ONCE here — explicit
        #: arg > SVOC_COST_PLANE env > PERF_DECISIONS.json — the same
        #: construction-time pinning as consensus_impl/commit_mode.
        #: Marks use the tier clock so virtual-time scenarios stay
        #: deterministic; the router shares this plane for its
        #: dispatch-cost windows.
        self.cost_plane = (
            cost_plane
            if cost_plane is not None
            else resolve_cost_plane(clock=self._clock, metrics=self._metrics)
        )
        self.multi.router.cost_plane = self.cost_plane
        self.frontend = ServingFrontend(
            multi,
            admission=admission,
            cache=cache,
            metrics=self._metrics,
            journal=self._journal,
            clock=self._clock,
            cold_gate=self._claim_cold,
            cost_plane=self.cost_plane,
        )
        #: The cross-claim vectorizer.  None = each micro-batch builds
        #: on demand from the FIRST claim session's vectorizer (the
        #: shared packed pipeline in live deployments; injected fakes in
        #: tests/scenarios always pass one explicitly).
        self._vectorizer = vectorizer
        self.batcher = MicroBatcher(
            self.frontend,
            vectorizer,
            max_requests=max_requests_per_step,
            max_segments=max_segments,
            metrics=self._metrics,
        )
        #: The serving SLOs (request_latency drives admission).  The
        #: evaluator clock defaults to the tier clock so virtual-time
        #: scenarios burn deterministically.
        self._evaluator = SLOEvaluator(
            slos if slos is not None else serving_slos(self._metrics),
            registry=self._metrics,
            journal=self._journal,
            clock=slo_clock if slo_clock is not None else self._clock,
        )
        self.steps = 0
        #: End-of-step hooks (the recovery manager's snapshot cadence,
        #: docs/RESILIENCE.md §durability): run AFTER completions are
        #: counted and queues updated — the tier's only fully-quiesced
        #: point, so a snapshot here can account every admitted request
        #: as completed, queued, or (post-snapshot) deferred.  Same
        #: contract as ``ClaimRouter.post_step_hooks``: registration
        #: order, exceptions counted, never kill the loop.
        self.post_step_hooks: List[Any] = []
        self._loop_thread: Optional[threading.Thread] = None
        self._loop_stop: Optional[threading.Event] = None

    @property
    def cache(self) -> ResultCache:
        return self.frontend.cache

    @property
    def prewarmer(self):
        """The active prewarm worker: the injected one, else whatever
        ``MultiSession.start_prewarm`` attached to the router."""
        return (
            self._prewarmer
            if self._prewarmer is not None
            else self.multi.router.prewarmer
        )

    def _claim_cold(self, claim_id: str) -> bool:
        """The frontend's cold-shape gate: True while an in-flight
        prewarm has not yet compiled this claim's dispatch group.  No
        worker (or a finished one) defers nothing."""
        worker = self.prewarmer
        if worker is None or not worker.active:
            return False
        return worker.claim_cold(self.multi.get(claim_id).spec)

    def _resolve_vectorizer(self):
        if self._vectorizer is None:
            states = self.multi.registry.states()
            if not states:
                raise RuntimeError("serving tier has no claims to serve")
            # The claims share one model anyway (the session property
            # builds the same pipeline); reuse the first session's.
            self._vectorizer = states[0].session.vectorizer
            self.batcher.vectorizer = self._vectorizer
        return self._vectorizer

    # -- ingestion edge -----------------------------------------------------

    def submit(self, claim_id: str, text: str) -> Dict[str, Any]:
        """One request through cache + admission (``ServingFrontend``)."""
        # Membership check BEFORE the labeled counter: claim ids come
        # straight off the wire, and a counter per arbitrary client
        # string would grow the registry without bound (and count 404s
        # as submissions).
        state = self.multi.get(claim_id)  # KeyError → the HTTP layer's 404
        self._metrics.counter(
            "serving_submitted", labels={"claim": claim_id}
        ).add(1)
        return self.frontend.submit(claim_id, text, state=state)

    # -- the continuous-batching cycle --------------------------------------

    def step(self) -> Dict[str, Any]:
        """One serving cycle; returns the step report (consumed request
        count, per-claim fabric outcome, completion latencies)."""
        report = self._step_inner()
        for hook in list(self.post_step_hooks):
            try:
                hook(report)
            except Exception:  # noqa: BLE001 — a hook must not kill serving
                self._metrics.counter("serving_hook_errors").add(1)
        return report

    def _step_inner(self) -> Dict[str, Any]:
        self.steps += 1
        plane = self.cost_plane
        report: Dict[str, Any] = {
            "step": self.steps,
            "requests": 0,
            "claims": 0,
            "served": [],
            "skipped": {},
            "dropped": 0,
            "latencies_s": [],
        }
        with stage_span("serving_step"):
            dropped = self._purge_removed_claims()
            report["dropped"] = dropped
            requests = self.batcher.assemble()
            if not requests:
                # Idle tick: still refresh the burn gauges, so recovery
                # after an overload is observed even with no traffic.
                self._evaluator.evaluate()
                return report
            # Batch assembly done: queue_wait ends here for every
            # drained request (cost plane; no-op when disabled).
            plane.mark_requests(requests, "assembled")
            self._resolve_vectorizer()
            drained = len(requests)
            # Every drained request must end this step accounted —
            # completed or dropped — even when the step dies mid-way
            # (an XLA runtime error, a buggy injected vectorizer):
            # `pending` holds the not-yet-accounted set, and the
            # except-hook below drops whatever is left before
            # re-raising, so admission_sample (utils/slo.py) can never
            # read a lost request as served.
            pending = set(requests)

            def drop(request) -> None:
                nonlocal dropped
                self._metrics.counter(
                    "serving_dropped", labels={"claim": request.claim}
                ).add(1)
                # Dropped requests still close their timeline (outcome
                # keeps the per-stage histograms clean of partial
                # flows, but the lineage stays joinable offline).
                plane.complete(request, self._clock(), outcome="dropped")
                pending.discard(request)
                dropped += 1

            try:
                with stage_span("serving_batch"):
                    try:
                        # Dedup keys on the admission-time digest —
                        # the text is never re-hashed after submit
                        # (docs/SERVING.md §hash-once).
                        vectors = self.batcher.vectorize_requests(requests)
                    except Exception:  # svoclint: disable=SVOC014 -- deliberate: the degrade engages two lines below where BOTH lanes into vectors=None share one counted serving_vectorize_errors increment
                        vectors = None
                if vectors is None:
                    # One poisoned text must not lose the whole
                    # cross-claim micro-batch (the per-claim isolation
                    # contract extends through the shared forward):
                    # fall back to per-request vectorize and drop ONLY
                    # the requests that fail.
                    self._metrics.counter("serving_vectorize_errors").add(1)
                    survivors: List[Any] = []
                    vecs: List[np.ndarray] = []
                    for request in requests:
                        try:
                            vecs.append(
                                self.batcher.vectorize([request.text])[0]
                            )
                            survivors.append(request)
                        except Exception:  # svoclint: disable=SVOC014 -- deliberate: drop() counts serving_dropped{claim=} and closes the request's timeline with outcome="dropped" — the closure is the accounting
                            drop(request)
                    requests, vectors = survivors, vecs
                plane.mark_requests(requests, "vectorized")
                for request, vector in zip(requests, vectors):
                    # The serving step's documented host fetch: the
                    # packed forward's vectors must land on host to
                    # fill the dedup cache and feed the per-claim
                    # fabric groups.
                    request.vector = np.asarray(vector, dtype=np.float64)  # svoclint: disable=SVOC001
                    self.cache.put(request.key, request.vector)
                if requests:
                    feeds = self.batcher.group_by_claim(requests)
                    fabric_report = self.multi.step(feeds=feeds)
                else:
                    feeds = {}
                    fabric_report = {"served": [], "skipped": {}}
                served_claims = set(fabric_report["served"])
                now = self._clock()
                latencies: List[float] = []
                for request in requests:
                    if request.claim not in served_claims:
                        # The fabric skipped this claim mid-cycle
                        # (paused after admission, malformed feed,
                        # fetch error): its drained requests did NOT
                        # complete.  They land in serving_dropped,
                        # which counts against the serving_admission
                        # objective (utils/slo.py) — a blackholed claim
                        # burns the SLO instead of reading green
                        # forever.
                        drop(request)
                        continue
                    latency = max(0.0, now - request.t_submit)
                    latencies.append(latency)
                    self._metrics.histogram(
                        REQUEST_LATENCY_HISTOGRAM
                    ).observe(latency)
                    self._metrics.counter(
                        "serving_completed", labels={"claim": request.claim}
                    ).add(1)
                    # Fold the router's per-claim dispatch marks into
                    # this request's timeline and close it at the SAME
                    # `now` the latency histogram used — stage sums
                    # telescope to the observed end-to-end latency.
                    plane.complete(request, now)
                    pending.discard(request)
            except BaseException:
                for request in list(pending):
                    drop(request)
                raise
            finally:
                # The router's claim marks are per-step state; clear
                # them even when the step dies mid-way so a failed
                # step's marks never leak into the next one.
                plane.end_step()
            report.update(
                requests=drained,
                claims=len(feeds),
                served=fabric_report["served"],
                skipped=fabric_report["skipped"],
                dropped=dropped,
                latencies_s=latencies,
            )
            # One step event (counts only — deterministic under virtual
            # clocks; latencies live in the histogram, not the journal).
            self._journal.emit(
                "serving.step",
                step=self.steps,
                requests=drained,
                claims=len(feeds),
                served=len(fabric_report["served"]),
            )
            # Burn-rate fold: the gauges admission reads next submit.
            self._evaluator.evaluate()
        return report

    def _purge_removed_claims(self) -> int:
        """Queues whose claim has left the fabric (``remove_claim``
        after requests were admitted): purge and account every stranded
        request as dropped.  The batcher's round-robin only visits live
        claims, so without this sweep the requests would sit queued
        forever while ``admission_sample`` (utils/slo.py) reads them as
        served and ``/api/state`` shows a ghost queue."""
        live = set(self.multi.claim_ids())
        n = 0
        for cid in [c for c in self.frontend.depths() if c not in live]:
            for request in self.frontend.purge(cid):
                self._metrics.counter(
                    "serving_dropped", labels={"claim": request.claim}
                ).add(1)
                n += 1
        return n

    # -- graceful drain (docs/RESILIENCE.md §drain) --------------------------

    def drain(self, max_steps: int = 16) -> Dict[str, Any]:
        """Stop admission and flush: new submissions shed with
        ``reason="draining"`` (typed ``serving.shed`` events); up to
        ``max_steps`` serving cycles run the already-admitted queues
        through the fabric; whatever still cannot complete (a paused
        claim, a failing fetch) is purged and journaled per-request as
        ``serving.deferred{reason="draining"}`` — every admitted
        request ends the drain either ANSWERED or DEFERRED, never
        silently lost.  Idempotent; returns the accounting."""
        self.frontend.set_draining(True)
        deferred = 0

        def defer(request) -> None:
            nonlocal deferred
            self._metrics.counter(
                "serving_dropped", labels={"claim": request.claim}
            ).add(1)
            self._journal.emit(
                "serving.deferred",
                lineage=request.lineage,
                claim=request.claim,
                seq=request.seq,
                reason="draining",
            )
            deferred += 1

        # Paused claims first: the flush loop cannot serve them (the
        # router skips paused claims), and letting the batcher drain
        # their queues into a step would silently drop them instead of
        # journaling the deferral.
        for state in self.multi.registry.states():
            if state.paused:
                for request in self.frontend.purge(state.spec.claim_id):
                    defer(request)
        flushed_steps = 0
        while flushed_steps < max_steps and any(
            self.frontend.depths().values()
        ):
            self.step()
            flushed_steps += 1
        for cid in list(self.frontend.depths()):
            for request in self.frontend.purge(cid):
                defer(request)
        return {
            "flush_steps": flushed_steps,
            "deferred": deferred,
            "queues_empty": not any(self.frontend.depths().values()),
        }

    def serving_state_dict(self) -> Dict[str, Any]:
        """The tier's durable slice (queued requests + seq cursors +
        the step cursor) — embedded in the recovery manager's
        snapshot."""
        return {"steps": self.steps, **self.frontend.state_dict()}

    def restore_serving_state(self, state: Dict[str, Any]) -> int:
        self.steps = max(self.steps, int(state.get("steps", 0)))
        return self.frontend.restore_state(state)

    # -- background loop (live deployments) ---------------------------------

    def run_loop(self, period_s: float = 0.05) -> threading.Event:
        """Drive ``step()`` on a daemon thread every ``period_s``;
        returns the stop event.  Idempotent: a live loop is reused.

        This is the live deployment's entry point, so it ACTIVATES the
        committed compile-plane routing (docs/PARALLELISM.md
        §compile-plane): under ``warmup_mode="prewarm"`` the AOT walk
        starts in the background before the first tick — cold shapes
        defer instead of compiling inline — exactly like
        ``commit_mode`` activates at Session construction (the PR 13
        precedent).  A scenario/test driving ``step()`` directly stays
        warmup-free, as before."""
        if self._loop_thread is not None and self._loop_thread.is_alive():
            return self._loop_stop
        if self.multi.router.warmup_mode == "prewarm":
            # Unconditional (not gated on an existing worker): after a
            # primary-only recovery walk the SAME worker must run a
            # background pass that picks up the restart-insurance twin
            # variants — warmed keys are skipped, so a fully-warm
            # universe makes this a fast no-op walk.  start() is
            # idempotent while a walk is live.
            self.multi.start_prewarm(background=True)
        stop = threading.Event()

        def loop():
            while not stop.is_set():
                try:
                    self.step()
                except Exception:
                    # A serving-cycle defect must not kill the loop —
                    # per-claim failures are already isolated below;
                    # this catches tier-level bugs and counts them.
                    self._metrics.counter("serving_step_errors").add(1)
                stop.wait(period_s)

        self._loop_stop = stop  # svoc: volatile(thread handle; the serving loop is restarted explicitly after recovery)
        self._loop_thread = threading.Thread(target=loop, daemon=True)  # svoc: volatile(thread handle; see _loop_stop)
        self._loop_thread.start()
        return stop

    def stop_loop(self) -> None:
        if self._loop_stop is not None:
            self._loop_stop.set()

    # -- views ---------------------------------------------------------------

    def slo_snapshot(self) -> Dict[str, Any]:
        """Evaluate the serving SLOs (request_latency / admission)."""
        return self._evaluator.evaluate()

    def snapshot(self) -> Dict[str, Any]:
        """The ``/api/state`` serving section / console ``serving``
        payload: queues, admission counts, cache, throughput."""
        reg = self._metrics
        return {
            "steps": self.steps,
            # The claim-cube execution strategy serving this tier's
            # consensus dispatches (docs/FABRIC.md §consensus_impl) —
            # surfaced so an operator can tell a pallas-routed box from
            # an XLA one without reading PERF_DECISIONS.json.
            "consensus_impl": self.multi.router.consensus_impl,
            # The pinned (claim × oracle) dispatch mesh, or None for
            # the single-device path (docs/FABRIC.md §mesh) — same
            # replay-pinning contract as the impl above.
            "mesh": self.multi.router.mesh_spec,
            # Compile plane (docs/PARALLELISM.md §compile-plane): the
            # pinned warmup routing, the live prewarm walk, and the
            # cold-shape deferral count — an operator can tell a tier
            # still compiling its universe from a saturated one.
            "warmup_mode": self.multi.router.warmup_mode,
            "prewarm": (
                self.prewarmer.stats()
                if self.prewarmer is not None
                else None
            ),
            "deferred": reg.family_total("serving_deferred"),
            "queues": self.frontend.depths(),
            "submitted": reg.family_total("serving_submitted"),
            "admitted": reg.family_total("serving_admitted"),
            "cached": reg.family_total("serving_cached"),
            "shed": reg.family_total("serving_shed"),
            "completed": reg.family_total("serving_completed"),
            "dropped": reg.family_total("serving_dropped"),
            "cache": self.cache.stats(),
            "burn_rate": self.frontend.controller.burn_rate(),
            "latency": reg.histogram(REQUEST_LATENCY_HISTOGRAM).snapshot(),
            # Cost-attribution plane (docs/OBSERVABILITY.md
            # §cost-attribution): the shape-keyed dispatch-cost ledger
            # summary + cells the console `costs` command renders.
            "costs": self.cost_plane.snapshot(),
        }

    def attach(self, console) -> None:
        """Expose the tier through a
        :class:`~svoc_tpu.apps.commands.CommandConsole`: the ``serving``
        command, ``POST /api/submit``, and ``/api/state``'s serving
        section read it."""
        console.serving = self
