"""Dedup/result cache: content-keyed LRU over sentiment vectors.

Serving traffic repeats — the same viral comment is submitted against
the same claim thousands of times — and the expensive stages
(tokenize → pack → forward) are pure functions of the text.  This cache
keys on ``(claim, comment-content-hash)`` so a repeat skips the whole
model path and is answered at submit time, before it ever occupies a
queue slot or a packed segment (docs/SERVING.md §cache).

Semantics:

- **content-keyed**: the key digests the claim id and the raw comment
  text; two claims submitting the same text do NOT share an entry (the
  response also carries the claim's consensus, and an eviction in one
  claim must not dent another's hit rate).
- **bounded LRU**: ``capacity`` entries, least-recently-*used* evicted
  (a hit refreshes recency), so a hot comment survives a flood of
  one-off texts.
- **observable**: every lookup and eviction lands in the
  ``serving_cache{event=hit|miss|evict}`` counters the SLO/console/
  bench surfaces read — the hit rate is a first-class serving metric.

Thread-safe: the web handler's submit path and the batcher's fill path
touch it concurrently.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, Optional

import numpy as np

from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry


def text_digest(text: str) -> str:
    """sha256 of the raw comment text — computed ONCE per request at
    admission (docs/SERVING.md §hash-once) and threaded through every
    consumer: the cache key derives from it, the batcher's in-batch
    dedup compares it, and the audit trail can carry it without ever
    re-reading the text.  This is the only place serving hashes
    variable-length content; everything downstream hashes (or
    compares) the fixed-size digest."""
    return hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()


def content_key_from_digest(claim_id: str, digest: str) -> str:
    """The cache key for a text whose :func:`text_digest` is already
    known — the submit path computes the digest once and derives the
    key from it (hashing a fixed 64-char digest, never the text
    again).  Keys stay claim-scoped: two claims submitting the same
    text do NOT share an entry."""
    return hashlib.sha256(
        f"{claim_id}\x00{digest}".encode()
    ).hexdigest()[:24]


def content_key(claim_id: str, text: str) -> str:
    """The cache key: a stable digest of ``(claim, comment text)``.
    Hash-based (not the raw text) so keys are fixed-size and never leak
    comment content into metrics labels or logs.  One-shot convenience
    over :func:`text_digest` + :func:`content_key_from_digest` — hot
    paths that already hold the digest use the latter directly."""
    return content_key_from_digest(claim_id, text_digest(text))


class ResultCache:
    """Bounded LRU of ``key → [M] sentiment vector``."""

    def __init__(
        self,
        capacity: int = 4096,
        *,
        metrics: Optional[MetricsRegistry] = None,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._metrics = metrics or _default_registry
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, np.ndarray]" = OrderedDict()

    def _count(self, event: str) -> None:
        self._metrics.counter(
            "serving_cache", labels={"event": event}
        ).add(1)

    def get(self, key: str) -> Optional[np.ndarray]:
        """The cached vector (a copy — callers mutate responses), or
        None.  Counts one hit or miss per lookup."""
        with self._lock:
            vec = self._entries.get(key)
            if vec is not None:
                self._entries.move_to_end(key)
        self._count("hit" if vec is not None else "miss")
        return None if vec is None else vec.copy()

    def put(self, key: str, vector: np.ndarray) -> None:
        """Insert/refresh an entry, evicting the least-recently-used
        one when full.  Idempotent on repeats (the batcher computes a
        duplicate submitted twice before its first completion)."""
        vec = np.asarray(vector, dtype=np.float64).copy()
        evicted = False
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self._entries[key] = vec
            else:
                if len(self._entries) >= self.capacity:
                    self._entries.popitem(last=False)
                    evicted = True
                self._entries[key] = vec
        if evicted:
            self._count("evict")

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._entries

    def stats(self) -> Dict[str, float]:
        """Size + the registry's cumulative hit/miss/evict counts — the
        console ``serving`` command / ``/api/state`` payload."""
        counts = {
            event: self._metrics.counter(
                "serving_cache", labels={"event": event}
            ).count
            for event in ("hit", "miss", "evict")
        }
        lookups = counts["hit"] + counts["miss"]
        return {
            "size": len(self),
            "capacity": self.capacity,
            "hits": counts["hit"],
            "misses": counts["miss"],
            "evictions": counts["evict"],
            "hit_rate": round(counts["hit"] / lookups, 6) if lookups else 0.0,
        }
