"""Seeded serving scenario: the ``make serving-smoke`` gate.

A deterministic, virtual-time micro-load through the whole serving
tier: open-loop arrivals over N claims sweep through three phases —
**warm** (under capacity: shed rate must be ~0), **overload** (arrivals
far above the per-step batch budget: queues hit their bounds, queued
requests blow the latency target, the ``request_latency`` burn gauge
crosses the admission threshold, and the tier MUST shed), and
**recovery** (load drops back; queues drain).  A seeded fraction of
arrivals repeat comments from a small hot pool, so the dedup cache
serves real hits mid-overload (the degrade-to-cached path).

Everything is a pure function of ``seed``: arrivals key off
:func:`svoc_tpu.sim.generators.claim_seed`, the vectorizer is the
fabric scenario's deterministic crc-of-text map, time is a virtual
clock the scenario advances itself (latencies, burn-rate windows, and
therefore every shed decision are clock-exact), and the run gets a
FRESH journal + FRESH metrics registry + pinned lineage scope — the
PR 6 replay-pinning rules (docs/SERVING.md §replay).
``tools/serving_smoke.py`` runs it twice and asserts byte-identical
journal fingerprints, shed > 0 only under overload, cache hits > 0,
and a reported p99.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from svoc_tpu.fabric.registry import ClaimSpec
from svoc_tpu.fabric.scenario import _claim_names, deterministic_vectorizer
from svoc_tpu.sim.generators import claim_seed

#: (arrivals per step, steps) per phase: warm / overload / recovery.
DEFAULT_PHASES: Tuple[Tuple[int, int], ...] = ((6, 8), (60, 10), (6, 8))


class VirtualClock:
    """A monotonic clock the scenario advances explicitly — latencies
    and SLO windows become pure functions of the step count."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def advance(self, dt: float) -> float:
        self.now += float(dt)
        return self.now

    def __call__(self) -> float:
        return self.now


def draw_arrival(rng, names, pool, hot_fraction, unique_text):
    """One seeded open-loop arrival: ``(claim, text)`` with the claim
    drawn uniformly and the text either a hot-pool repeat (the dedup
    cache's workload) or ``unique_text(claim)``.  Shared by this
    scenario and ``bench_serving.py`` so the smoke gate and the bench
    artifact can never drift apart on arrival keying; the draw order
    (claim, hot-vs-unique, pool index) is part of every seeded serving
    fingerprint."""
    claim = names[int(rng.integers(0, len(names)))]
    if rng.random() < hot_fraction:
        return claim, pool[int(rng.integers(0, len(pool)))]
    return claim, unique_text(claim)


def shed_by_reason(metrics) -> Dict[str, float]:
    """Per-reason shed totals with claims folded — the reporting shape
    both the scenario result and the bench artifact carry."""
    out: Dict[str, float] = {}
    for labels, count in metrics.family_series("serving_shed"):
        reason = labels.get("reason", "")
        out[reason] = out.get(reason, 0.0) + count
    return out


def run_serving_scenario(
    seed: int = 0,
    *,
    phases: Tuple[Tuple[int, int], ...] = DEFAULT_PHASES,
    n_claims: int = 3,
    n_oracles: int = 7,
    dimension: int = 6,
    step_period_s: float = 0.1,
    max_requests_per_step: int = 16,
    queue_capacity: int = 48,
    hot_pool: int = 10,
    hot_fraction: float = 0.35,
    journal=None,
    metrics=None,
    cost_plane: Optional[str] = None,
    cost_trace_path: Optional[str] = None,
) -> Dict[str, Any]:
    """One seeded serving run; returns the journal fingerprint,
    per-phase shed accounting, cache stats, and latency percentiles.

    ``cost_plane`` pins the cost-attribution plane explicitly for this
    run: ``"on"`` / ``"off"`` build a plane on the scenario's virtual
    clock + fresh metrics (``make obs-cost-smoke`` runs both and
    asserts fingerprint identity); None inherits the tier's default
    resolution (env > PERF_DECISIONS.json).  ``cost_trace_path``
    optionally streams the plane's observation records to JSONL for
    ``tools/obs_query.py``."""
    from svoc_tpu.fabric.session import MultiSession
    from svoc_tpu.obsplane.plane import CostPlane
    from svoc_tpu.serving.frontend import AdmissionConfig
    from svoc_tpu.serving.tier import ServingTier
    from svoc_tpu.utils.events import EventJournal
    from svoc_tpu.utils.metrics import MetricsRegistry
    from svoc_tpu.utils.slo import REQUEST_LATENCY_HISTOGRAM, serving_slos

    journal = journal if journal is not None else EventJournal()
    metrics = metrics if metrics is not None else MetricsRegistry()
    clock = VirtualClock()
    names = _claim_names(n_claims)

    multi = MultiSession(
        base_seed=seed,
        vectorizer=deterministic_vectorizer,
        journal=journal,
        metrics=metrics,
        lineage_scope="srv",
        # Serving mode: gate + consensus fused in one traced program
        # per micro-batch (docs/SERVING.md §consensus).
        sanitized_dispatch=True,
        # The per-claim SLO evaluators must share the scenario's
        # virtual clock: their latched slo.alert events land in the
        # fingerprinted journal, and wall-clock burn windows would let
        # two identical runs alert differently on a loaded host.
        clock=clock,
    )
    for name in names:
        multi.add_claim(
            ClaimSpec(
                claim_id=name, n_oracles=n_oracles, dimension=dimension
            )
        )
    plane = (
        CostPlane(
            enabled=(cost_plane == "on"),
            clock=clock,
            metrics=metrics,
            trace_path=cost_trace_path,
        )
        if cost_plane is not None
        else None
    )
    tier = ServingTier(
        multi,
        vectorizer=deterministic_vectorizer,
        cost_plane=plane,
        admission=AdmissionConfig(
            queue_capacity=queue_capacity, burn_threshold=4.0, seed=seed
        ),
        max_requests_per_step=max_requests_per_step,
        clock=clock,
        # Short SLO windows so the burn reacts within the (virtual-
        # seconds) run; the latency target makes a ≥3-step queue wait a
        # bad request.
        slos=serving_slos(
            metrics,
            latency_target_s=2.5 * step_period_s,
            fast_window_s=10 * step_period_s,
            slow_window_s=50 * step_period_s,
        ),
    )

    rng = np.random.default_rng(claim_seed(seed, "serving_arrivals"))
    pool = [f"hot comment {i} — every market has a viral take" for i in range(hot_pool)]
    phase_stats: List[Dict[str, Any]] = []
    step_no = 0
    for phase_idx, (per_step, steps) in enumerate(phases):
        shed_before = metrics.family_total("serving_shed")
        hits_before = metrics.counter(
            "serving_cache", labels={"event": "hit"}
        ).count
        submitted = 0
        for _ in range(steps):
            clock.advance(step_period_s)
            for i in range(per_step):
                claim, text = draw_arrival(
                    rng,
                    names,
                    pool,
                    hot_fraction,
                    lambda c: f"unique comment {c} step {step_no} #{i}",
                )
                tier.submit(claim, text)
                submitted += 1
            tier.step()
            step_no += 1
        phase_stats.append(
            {
                "phase": phase_idx,
                "arrivals_per_step": per_step,
                "steps": steps,
                "submitted": submitted,
                "shed": metrics.family_total("serving_shed") - shed_before,
                "cache_hits": metrics.counter(
                    "serving_cache", labels={"event": "hit"}
                ).count
                - hits_before,
            }
        )

    latency = metrics.histogram(REQUEST_LATENCY_HISTOGRAM).snapshot()
    reason_totals = shed_by_reason(metrics)

    return {
        "seed": seed,
        "claims": names,
        "steps": step_no,
        "phases": phase_stats,
        "submitted": metrics.family_total("serving_submitted"),
        "admitted": metrics.family_total("serving_admitted"),
        "cached": metrics.family_total("serving_cached"),
        "shed": metrics.family_total("serving_shed"),
        "completed": metrics.family_total("serving_completed"),
        "shed_by_reason": dict(sorted(reason_totals.items())),
        "cache": tier.cache.stats(),
        "latency": latency,
        "snapshot": tier.snapshot(),
        "journal_fingerprint": journal.fingerprint(),
        "journal_events": journal.last_seq(),
        "per_claim_fingerprints": {
            name: multi.claim_fingerprint(name) for name in names
        },
        # The live plane object (not just its snapshot): the obs smoke
        # inspects timelines/ledger/model directly after the run.
        "cost_plane": tier.cost_plane,
        # The live session: the obs smoke enumerates the router's
        # compile universe to assert ledger estimate coverage.
        "multi": multi,
    }
