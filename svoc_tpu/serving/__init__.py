"""Continuous-batching serving tier (docs/SERVING.md).

The request path in front of the multi-claim fabric: async ingestion
with SLO-driven admission control (:mod:`svoc_tpu.serving.frontend`),
cross-claim micro-batch assembly into the packed forward and the fused
claim-cube consensus (:mod:`svoc_tpu.serving.batcher`), a content-keyed
dedup/result cache (:mod:`svoc_tpu.serving.cache`), the
:class:`~svoc_tpu.serving.tier.ServingTier` facade, and the seeded
virtual-time scenario behind ``make serving-smoke``
(:mod:`svoc_tpu.serving.scenario`).
"""

from svoc_tpu.serving.batcher import MicroBatcher
from svoc_tpu.serving.cache import (
    ResultCache,
    content_key,
    content_key_from_digest,
    text_digest,
)
from svoc_tpu.serving.frontend import (
    AdmissionConfig,
    AdmissionController,
    AdmissionDecision,
    ServingFrontend,
    ServingRequest,
)
from svoc_tpu.serving.tier import ServingTier

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "AdmissionDecision",
    "MicroBatcher",
    "ResultCache",
    "ServingFrontend",
    "ServingRequest",
    "ServingTier",
    "content_key",
    "content_key_from_digest",
    "text_digest",
]
