"""Dynamic micro-batch assembly: many claims, one device dispatch.

The serving hot path has two batchable axes and this module fills both
(docs/SERVING.md §batcher):

- **the forward's segment axis** — pending requests from EVERY claim
  are tokenized and packed together through the segment-packed flash
  forward (:meth:`svoc_tpu.models.sentiment.SentimentPipeline.
  call_packed`).  BENCH_r05's store-driven windows average
  packing_factor 3.03 against ``max_segments=8``; cross-claim assembly
  exists to fill that idle headroom — short comments from four markets
  pack the rows a single market leaves ~60 % empty.  The pack path's
  ``packing_fill_ratio{kind=}`` gauges make the claim checkable.
- **the consensus' claim axis** — the per-claim vector groups feed the
  request-driven fabric cycle, whose consensus runs as ONE fused
  gate+kernel claim-cube dispatch
  (:func:`svoc_tpu.consensus.batch.claims_consensus_sanitized`, the
  router's ``sanitized_dispatch`` mode), pow2-bucketed so the compile
  count stays bounded (SVOC003 discipline).

Assembly order is a deterministic round-robin over claims in
registration order, one request per claim per round — fair across
claims (a deep queue cannot monopolize a batch) and replayable (the
assembly order is part of the seeded serving fingerprint).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from svoc_tpu.serving.frontend import ServingFrontend, ServingRequest
from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry


class MicroBatcher:
    """Assembles one micro-batch per serving step and runs the shared
    cross-claim vectorize."""

    def __init__(
        self,
        frontend: ServingFrontend,
        vectorizer,
        *,
        max_requests: int = 64,
        max_segments: int = 8,
        metrics: Optional[MetricsRegistry] = None,
    ):
        if max_requests < 1:
            raise ValueError("max_requests must be >= 1")
        self.frontend = frontend
        self.vectorizer = vectorizer
        self.max_requests = max_requests
        self.max_segments = max_segments
        self._metrics = metrics or _default_registry

    def assemble(self) -> List[ServingRequest]:
        """Drain up to ``max_requests`` pending requests, round-robin
        one-per-claim over the registry's registration order.  Claims
        whose consensus shape is still compiling are SKIPPED — their
        deferred requests stay queued (docs/SERVING.md §cold-start)
        rather than dragging a whole cross-claim micro-batch into an
        inline compile; the next assemble after the prewarmer reaches
        their shape drains them normally."""
        picked: List[ServingRequest] = []
        order = [
            cid
            for cid in self.frontend.multi.claim_ids()
            if self.frontend.depth(cid) > 0
            and not self.frontend.is_cold(cid)
        ]
        while order and len(picked) < self.max_requests:
            still_pending: List[str] = []
            for cid in order:
                if len(picked) >= self.max_requests:
                    break
                got = self.frontend.drain(cid, 1)
                if got:
                    picked.append(got[0])
                    if self.frontend.depth(cid) > 0:
                        still_pending.append(cid)
            order = still_pending
        if picked:
            self._metrics.counter("serving_batches").add(1)
            self._metrics.gauge("serving_batch_requests").set(len(picked))
            self._metrics.gauge("serving_batch_claims").set(
                len({r.claim for r in picked})
            )
        return picked

    def vectorize_requests(
        self, requests: Sequence[ServingRequest]
    ) -> np.ndarray:
        """``[K, M]`` sentiment vectors for one micro-batch,
        deduplicated on each request's ADMISSION-TIME content digest
        (docs/SERVING.md §hash-once): a hot comment submitted to
        several claims before its first completion — the dedup cache
        only helps ACROSS steps — is forwarded once and fanned back
        out, and the dedup key is the sha256 the frontend already
        computed, so no byte of text is hashed (or dict-keyed) a
        second time on the hot path."""
        seen: Dict[str, int] = {}
        texts: List[str] = []
        for request in requests:
            if request.digest not in seen:
                seen[request.digest] = len(texts)
                texts.append(request.text)
        vectors = self._vectorize_unique(texts)
        if len(texts) == len(requests):
            return vectors
        return vectors[[seen[r.digest] for r in requests]]

    def vectorize(self, texts: Sequence[str]) -> np.ndarray:
        """Texts → ``[K, M]`` sentiment vectors through the packed
        cross-claim forward when the vectorizer is a
        ``SentimentPipeline`` (its pack stage exports the fill-ratio
        gauges), plain call otherwise (injected test/scenario
        vectorizers).

        Duplicate texts within one micro-batch are forwarded once and
        fanned back out.  Raw-text convenience twin of
        :meth:`vectorize_requests` (which dedups on the admission-time
        digest instead of re-keying the full text)."""
        texts = list(texts)
        unique = list(dict.fromkeys(texts))
        vectors = self._vectorize_unique(unique)
        if len(unique) == len(texts):
            return vectors
        index = {text: i for i, text in enumerate(unique)}
        return vectors[[index[text] for text in texts]]

    def _vectorize_unique(self, texts: List[str]) -> np.ndarray:
        call_packed = getattr(self.vectorizer, "call_packed", None)
        if call_packed is not None:
            return np.asarray(
                call_packed(list(texts), self.max_segments), dtype=np.float64
            )
        return np.asarray(self.vectorizer(list(texts)), dtype=np.float64)

    @staticmethod
    def group_by_claim(
        requests: Sequence[ServingRequest],
    ) -> Dict[str, np.ndarray]:
        """The request-driven feed map: per-claim ``[K, M]`` vector
        stacks in request order (every request must already carry its
        vector)."""
        grouped: Dict[str, List[np.ndarray]] = {}
        for request in requests:
            if request.vector is None:
                raise ValueError(
                    f"request {request.request_id} has no vector — "
                    "vectorize before grouping"
                )
            grouped.setdefault(request.claim, []).append(request.vector)
        return {
            cid: np.stack(vectors).astype(np.float32)
            for cid, vectors in grouped.items()
        }
