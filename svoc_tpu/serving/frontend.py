"""Async ingestion front: bounded per-claim queues + admission control.

The fabric of PR 6 *pulls* work on its own cadence; production traffic
*pushes*.  This module is the push boundary (ROADMAP item 2, following
G-Core's balanced trainer/server split): every submitted request lands
in its claim's bounded queue — or is **shed before it costs anything**,
because overload handled at the door is cheap and overload handled at
the p99 tail burns the commit objective.

Admission is layered, cheapest check first (docs/SERVING.md §admission):

1. **Cache** — a ``(claim, comment-hash)`` hit is answered immediately
   from :class:`~svoc_tpu.serving.cache.ResultCache` with the claim's
   latest consensus attached; it never occupies a queue slot.  This is
   also the degraded-mode path: while the tier is shedding, repeats
   still get real answers.
2. **Queue bound** — a full claim queue sheds with ``reason=
   "queue_full"``.  Bounds are per claim, so one flooded market never
   starves a sibling's slots (the PR 6 isolation contract extended to
   the request path).
3. **SLO burn** — the controller reads the live
   ``slo_burn_rate{slo="request_latency", window="fast"}`` gauge the
   PR 5 evaluator maintains; above the threshold it sheds a configured
   fraction of cache-miss traffic with ``reason="slo_burn"`` — load
   drops *before* the 99 % objective's budget is gone.

Every decision is **deterministic and seeded**: the burn-mode shed draw
is a crc32 of ``(seed, claim, request seq)`` — the fault-plan
discipline of PRs 3–4 — so a seeded serving replay reproduces the exact
shed sequence byte-for-byte (``make serving-smoke``).  Admitted and
shed requests both emit typed journal events (``serving.admitted`` /
``serving.shed``) carrying a block-lineage id inside the claim's
lineage family (``blk<scope>-<claim>-rq<seq>``), so the flight recorder
partitions serving traffic per claim exactly like consensus blocks.
"""

from __future__ import annotations

import dataclasses
import threading
import zlib
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from svoc_tpu.serving.cache import (
    ResultCache,
    content_key_from_digest,
    text_digest,
)
from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry
from svoc_tpu.utils.rounding import round6_list


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """The admission policy's knobs.

    ``shed_fraction`` is the fraction of cache-miss traffic dropped
    while the burn gauge is above ``burn_threshold`` (1.0 = full brownout
    of misses; 0.5 = shed every other request, selected by the seeded
    draw).  ``seed`` keys the draw — replays of one seed shed the same
    requests.
    """

    queue_capacity: int = 64
    burn_slo: str = "request_latency"
    burn_window: str = "fast"
    #: Fast-window burn rate above which misses shed.  The default sits
    #: well under the 14.4× page threshold: shedding is the *remedy*
    #: that should prevent the page, not follow it.
    burn_threshold: float = 4.0
    shed_fraction: float = 1.0
    seed: int = 0

    def __post_init__(self):
        if self.queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")
        if not 0.0 <= self.shed_fraction <= 1.0:
            raise ValueError("shed_fraction must be in [0, 1]")
        if self.burn_threshold <= 0.0:
            raise ValueError("burn_threshold must be > 0")


@dataclasses.dataclass(frozen=True)
class AdmissionDecision:
    action: str  # "admit" | "shed"
    reason: str = ""


class AdmissionController:
    """Deterministic admit/shed policy over queue depth + burn gauges."""

    def __init__(
        self,
        config: Optional[AdmissionConfig] = None,
        *,
        metrics: Optional[MetricsRegistry] = None,
    ):
        self.config = config or AdmissionConfig()
        self._metrics = metrics or _default_registry

    def burn_rate(self) -> float:
        """The live fast-window burn of the configured SLO (0 until the
        evaluator's first pass — a cold tier admits everything)."""
        return self._metrics.gauge(
            "slo_burn_rate",
            labels={
                "slo": self.config.burn_slo,
                "window": self.config.burn_window,
            },
        ).get()

    def _shed_draw(self, claim_id: str, seq: int) -> float:
        """Uniform [0, 1) from a crc32 of (seed, claim, seq) — the
        fault-plan keying discipline: replayable across processes,
        decorrelated across claims and requests."""
        key = f"{self.config.seed}:{claim_id}:{seq}".encode()
        return zlib.crc32(key) / 2**32

    def decide(
        self, claim_id: str, queue_depth: int, seq: int
    ) -> AdmissionDecision:
        cfg = self.config
        if queue_depth >= cfg.queue_capacity:
            return AdmissionDecision("shed", "queue_full")
        if self.burn_rate() >= cfg.burn_threshold:
            if self._shed_draw(claim_id, seq) < cfg.shed_fraction:
                return AdmissionDecision("shed", "slo_burn")
        return AdmissionDecision("admit")


class ServingRequest:
    """One in-flight request: claim, text, content key, lineage, and
    the completion slots the batcher fills."""

    __slots__ = (
        "claim",
        "text",
        "seq",
        "request_id",
        "lineage",
        "digest",
        "key",
        "t_submit",
        "vector",
        "timeline",
    )

    def __init__(
        self,
        claim: str,
        text: str,
        seq: int,
        lineage: str,
        t_submit: float,
        key: Optional[str] = None,
        digest: Optional[str] = None,
    ):
        self.claim = claim
        self.text = text
        self.seq = seq
        self.request_id = f"{claim}:{seq}"
        self.lineage = lineage
        # Hash-once (docs/SERVING.md §hash-once): the submit path
        # hashed the text at admission; the digest rides the request so
        # the cache key, the batcher's in-batch dedup, and any audit
        # surface reuse it instead of re-hashing the text per consumer.
        self.digest = digest if digest is not None else text_digest(text)
        self.key = (
            key
            if key is not None
            else content_key_from_digest(claim, self.digest)
        )
        self.t_submit = t_submit
        self.vector: Optional[np.ndarray] = None
        #: Cost-attribution timeline (docs/OBSERVABILITY.md
        #: §cost-attribution) — attached at admission when the tier's
        #: cost plane is enabled, None otherwise (and always None for
        #: snapshot-restored requests: a timeline spanning a restart
        #: would mix two clocks).
        self.timeline = None


class ServingFrontend:
    """Per-claim bounded queues + the admission controller, over a
    :class:`~svoc_tpu.fabric.session.MultiSession`'s claims."""

    def __init__(
        self,
        multi,
        *,
        admission: Optional[AdmissionConfig] = None,
        cache: Optional[ResultCache] = None,
        metrics: Optional[MetricsRegistry] = None,
        journal=None,
        clock=None,
        cold_gate=None,
        cost_plane=None,
    ):
        import time

        from svoc_tpu.fabric.router import resolve_journal

        self.multi = multi
        self._metrics = metrics or _default_registry
        self._journal = resolve_journal(journal)
        self._clock = clock if clock is not None else time.monotonic
        self.cache = cache if cache is not None else ResultCache(
            metrics=self._metrics
        )
        self.controller = AdmissionController(admission, metrics=self._metrics)
        self._lock = threading.Lock()
        self._queues: Dict[str, deque] = {}
        self._seqs: Dict[str, int] = {}
        #: Graceful-drain latch (docs/RESILIENCE.md §drain): while set,
        #: every cache-miss submission sheds with ``reason="draining"``
        #: — admission stops at the door so the flush loop can empty
        #: the queues.  Cache hits still answer (they cost nothing and
        #: occupy no slot — the same degraded-mode contract as SLO-burn
        #: shedding).
        self._draining = False
        #: Cold-shape gate (docs/SERVING.md §cold-start):
        #: ``cold_gate(claim_id) -> bool`` says whether the claim's
        #: consensus program is STILL COMPILING (an AOT prewarm in
        #: flight that hasn't reached its shape yet).  A cold claim's
        #: cache-miss submissions are DEFERRED, not shed: admitted to
        #: the bounded queue (the queue-full bound still applies — a
        #: full queue sheds regardless) with a typed
        #: ``serving.deferred{reason="cold_shape"}`` event, and the
        #: batcher skips the claim until the gate opens — the request
        #: waits out the compile instead of either being dropped or
        #: blocking a whole serving step on an inline compile.  None
        #: (the default, and always once warmup finishes) defers
        #: nothing — the PR 7 admission path byte-for-byte.
        self._cold_gate = cold_gate
        #: Cost-attribution plane (docs/OBSERVABILITY.md
        #: §cost-attribution); None/disabled leaves the submit path —
        #: and its journal event stream — byte-identical.
        self._cost_plane = cost_plane

    # -- the submit path ----------------------------------------------------

    def submit(
        self, claim_id: str, text: str, state=None
    ) -> Dict[str, Any]:
        """One request through admission.  Returns the response dict
        the web/console surfaces serialize:

        - ``status="cached"`` — answered now, with the vector and the
          claim's latest consensus slice;
        - ``status="admitted"`` — queued for the next micro-batch;
        - ``status="deferred"`` — queued like an admission, but the
          claim's consensus program is still compiling
          (``reason="cold_shape"``, docs/SERVING.md §cold-start): the
          batcher will drain it once the shape is warm — NOT a
          rejection, HTTP 200;
        - ``status="shed"`` — rejected, with the reason (HTTP 429).

        Raises ``KeyError`` for an unknown claim (the HTTP layer maps
        it to 404 — an unknown market is a client error, not load).
        ``state`` lets the tier pass the claim state it already
        resolved for its membership check, saving a registry lookup on
        the hot path."""
        if state is None:
            state = self.multi.get(claim_id)  # KeyError → caller's 404
        prefix = state.session.lineage_prefix
        with self._lock:
            seq = self._seqs.get(claim_id, 0) + 1
            self._seqs[claim_id] = seq
        # Request lineage lives INSIDE the claim's lineage family
        # (``blk<scope>-<claim>-rq<seq>``): per-claim journal slices and
        # fingerprints cover serving traffic with no new partition key.
        lineage = f"{prefix}-rq{seq:06x}"
        # The ONE content hash per request (docs/SERVING.md
        # §hash-once): everything downstream — cache key, in-batch
        # dedup, lineage audit — derives from this digest.
        digest = text_digest(text)
        key = content_key_from_digest(claim_id, digest)
        cached = self.cache.get(key)
        if cached is not None:
            self._metrics.counter(
                "serving_cached", labels={"claim": claim_id}
            ).add(1)
            self._journal.emit(
                "serving.admitted",
                lineage=lineage,
                claim=claim_id,
                seq=seq,
                source="cache",
            )
            return {
                "status": "cached",
                "claim": claim_id,
                "request_id": f"{claim_id}:{seq}",
                "lineage": lineage,
                "vector": round6_list(cached),
                "consensus": state.last_consensus,
            }
        request = ServingRequest(
            claim_id, text, seq, lineage, self._clock(), key=key,
            digest=digest,
        )
        plane = self._cost_plane
        if plane is not None and plane.enabled:
            # The admission mark IS t_submit — queue wait starts here.
            request.timeline = plane.timeline_for(
                lineage, claim_id, request.t_submit
            )
        deferred = self.is_cold(claim_id)
        with self._lock:
            q = self._queues.setdefault(claim_id, deque())
            if self._draining:
                decision = AdmissionDecision("shed", "draining")
            else:
                decision = self.controller.decide(claim_id, len(q), seq)
            if decision.action == "admit":
                q.append(request)
                depth = len(q)
        if decision.action == "admit":
            self._metrics.counter(
                "serving_admitted", labels={"claim": claim_id}
            ).add(1)
            self._metrics.gauge(
                "serving_queue_depth", labels={"claim": claim_id}
            ).set(depth)
            # Emission OUTSIDE the frontend lock — the journal lock is
            # a leaf and subscribers may re-enter serving snapshots.
            self._journal.emit(
                "serving.admitted",
                lineage=lineage,
                claim=claim_id,
                seq=seq,
                source="queue",
            )
            if deferred:
                # Cold shape (docs/SERVING.md §cold-start): queued, but
                # the batcher will not drain this claim until its
                # program is compiled.  The ``serving.admitted`` event
                # above still fires (crash recovery accounts admitted
                # queue requests by it); the deferral is its own typed
                # event so the flight recorder shows WHY the request
                # waited.  Both are deterministic given a deterministic
                # warmup schedule (seeded smokes warm synchronously
                # first, so replays never see a deferral they can't
                # reproduce).
                self._metrics.counter(
                    "serving_deferred",
                    labels={"claim": claim_id, "reason": "cold_shape"},
                ).add(1)
                self._journal.emit(
                    "serving.deferred",
                    lineage=lineage,
                    claim=claim_id,
                    seq=seq,
                    reason="cold_shape",
                )
                return {
                    "status": "deferred",
                    "claim": claim_id,
                    "request_id": request.request_id,
                    "lineage": lineage,
                    "queue_depth": depth,
                    "reason": "cold_shape",
                }
            return {
                "status": "admitted",
                "claim": claim_id,
                "request_id": request.request_id,
                "lineage": lineage,
                "queue_depth": depth,
            }
        self._metrics.counter(
            "serving_shed",
            labels={"claim": claim_id, "reason": decision.reason},
        ).add(1)
        self._journal.emit(
            "serving.shed",
            lineage=lineage,
            claim=claim_id,
            seq=seq,
            reason=decision.reason,
        )
        if plane is not None and plane.enabled:
            # Admission-only timeline: the shed verdict is journaled
            # above; this observation-channel record keeps the lineage
            # joinable in the same timeline tooling (and fingerprints
            # never see it).
            plane.shed(lineage, claim_id, decision.reason)
        return {
            "status": "shed",
            "claim": claim_id,
            "request_id": request.request_id,
            "lineage": lineage,
            "reason": decision.reason,
        }

    def is_cold(self, claim_id: str) -> bool:
        """Whether the claim's consensus shape is still compiling (the
        cold-shape defer window).  False without a gate, and false for
        ANY gate failure — a broken warmth probe must degrade to the
        historical serve-now behavior, never to an eternal deferral."""
        gate = self._cold_gate
        if gate is None:
            return False
        try:
            return bool(gate(claim_id))
        except Exception:  # noqa: BLE001 — degrade open, count it
            self._metrics.counter("serving_cold_gate_errors").add(1)
            return False

    def set_draining(self, draining: bool = True) -> None:
        """Flip the drain latch (the SIGTERM handler's first act)."""
        with self._lock:
            self._draining = bool(draining)  # svoc: volatile(per-process drain latch; a restarted process starts undrained by definition)

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    # -- snapshot / restore (docs/RESILIENCE.md §durability) ---------------

    def state_dict(self) -> Dict[str, Any]:
        """Queued requests + per-claim seq cursors, JSON-safe.  Seqs
        MUST survive a restart: request lineage is minted from them,
        and a reset would re-mint already-published lineage ids."""
        with self._lock:
            return {
                "seqs": dict(self._seqs),
                "queues": {
                    cid: [
                        {
                            "text": r.text,
                            "seq": r.seq,
                            "lineage": r.lineage,
                            "t_submit": r.t_submit,
                        }
                        for r in q
                    ]
                    for cid, q in self._queues.items()
                    if q
                },
            }

    def restore_state(self, state: Dict[str, Any]) -> int:
        """Re-enqueue snapshotted requests and restore seq cursors
        (max-merged — never move a cursor backwards).  Returns the
        number of re-enqueued requests."""
        n = 0
        with self._lock:
            for cid, seq in (state.get("seqs") or {}).items():
                self._seqs[cid] = max(self._seqs.get(cid, 0), int(seq))
            for cid, entries in (state.get("queues") or {}).items():
                q = self._queues.setdefault(cid, deque())
                for e in entries:
                    q.append(
                        ServingRequest(
                            cid,
                            e["text"],
                            int(e["seq"]),
                            e["lineage"],
                            float(e.get("t_submit", 0.0)),
                        )
                    )
                    n += 1
                depth = len(q)
                self._metrics.gauge(
                    "serving_queue_depth", labels={"claim": cid}
                ).set(depth)
        return n

    # -- the batcher's side -------------------------------------------------

    def depth(self, claim_id: str) -> int:
        with self._lock:
            q = self._queues.get(claim_id)
            return len(q) if q else 0

    def depths(self) -> Dict[str, int]:
        with self._lock:
            return {cid: len(q) for cid, q in self._queues.items()}

    def purge(self, claim_id: str) -> List[ServingRequest]:
        """Drop a claim's queue outright (the claim left the fabric);
        returns the stranded requests so the caller can account every
        one as dropped — unaccounted strands would read as served in
        the admission SLO forever."""
        with self._lock:
            q = self._queues.pop(claim_id, None)
            out = list(q) if q else []
        if out:
            self._metrics.gauge(
                "serving_queue_depth", labels={"claim": claim_id}
            ).set(0)
        return out

    def drain(self, claim_id: str, limit: int) -> List[ServingRequest]:
        """Pop up to ``limit`` queued requests (FIFO) and refresh the
        depth gauge."""
        out: List[ServingRequest] = []
        with self._lock:
            q = self._queues.get(claim_id)
            if not q:
                return out
            while q and len(out) < limit:
                out.append(q.popleft())
            depth = len(q)
        if out:
            self._metrics.gauge(
                "serving_queue_depth", labels={"claim": claim_id}
            ).set(depth)
        return out
