// Native batch tokenizer: the hot host-side stage of the ingest
// pipeline (reference: the HF pipeline's Rust tokenizer inside
// client/oracle_scheduler.py:23-24; here the hashing scheme of
// svoc_tpu/models/tokenizer.py implemented for throughput).
//
// Semantics mirror HashingTokenizer exactly for ASCII text: lowercase,
// split on non-alphanumeric bytes, FNV-1a hash each word into
// [N_SPECIAL, vocab_size), wrap with bos/eos, pad to seq_len.
// Non-ASCII UTF-8 bytes are treated as word characters without case
// folding (Python's unicode isalnum()/lower() may differ there — the
// Python reference implementation remains the source of truth and the
// fallback).
//
// Exposed as a C ABI for ctypes; calls release the GIL on the Python
// side, so tokenization overlaps device compute in the input pipeline.

#include <cstddef>
#include <cstdint>

namespace {

constexpr uint64_t kFnvOffset = 14695981039346656037ULL;
constexpr uint64_t kFnvPrime = 1099511628211ULL;
constexpr int kNSpecial = 4;  // HashingTokenizer.N_SPECIAL

inline bool ascii_alnum(unsigned char c) {
  return (c >= '0' && c <= '9') || (c >= 'a' && c <= 'z') ||
         (c >= 'A' && c <= 'Z');
}

inline unsigned char ascii_lower(unsigned char c) {
  return (c >= 'A' && c <= 'Z') ? static_cast<unsigned char>(c + 32) : c;
}

}  // namespace

extern "C" {

// Tokenize one row; returns the number of ids written (<= seq_len).
// ids/mask point at the row's seq_len-sized slices.
static int tokenize_row(const char* text, int seq_len, int64_t vocab_size,
                        int32_t pad_id, int32_t bos_id, int32_t eos_id,
                        int32_t* ids, int32_t* mask) {
  for (int i = 0; i < seq_len; ++i) {
    ids[i] = pad_id;
    mask[i] = 0;
  }
  if (seq_len < 2) return 0;

  const int64_t span = vocab_size - kNSpecial;
  int out = 0;
  ids[out++] = bos_id;

  uint64_t h = kFnvOffset;
  bool in_word = false;
  const int max_words = seq_len - 2;
  int n_words = 0;
  for (const char* p = text; *p != '\0' && n_words < max_words; ++p) {
    const unsigned char c = static_cast<unsigned char>(*p);
    if (ascii_alnum(c) || c >= 0x80) {
      h = (h ^ ascii_lower(c)) * kFnvPrime;
      in_word = true;
    } else if (in_word) {
      ids[out++] = static_cast<int32_t>(kNSpecial + (h % span));
      ++n_words;
      h = kFnvOffset;
      in_word = false;
    }
  }
  if (in_word && n_words < max_words) {
    ids[out++] = static_cast<int32_t>(kNSpecial + (h % span));
  }
  ids[out++] = eos_id;
  for (int i = 0; i < out; ++i) mask[i] = 1;
  return out;
}

void svoc_tokenize_batch(const char** texts, int n_texts, int seq_len,
                         int64_t vocab_size, int32_t pad_id, int32_t bos_id,
                         int32_t eos_id, int32_t* ids, int32_t* mask) {
  for (int i = 0; i < n_texts; ++i) {
    tokenize_row(texts[i], seq_len, vocab_size, pad_id, bos_id, eos_id,
                 ids + static_cast<ptrdiff_t>(i) * seq_len,
                 mask + static_cast<ptrdiff_t>(i) * seq_len);
  }
}

}  // extern "C"
