"""Native (C++) runtime components, loaded via ctypes.

The compute path is JAX/XLA; this package holds the host-side native
pieces around it — currently the batch tokenizer feeding the input
pipeline (the stage the end-to-end benchmark is bound by).

The shared library is built on demand with ``g++ -O3`` into
``svoc_tpu/runtime/_build/`` and loaded with :mod:`ctypes`; every
consumer falls back to the pure-Python implementation when no compiler
is available, so the framework never hard-requires the native path.
"""

from svoc_tpu.runtime.native import (
    NativeHashingTokenizer,
    load_native_library,
    native_available,
    native_pack_tokens_raw,
)

__all__ = [
    "NativeHashingTokenizer",
    "load_native_library",
    "native_available",
    "native_pack_tokens_raw",
]
