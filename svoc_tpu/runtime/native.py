"""Build + ctypes bindings for the native runtime library.

``load_native_library()`` compiles ``tokenizer.cpp`` (and future
translation units) into ``_build/libsvoc_runtime.so`` the first time it
is called, memoizing the handle; failures (no compiler, read-only
checkout) degrade to ``None`` and the Python fallbacks take over.

:class:`NativeHashingTokenizer` is call-compatible with
:class:`svoc_tpu.models.tokenizer.HashingTokenizer` and bit-identical
on ASCII text (equality-tested in ``tests/test_runtime.py``); ctypes
releases the GIL during the batch call, so tokenization overlaps
device compute in the streaming pipeline.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional, Sequence, Tuple

import numpy as np

_SRC_DIR = Path(__file__).resolve().parent
_BUILD_DIR = _SRC_DIR / "_build"
_LIB_PATH = _BUILD_DIR / "libsvoc_runtime.so"
_SOURCES = ["tokenizer.cpp"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _compile() -> bool:
    try:
        srcs = [str(_SRC_DIR / s) for s in _SOURCES]
        newest_src = max(os.path.getmtime(s) for s in srcs)
        if _LIB_PATH.exists() and os.path.getmtime(_LIB_PATH) >= newest_src:
            return True
        _BUILD_DIR.mkdir(exist_ok=True)
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC",
             "-std=c++17", "-o", str(_LIB_PATH), *srcs],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        # No compiler / read-only checkout / missing sources: the
        # Python fallback takes over.
        return False


def load_native_library() -> Optional[ctypes.CDLL]:
    """Compile-on-demand + load; ``None`` when unavailable."""
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if not _compile():
            return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
            lib.svoc_tokenize_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),  # texts
                ctypes.c_int,  # n_texts
                ctypes.c_int,  # seq_len
                ctypes.c_int64,  # vocab_size
                ctypes.c_int32,  # pad_id
                ctypes.c_int32,  # bos_id
                ctypes.c_int32,  # eos_id
                ctypes.POINTER(ctypes.c_int32),  # ids out
                ctypes.POINTER(ctypes.c_int32),  # mask out
            ]
            lib.svoc_tokenize_batch.restype = None
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def native_available() -> bool:
    return load_native_library() is not None


class NativeHashingTokenizer:
    """Drop-in native replacement for ``HashingTokenizer``.

    Same special-id layout (pad/bos/eos among ids 0..3) and the same
    FNV-1a word hashing; raises :class:`RuntimeError` at construction
    when the native library cannot be built.
    """

    N_SPECIAL = 4

    def __init__(self, vocab_size: int, pad_id: int = 1, max_len: int = 512):
        lib = load_native_library()
        if lib is None:
            raise RuntimeError(
                "native runtime unavailable (no g++ or build failed) — "
                "use svoc_tpu.models.tokenizer.HashingTokenizer"
            )
        self._lib = lib
        self.vocab_size = vocab_size
        self.pad_id = pad_id
        self.max_len = max_len
        specials = list(range(self.N_SPECIAL))
        self.bos_id = next(i for i in specials if i != pad_id)
        self.eos_id = next(
            i for i in specials if i not in (pad_id, self.bos_id)
        )

    def __call__(
        self, texts: Sequence[str], seq_len: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        t = seq_len or self.max_len
        b = len(texts)
        ids = np.empty((b, t), dtype=np.int32)
        mask = np.empty((b, t), dtype=np.int32)
        encoded = [s.encode("utf-8") for s in texts]
        arr = (ctypes.c_char_p * b)(*encoded)
        self._lib.svoc_tokenize_batch(
            arr,
            b,
            t,
            self.vocab_size,
            self.pad_id,
            self.bos_id,
            self.eos_id,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return ids, mask
