"""Build + ctypes bindings for the native runtime library.

``load_native_library()`` compiles ``tokenizer.cpp`` (and future
translation units) into ``_build/libsvoc_runtime.so`` the first time it
is called, memoizing the handle; failures (no compiler, read-only
checkout) degrade to ``None`` and the Python fallbacks take over.

:class:`NativeHashingTokenizer` is call-compatible with
:class:`svoc_tpu.models.tokenizer.HashingTokenizer` and bit-identical
on ASCII text (equality-tested in ``tests/test_runtime.py``); ctypes
releases the GIL during the batch call, so tokenization overlaps
device compute in the streaming pipeline.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional, Sequence, Tuple

import numpy as np

_SRC_DIR = Path(__file__).resolve().parent
_BUILD_DIR = _SRC_DIR / "_build"
_LIB_PATH = _BUILD_DIR / "libsvoc_runtime.so"
_SOURCES = ["tokenizer.cpp", "packer.cpp"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _compile() -> bool:
    try:
        srcs = [str(_SRC_DIR / s) for s in _SOURCES]
        newest_src = max(os.path.getmtime(s) for s in srcs)
        if _LIB_PATH.exists() and os.path.getmtime(_LIB_PATH) >= newest_src:
            return True
        _BUILD_DIR.mkdir(exist_ok=True)
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC",
             "-std=c++17", "-o", str(_LIB_PATH), *srcs],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        # No compiler / read-only checkout / missing sources: the
        # Python fallback takes over.
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Declare the C signatures; raises AttributeError when the library
    is missing a symbol (a stale prebuilt .so)."""
    lib.svoc_tokenize_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),  # texts
        ctypes.c_int,  # n_texts
        ctypes.c_int,  # seq_len
        ctypes.c_int64,  # vocab_size
        ctypes.c_int32,  # pad_id
        ctypes.c_int32,  # bos_id
        ctypes.c_int32,  # eos_id
        ctypes.POINTER(ctypes.c_int32),  # ids out
        ctypes.POINTER(ctypes.c_int32),  # mask out
    ]
    lib.svoc_tokenize_batch.restype = None
    lib.svoc_pack_tokens.argtypes = [
        ctypes.POINTER(ctypes.c_int32),  # flat tokens
        ctypes.POINTER(ctypes.c_int64),  # offsets [n+1]
        ctypes.c_int,  # n_lists
        ctypes.c_int,  # seq_len
        ctypes.c_int,  # max_segments
        ctypes.c_int32,  # pad_id
        ctypes.c_int,  # rows_cap
        ctypes.POINTER(ctypes.c_int32),  # ids out
        ctypes.POINTER(ctypes.c_int32),  # pos out
        ctypes.POINTER(ctypes.c_int32),  # seg out
        ctypes.POINTER(ctypes.c_int32),  # cls_pos out
        ctypes.POINTER(ctypes.c_int32),  # seg_valid out
        ctypes.POINTER(ctypes.c_int32),  # owner out
        ctypes.POINTER(ctypes.c_int32),  # out counts [2]
    ]
    lib.svoc_pack_tokens.restype = None
    return lib


def load_native_library() -> Optional[ctypes.CDLL]:
    """Compile-on-demand + load; ``None`` when unavailable.

    A library that loads but is missing a symbol (stale prebuilt .so
    whose mtime outruns the sources — e.g. shipped by tar/docker with
    preserved times) is deleted and rebuilt ONCE, so one stale artifact
    cannot silently disable the whole native runtime."""
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if not _compile():
            return None
        try:
            _lib = _bind(ctypes.CDLL(str(_LIB_PATH)))
        except AttributeError:
            # Stale artifact missing a symbol: rebuild from sources and
            # load under an ALIAS path — glibc dlopen dedupes loaded
            # objects by pathname, so reloading _LIB_PATH would return
            # the stale handle.  The alias is unlinked immediately (the
            # mapping survives); fresh processes load the rebuilt
            # _LIB_PATH directly.
            import shutil

            _lib = None
            try:
                _LIB_PATH.unlink()
                if _compile():
                    alias = _BUILD_DIR / f"libsvoc_runtime.{os.getpid()}.so"
                    shutil.copy2(_LIB_PATH, alias)
                    try:
                        _lib = _bind(ctypes.CDLL(str(alias)))
                    finally:
                        alias.unlink(missing_ok=True)
            except (OSError, AttributeError):
                _lib = None
        except OSError:
            _lib = None
        return _lib


def native_available() -> bool:
    return load_native_library() is not None


def _int32_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def native_pack_tokens_raw(
    token_lists: Sequence[Sequence[int]],
    seq_len: int,
    max_segments: int,
    pad_id: int,
    rows: Optional[int] = None,
) -> Optional[tuple]:
    """C++ greedy next-fit packer (``packer.cpp``), GIL-free during the
    pack.  Returns raw numpy arrays ``(ids, pos, seg, cls_pos,
    seg_valid, owner, n_consumed)`` with semantics identical to
    :func:`svoc_tpu.models.packing.pack_tokens` (which wraps them into a
    ``PackedBatch``), or ``None`` when the native library is
    unavailable."""
    lib = load_native_library()
    if lib is None:
        return None
    if max_segments < 1:
        raise ValueError(f"max_segments must be >= 1, got {max_segments}")
    if rows is not None and rows < 1:
        raise ValueError(f"rows must be >= 1, got {rows}")
    n = len(token_lists)
    arrs = [np.asarray(t, dtype=np.int32) for t in token_lists]
    lengths = np.fromiter((a.size for a in arrs), dtype=np.int64, count=n)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lengths, out=offsets[1:])
    flat = (
        np.ascontiguousarray(np.concatenate(arrs), dtype=np.int32)
        if n and offsets[-1]
        else np.zeros(0, dtype=np.int32)
    )

    rows_cap = rows if rows is not None else max(1, n)
    t, s = seq_len, max_segments
    ids = np.full((rows_cap, t), pad_id, dtype=np.int32)
    pos = np.full((rows_cap, t), pad_id, dtype=np.int32)
    seg = np.zeros((rows_cap, t), dtype=np.int32)
    cls_pos = np.zeros((rows_cap, s), dtype=np.int32)
    seg_valid = np.zeros((rows_cap, s), dtype=np.int32)
    owner = np.full((rows_cap, s), -1, dtype=np.int32)
    counts = np.zeros(2, dtype=np.int32)
    lib.svoc_pack_tokens(
        _int32_ptr(flat),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        t,
        s,
        pad_id,
        rows_cap,
        _int32_ptr(ids),
        _int32_ptr(pos),
        _int32_ptr(seg),
        _int32_ptr(cls_pos),
        _int32_ptr(seg_valid),
        _int32_ptr(owner),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rows is None:
        # copy() the trims: a bare slice is a view keeping the whole
        # worst-case [n, T] allocation alive for the batch's lifetime.
        used = max(1, int(counts[0]))
        ids, pos, seg = ids[:used].copy(), pos[:used].copy(), seg[:used].copy()
        cls_pos, seg_valid, owner = (
            cls_pos[:used].copy(),
            seg_valid[:used].copy(),
            owner[:used].copy(),
        )
    return ids, pos, seg, cls_pos, seg_valid, owner, int(counts[1])


class NativeHashingTokenizer:
    """Drop-in native replacement for ``HashingTokenizer``.

    Same special-id layout (pad/bos/eos among ids 0..3) and the same
    FNV-1a word hashing; raises :class:`RuntimeError` at construction
    when the native library cannot be built.
    """

    N_SPECIAL = 4

    def __init__(self, vocab_size: int, pad_id: int = 1, max_len: int = 512):
        lib = load_native_library()
        if lib is None:
            raise RuntimeError(
                "native runtime unavailable (no g++ or build failed) — "
                "use svoc_tpu.models.tokenizer.HashingTokenizer"
            )
        self._lib = lib
        self.vocab_size = vocab_size
        self.pad_id = pad_id
        self.max_len = max_len
        specials = list(range(self.N_SPECIAL))
        self.bos_id = next(i for i in specials if i != pad_id)
        self.eos_id = next(
            i for i in specials if i not in (pad_id, self.bos_id)
        )

    def __call__(
        self, texts: Sequence[str], seq_len: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        t = seq_len or self.max_len
        b = len(texts)
        ids = np.empty((b, t), dtype=np.int32)
        mask = np.empty((b, t), dtype=np.int32)
        encoded = [s.encode("utf-8") for s in texts]
        arr = (ctypes.c_char_p * b)(*encoded)
        self._lib.svoc_tokenize_batch(
            arr,
            b,
            t,
            self.vocab_size,
            self.pad_id,
            self.bos_id,
            self.eos_id,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return ids, mask
