// Native greedy next-fit sequence packer — the host-side hot stage of
// the packed inference path (svoc_tpu/models/packing.py:pack_tokens is
// the Python reference; semantics must match it EXACTLY, asserted in
// tests/test_runtime.py).
//
// Input is the flattened concatenation of per-comment token lists with
// prefix offsets (list i = flat[offsets[i] .. offsets[i+1])).  Output
// arrays are caller-allocated [rows_cap, seq_len] / [rows_cap,
// max_segments] and must be PRE-FILLED by the caller (ids/pos = pad_id,
// seg/cls_pos/seg_valid = 0, owner = -1) — the packer only writes the
// cells it fills, exactly like the numpy reference.
//
// out[0] = rows actually used, out[1] = comments consumed (when
// rows_cap bounds the packing, unconsumed comments stay for the next
// call — the streaming resume contract).

#include <cstdint>

extern "C" void svoc_pack_tokens(
    const int32_t* flat,
    const int64_t* offsets,
    int n_lists,
    int seq_len,
    int max_segments,
    int32_t pad_id,
    int rows_cap,
    int32_t* ids,
    int32_t* pos,
    int32_t* seg,
    int32_t* cls_pos,
    int32_t* seg_valid,
    int32_t* owner,
    int32_t* out) {
  if (rows_cap < 1) {  // defensive: the ctypes wrapper validates too
    out[0] = 0;
    out[1] = 0;
    return;
  }
  int row = 0;
  int cur_len = 0;
  int cur_seg = 0;
  int consumed = 0;
  for (int i = 0; i < n_lists; ++i) {
    int64_t begin = offsets[i];
    int len = static_cast<int>(offsets[i + 1] - begin);
    if (len > seq_len) len = seq_len;  // truncate (== toks[:seq_len])
    const bool empty = (len == 0);     // degenerate: still owns a segment
    const int eff = empty ? 1 : len;
    if (cur_len + eff > seq_len || cur_seg >= max_segments) {
      // flush (the condition can only trigger with a non-empty row,
      // since a single truncated list always fits an empty one)
      ++row;
      cur_len = 0;
      cur_seg = 0;
      if (row >= rows_cap) break;  // row budget: do NOT consume list i
    }
    const int64_t base = static_cast<int64_t>(row) * seq_len;
    if (empty) {
      ids[base + cur_len] = pad_id;
    } else {
      const int32_t* src = flat + begin;
      for (int j = 0; j < len; ++j) ids[base + cur_len + j] = src[j];
    }
    for (int j = 0; j < eff; ++j) {
      pos[base + cur_len + j] = pad_id + 1 + j;  // restart per segment
      seg[base + cur_len + j] = cur_seg + 1;     // 1-based, 0 = padding
    }
    const int64_t sbase = static_cast<int64_t>(row) * max_segments;
    cls_pos[sbase + cur_seg] = cur_len;
    seg_valid[sbase + cur_seg] = 1;
    owner[sbase + cur_seg] = i;
    cur_len += eff;
    ++cur_seg;
    ++consumed;
  }
  out[0] = row + (cur_seg > 0 ? 1 : 0);
  out[1] = consumed;
}
