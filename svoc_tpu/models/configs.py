"""Model configurations.

``ROBERTA_GO_EMOTIONS`` matches the architecture of the reference's
classifier ``SamLowe/roberta-base-go_emotions``
(``client/oracle_scheduler.py:23-24``: RoBERTa-base, 28 go_emotions
labels, multi-label sigmoid head); ``DISTILBERT_SST2`` covers
BASELINE.json config 1 ("Single oracle: DistilBERT-SST2").  Weights are
randomly initialized unless a converted checkpoint is supplied — the
framework's contract is architecture + throughput parity; the
environment has no network egress for pulling HF weights.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 50265
    hidden: int = 768
    n_layers: int = 12
    n_heads: int = 12
    intermediate: int = 3072
    max_len: int = 512
    n_labels: int = 28
    pad_id: int = 1
    ln_eps: float = 1e-5
    #: computation dtype — bf16 keeps the MXU fed; params stay f32.
    dtype: Any = jnp.bfloat16
    #: rematerialize each encoder block (jax.checkpoint) to trade
    #: FLOPs for HBM during fine-tuning.
    remat: bool = False
    #: "sigmoid" (multi-label, go_emotions) or "softmax" (SST-2).
    head: str = "sigmoid"
    #: "dense" (fused XLA einsum chain) or "flash" (Pallas online-softmax
    #: kernel, :mod:`svoc_tpu.ops.pallas_attention`).  Honest amortized
    #: timings on v5e (FLASH_PROBE.json): flash wins from T=512
    #: (1.16×) and dominates long context (49× at T=8192, where the
    #: dense [B,H,T,T] HBM blowup bites); at the classifier's T=128
    #: dense is ~8% faster, so it stays the default.  Flash trains too
    #: (FlashAttention-2 custom VJP, gradient-parity-tested vs dense)
    #: and composes with packed batches via segment tags (no
    #: [R, 1, T, T] bias materialization — bench --config 12 measures
    #: it against packed×dense); only the ring/lse composition is
    #: inference-only.  The params tree is impl-independent —
    #: train/serve with either.
    attention: str = "dense"

    @property
    def head_dim(self) -> int:
        assert self.hidden % self.n_heads == 0
        return self.hidden // self.n_heads


ROBERTA_GO_EMOTIONS = EncoderConfig()

DISTILBERT_SST2 = EncoderConfig(
    vocab_size=30522,
    n_layers=6,
    max_len=512,
    n_labels=2,
    pad_id=0,
    head="softmax",
    ln_eps=1e-12,
)

#: Small config for unit tests and CPU dry-runs.
TINY_TEST = EncoderConfig(
    vocab_size=1024,
    hidden=64,
    n_layers=2,
    n_heads=4,
    intermediate=128,
    max_len=64,
    n_labels=28,
    dtype=jnp.float32,
)
