"""HF RoBERTa checkpoint → :class:`SentimentEncoder` params.

The reference's classifier is the HF torch model
``SamLowe/roberta-base-go_emotions`` (``client/oracle_scheduler.py:23``);
this converter maps a ``RobertaForSequenceClassification`` state dict
onto the from-scratch Flax encoder so real weights (when present in the
local HF cache — the environment has no egress) drive the TPU pipeline.

Architecture correspondences (verified logit-for-logit against torch in
``tests/test_convert.py``):

- ``embeddings.word_embeddings``            → ``tok_emb``
- ``embeddings.position_embeddings``        → ``pos_emb`` (same
  cumsum-past-pad position scheme, table height ``max_len + pad + 1``)
- ``embeddings.token_type_embeddings[0]``   → folded into ``pos_emb``
  (RoBERTa uses a single token type, added uniformly)
- ``encoder.layer.i.attention.self.q/k/v``  → ``block_i/attention/{query,key,value}``
- ``attention.output.dense``                → ``block_i/attention/out``
- ``attention.output.LayerNorm``            → ``block_i/ln_attn``
- ``intermediate.dense`` / ``output.dense`` → ``block_i/ffn_in`` / ``ffn_out``
- ``output.LayerNorm``                      → ``block_i/ln_ffn``
- ``classifier.dense`` / ``out_proj``       → ``head_dense`` / ``head_out``

Torch ``Linear`` weights are ``[out, in]`` and transpose to flax
``[in, out]`` kernels.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from svoc_tpu.models.configs import EncoderConfig
from svoc_tpu.models.encoder import SentimentEncoder


def _t(w) -> np.ndarray:
    return np.asarray(w, dtype=np.float32).T


def _a(w) -> np.ndarray:
    return np.asarray(w, dtype=np.float32)


def config_from_hf(hf_config, head: str = "sigmoid") -> EncoderConfig:
    """Derive an :class:`EncoderConfig` from a HF ``RobertaConfig``."""
    return EncoderConfig(
        vocab_size=hf_config.vocab_size,
        hidden=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        intermediate=hf_config.intermediate_size,
        max_len=hf_config.max_position_embeddings - hf_config.pad_token_id - 1,
        n_labels=hf_config.num_labels,
        pad_id=hf_config.pad_token_id,
        ln_eps=hf_config.layer_norm_eps,
        head=head,
    )


def convert_roberta_state_dict(
    state_dict: Dict[str, Any], cfg: EncoderConfig
) -> Dict[str, Any]:
    """Torch ``RobertaForSequenceClassification`` state dict → flax
    params for ``SentimentEncoder(cfg)``."""
    sd = {k: v.detach().cpu().numpy() for k, v in state_dict.items()}
    pre = "roberta."

    pos = _a(sd[pre + "embeddings.position_embeddings.weight"])
    type0 = _a(sd[pre + "embeddings.token_type_embeddings.weight"])[0]
    params: Dict[str, Any] = {
        "tok_emb": {
            "embedding": _a(sd[pre + "embeddings.word_embeddings.weight"])
        },
        # token type 0 is added to every position uniformly — fold it in.
        "pos_emb": {"embedding": pos + type0[None, :]},
        "ln_emb": {
            "scale": _a(sd[pre + "embeddings.LayerNorm.weight"]),
            "bias": _a(sd[pre + "embeddings.LayerNorm.bias"]),
        },
        "head_dense": {
            "kernel": _t(sd["classifier.dense.weight"]),
            "bias": _a(sd["classifier.dense.bias"]),
        },
        "head_out": {
            "kernel": _t(sd["classifier.out_proj.weight"]),
            "bias": _a(sd["classifier.out_proj.bias"]),
        },
    }

    for i in range(cfg.n_layers):
        hf = f"{pre}encoder.layer.{i}."
        params[f"block_{i}"] = {
            "attention": {
                "query": {
                    "kernel": _t(sd[hf + "attention.self.query.weight"]),
                    "bias": _a(sd[hf + "attention.self.query.bias"]),
                },
                "key": {
                    "kernel": _t(sd[hf + "attention.self.key.weight"]),
                    "bias": _a(sd[hf + "attention.self.key.bias"]),
                },
                "value": {
                    "kernel": _t(sd[hf + "attention.self.value.weight"]),
                    "bias": _a(sd[hf + "attention.self.value.bias"]),
                },
                "out": {
                    "kernel": _t(sd[hf + "attention.output.dense.weight"]),
                    "bias": _a(sd[hf + "attention.output.dense.bias"]),
                },
            },
            "ln_attn": {
                "scale": _a(sd[hf + "attention.output.LayerNorm.weight"]),
                "bias": _a(sd[hf + "attention.output.LayerNorm.bias"]),
            },
            "ffn_in": {
                "kernel": _t(sd[hf + "intermediate.dense.weight"]),
                "bias": _a(sd[hf + "intermediate.dense.bias"]),
            },
            "ffn_out": {
                "kernel": _t(sd[hf + "output.dense.weight"]),
                "bias": _a(sd[hf + "output.dense.bias"]),
            },
            "ln_ffn": {
                "scale": _a(sd[hf + "output.LayerNorm.weight"]),
                "bias": _a(sd[hf + "output.LayerNorm.bias"]),
            },
        }

    return {"params": params}


def load_hf_checkpoint(name_or_path: str, head: str = "sigmoid"):
    """Load a cached HF checkpoint → ``(SentimentEncoder, params)``.

    Requires the model in the local HF cache (no egress).
    """
    from transformers import AutoModelForSequenceClassification

    model = AutoModelForSequenceClassification.from_pretrained(
        name_or_path, local_files_only=True
    )
    cfg = config_from_hf(model.config, head=head)
    params = convert_roberta_state_dict(model.state_dict(), cfg)
    return SentimentEncoder(cfg), params


# --------------------------------------------------------------------------
# Converted-checkpoint persistence (single dependency-free .npz)
# --------------------------------------------------------------------------


def save_params(path: str, params: Dict) -> str:
    """Persist a flax params tree as one ``.npz`` (keys = /-joined tree
    paths) so a conversion runs once and serving loads an artifact.
    Returns the actual file path (``np.savez`` appends ``.npz`` when
    the suffix is missing)."""
    import jax

    flat = {}
    for key_path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = "/".join(
            getattr(p, "key", getattr(p, "name", str(p))) for p in key_path
        )
        flat[key] = np.asarray(leaf)
    if not path.endswith(".npz"):
        path += ".npz"
    np.savez(path, **flat)
    return path


def load_params(path: str) -> Dict:
    """Inverse of :func:`save_params`."""
    out: Dict[str, Any] = {}
    with np.load(path) as data:
        for key in data.files:
            node = out
            parts = key.split("/")
            for part in parts[:-1]:
                node = node.setdefault(part, {})
            node[parts[-1]] = data[key]
    return out


def main(argv=None) -> int:
    """CLI: ``python -m svoc_tpu.models.convert NAME -o params.npz`` —
    convert a locally-cached HF RoBERTa classifier to a reusable flax
    params artifact (pass it to ``SentimentPipeline(params=load_params(
    path))``)."""
    import argparse

    parser = argparse.ArgumentParser(description=main.__doc__)
    parser.add_argument("name_or_path", help="HF model name or local path")
    parser.add_argument("-o", "--out", required=True, help="output .npz")
    parser.add_argument(
        "--head", choices=("sigmoid", "softmax"), default="sigmoid"
    )
    args = parser.parse_args(argv)

    model, params = load_hf_checkpoint(args.name_or_path, head=args.head)
    out_path = save_params(args.out, params)
    n = sum(
        int(np.prod(np.shape(leaf)))
        for leaf in _tree_leaves_np(params)
    )
    print(
        f"converted {args.name_or_path}: {n / 1e6:.1f}M params "
        f"({model.cfg.n_layers}L/{model.cfg.hidden}H, "
        f"{model.cfg.n_labels} labels) -> {out_path}"
    )
    return 0


def _tree_leaves_np(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
