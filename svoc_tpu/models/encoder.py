"""From-scratch Flax transformer encoder for sentiment classification.

TPU-first design notes (not a port of HF modeling code):

- All matmuls run in ``cfg.dtype`` (bfloat16 by default) with float32
  parameters and float32 layernorm/softmax accumulations — the MXU path.
- Attention is a single fused ``einsum`` chain over ``[B, H, T, D]``;
  no data-dependent shapes, masks are additive float biases.
- Each block can be rematerialized (``cfg.remat``) for fine-tuning.
- Tensor-parallel sharding is applied externally by constraining the
  FFN/attention kernels over the ``"model"`` mesh axis
  (:func:`param_shardings`); the module itself stays mesh-agnostic so
  the same code runs single-chip and pod-sharded.

Architecture parity target: RoBERTa-base post-LN encoder + first-token
classification head, matching the reference classifier
``SamLowe/roberta-base-go_emotions`` (``client/oracle_scheduler.py:23``).
The module returns **logits**; the multi-label sigmoid / softmax lives in
:mod:`svoc_tpu.models.sentiment` (inference) and the loss (training).
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from svoc_tpu.models.configs import EncoderConfig


class SelfAttention(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        bias: jnp.ndarray,
        segments: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        cfg = self.cfg
        b, t, _ = x.shape
        h, d = cfg.n_heads, cfg.head_dim

        q = nn.Dense(cfg.hidden, dtype=cfg.dtype, name="query")(x).reshape(b, t, h, d)
        k = nn.Dense(cfg.hidden, dtype=cfg.dtype, name="key")(x).reshape(b, t, h, d)
        v = nn.Dense(cfg.hidden, dtype=cfg.dtype, name="value")(x).reshape(b, t, h, d)

        if cfg.attention == "flash":
            from svoc_tpu.ops.pallas_attention import flash_attention

            if segments is not None:
                # Packed rows: the kernel masks per tile from the [B, T]
                # segment ids — no [B, 1, T, T] bias ever materializes.
                ctx = flash_attention(q, k, v, segment_ids=segments)
            else:
                # The additive bias encodes key padding (0 kept / -1e9
                # masked, broadcast [B, 1, 1, T]) — recover the boolean
                # per-key mask the kernel consumes.
                kmask = (bias[:, 0, 0, :] > -1.0).astype(jnp.int32)
                ctx = flash_attention(q, k, v, kmask)
            ctx = ctx.reshape(b, t, cfg.hidden)
        else:
            scale = jnp.asarray(1.0 / jnp.sqrt(d), cfg.dtype)
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            scores = scores.astype(jnp.float32) + bias  # f32 softmax
            probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, t, cfg.hidden)
        return nn.Dense(cfg.hidden, dtype=cfg.dtype, name="out")(ctx)


class EncoderBlock(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        bias: jnp.ndarray,
        segments: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        cfg = self.cfg
        a = SelfAttention(cfg, name="attention")(x, bias, segments)
        x = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32, name="ln_attn")(
            x + a
        ).astype(cfg.dtype)
        f = nn.Dense(cfg.intermediate, dtype=cfg.dtype, name="ffn_in")(x)
        f = nn.gelu(f, approximate=False)
        f = nn.Dense(cfg.hidden, dtype=cfg.dtype, name="ffn_out")(f)
        x = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32, name="ln_ffn")(
            x + f
        ).astype(cfg.dtype)
        return x


class SentimentEncoder(nn.Module):
    """Token ids + attention mask → classification logits ``[B, n_labels]``."""

    cfg: EncoderConfig

    @nn.compact
    def __call__(self, ids: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg

        tok = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype, name="tok_emb")(
            ids
        )
        # RoBERTa-style positions: count only real tokens, offset past the
        # pad id (parity with the reference tokenizer's position scheme).
        pos_ids = jnp.cumsum(mask, axis=-1) * mask + cfg.pad_id
        # Table height max_len + pad_id + 1 = 514 for RoBERTa-base — the
        # HF max_position_embeddings, so converted checkpoints load 1:1.
        pos = nn.Embed(
            cfg.max_len + cfg.pad_id + 1, cfg.hidden, dtype=cfg.dtype, name="pos_emb"
        )(pos_ids)
        x = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32, name="ln_emb")(
            tok + pos
        ).astype(cfg.dtype)

        bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e9).astype(jnp.float32)

        block = nn.remat(EncoderBlock) if cfg.remat else EncoderBlock
        for i in range(cfg.n_layers):
            x = block(cfg, name=f"block_{i}")(x, bias)

        # First-token classification head (dense → tanh → out_proj), the
        # RobertaClassificationHead shape.
        cls = x[:, 0, :]
        cls = jnp.tanh(nn.Dense(cfg.hidden, dtype=cfg.dtype, name="head_dense")(cls))
        return nn.Dense(cfg.n_labels, dtype=jnp.float32, name="head_out")(cls)


def init_params(model: SentimentEncoder, seed: int = 0, batch: int = 2) -> Dict:
    cfg = model.cfg
    ids = jnp.ones((batch, min(16, cfg.max_len)), jnp.int32)
    mask = jnp.ones_like(ids)
    return model.init(jax.random.PRNGKey(seed), ids, mask)


def param_shardings(params: Any, mesh, model_axis: str = "model"):
    """NamedShardings for tensor parallelism: shard FFN and attention
    projection kernels over ``model_axis``, replicate the rest.

    ``ffn_in``/``query``/``key``/``value`` kernels ``[in, out]`` split on
    the output (column) dim; ``ffn_out``/attention-``out`` on the input
    (row) dim — the Megatron layout, so XLA inserts one all-reduce per
    half-block over ICI and activations stay sharded in between.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    col = ("ffn_in", "query", "key", "value")
    row = ("ffn_out", "attention/out")

    def spec_for(path_str: str, leaf) -> Any:
        if getattr(leaf, "ndim", 0) == 2 and path_str.endswith("kernel"):
            if any(k in path_str for k in col):
                return NamedSharding(mesh, P(None, model_axis))
            if any(k in path_str for k in row):
                return NamedSharding(mesh, P(model_axis, None))
        return NamedSharding(mesh, P())

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = []
    for path, leaf in flat:
        path_str = "/".join(
            getattr(p, "key", getattr(p, "name", str(p))) for p in path
        )
        specs.append(spec_for(path_str, leaf))
    return jax.tree_util.tree_unflatten(treedef, specs)
