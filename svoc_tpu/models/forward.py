"""Forward-function resolution shared by the pipeline and serving steps.

One place owns the (quant × packed) dispatch and its validation so
:class:`svoc_tpu.models.sentiment.SentimentPipeline` and the serving
step factories (:mod:`svoc_tpu.parallel.serving`) can never drift on
which forward implements a configuration.  Imports stay lazy per
branch: resolving a float forward never touches the int8 module (which
pulls in the parallel package's encoder math).
"""

from __future__ import annotations

from typing import Optional

from svoc_tpu.models.configs import EncoderConfig


def validate_quant(cfg: EncoderConfig, quant: Optional[str]) -> None:
    """The quant-option contract, raised identically by every entry."""
    if quant not in (None, "int8"):
        raise ValueError(f"quant must be None or 'int8', got {quant!r}")
    if quant == "int8" and cfg.attention != "dense":
        raise ValueError(
            "int8 serving uses the dense attention path — set "
            f"cfg.attention == 'dense' (got {cfg.attention!r})"
        )


def resolve_forward(
    cfg: EncoderConfig, quant: Optional[str] = None, packed: bool = False
):
    """The encoder forward for a serving/pipeline configuration.

    Returns ``(params, ids, mask) → logits`` (unpacked) or ``(params,
    ids, pos, seg, cls_pos) → logits`` (packed); the flax module's
    ``apply`` for float configs, the W8A8 math
    (:mod:`svoc_tpu.models.quant`) for ``quant="int8"`` — whose
    ``params`` is then the QUANTIZED tree (:func:`~svoc_tpu.models.
    quant.quantize_params`).
    """
    validate_quant(cfg, quant)
    if packed:
        if quant == "int8":
            from svoc_tpu.models.quant import quantized_packed_forward

            return lambda p, ids, pos, seg, cls_pos: quantized_packed_forward(
                p, ids, pos, seg, cls_pos, cfg
            )
        from svoc_tpu.models.packing import PackedSentimentEncoder

        return PackedSentimentEncoder(cfg).apply
    if quant == "int8":
        from svoc_tpu.models.quant import quantized_forward

        return lambda p, ids, mask: quantized_forward(p, ids, mask, cfg)
    from svoc_tpu.models.encoder import SentimentEncoder

    return SentimentEncoder(cfg).apply
