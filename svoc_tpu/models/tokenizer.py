"""Host-side tokenization producing fixed-shape device batches.

The reference delegates tokenization to the HF pipeline
(``client/oracle_scheduler.py:23-24``); here tokenization is an explicit
host stage feeding fixed ``[B, T]`` int32 batches so the device graph
never sees dynamic shapes.

Two backends:

- :func:`load_tokenizer` — a cached HuggingFace tokenizer when one is
  available on disk (``local_files_only``; the environment has no
  egress), giving vocabulary parity with the reference classifier.
- :class:`HashingTokenizer` — a dependency-free deterministic fallback
  (lowercase, split on non-alphanumerics, FNV-1a hash into the vocab).
  Architecture/throughput benchmarking does not depend on the vocab
  mapping, only on shapes.

A C++ fast path for the hashing backend lives in
:mod:`svoc_tpu.runtime` (used automatically when the native library is
built); this module is the reference implementation and fallback.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

_FNV_OFFSET = 14695981039346656037
_FNV_PRIME = 1099511628211
_U64 = (1 << 64) - 1


def _fnv1a(token: str) -> int:
    h = _FNV_OFFSET
    for byte in token.encode("utf-8"):
        h = ((h ^ byte) * _FNV_PRIME) & _U64
    return h


class HashingTokenizer:
    """Deterministic hashing tokenizer with BERT-style special ids.

    Reserves ``[pad, bos/cls, eos/sep, unk]`` then hashes word tokens
    into ``[n_special, vocab_size)``.
    """

    N_SPECIAL = 4

    def __init__(self, vocab_size: int, pad_id: int = 1, max_len: int = 512):
        self.vocab_size = vocab_size
        self.pad_id = pad_id
        self.max_len = max_len
        specials = [i for i in range(self.N_SPECIAL)]
        self.bos_id = next(i for i in specials if i != pad_id)
        self.eos_id = next(
            i for i in specials if i not in (pad_id, self.bos_id)
        )

    def _word_ids(self, text: str) -> List[int]:
        out: List[int] = []
        word = []
        for ch in text.lower():
            if ch.isalnum():
                word.append(ch)
            elif word:
                out.append(self._hash_word("".join(word)))
                word = []
        if word:
            out.append(self._hash_word("".join(word)))
        return out

    def _hash_word(self, word: str) -> int:
        span = self.vocab_size - self.N_SPECIAL
        return self.N_SPECIAL + (_fnv1a(word) % span)

    def __call__(
        self, texts: Sequence[str], seq_len: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Tokenize a batch → ``(ids [B, T], mask [B, T])`` int32."""
        t = seq_len or self.max_len
        b = len(texts)
        ids = np.full((b, t), self.pad_id, dtype=np.int32)
        mask = np.zeros((b, t), dtype=np.int32)
        for i, text in enumerate(texts):
            toks = [self.bos_id] + self._word_ids(text)[: t - 2] + [self.eos_id]
            ids[i, : len(toks)] = toks
            mask[i, : len(toks)] = 1
        return ids, mask


class _HFTokenizerAdapter:
    """Wraps a HuggingFace tokenizer into the same fixed-shape call."""

    def __init__(self, hf_tokenizer, max_len: int):
        self._tok = hf_tokenizer
        self.max_len = max_len
        self.pad_id = hf_tokenizer.pad_token_id or 0
        # len() includes added special tokens; .vocab_size does not —
        # the larger figure is the real id range the model must cover.
        self.vocab_size = max(len(hf_tokenizer), hf_tokenizer.vocab_size)

    def __call__(
        self, texts: Sequence[str], seq_len: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        enc = self._tok(
            list(texts),
            padding="max_length",
            truncation=True,
            max_length=seq_len or self.max_len,
            return_tensors="np",
        )
        return (
            enc["input_ids"].astype(np.int32),
            enc["attention_mask"].astype(np.int32),
        )


def load_tokenizer(
    name_or_path: Optional[str],
    vocab_size: int,
    pad_id: int = 1,
    max_len: int = 512,
):
    """Best-effort cached HF tokenizer, falling back to hashing (the
    native C++ batch tokenizer when it builds, else the Python one).

    Never touches the network (``local_files_only=True``).
    """
    if name_or_path:
        try:  # pragma: no cover — depends on local HF cache contents
            from transformers import AutoTokenizer

            hf = AutoTokenizer.from_pretrained(name_or_path, local_files_only=True)
            return _HFTokenizerAdapter(hf, max_len)
        except Exception:
            pass
    try:
        from svoc_tpu.runtime import NativeHashingTokenizer

        return NativeHashingTokenizer(vocab_size, pad_id=pad_id, max_len=max_len)
    except RuntimeError:
        return HashingTokenizer(vocab_size, pad_id=pad_id, max_len=max_len)
