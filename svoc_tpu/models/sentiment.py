"""The sentiment pipeline: texts → normalized 6-D emotion vectors.

Replaces ``sentiment_analysis`` + ``prediction_to_vector``
(``client/oracle_scheduler.py:27-40``): run the classifier over a batch,
select the 6 tracked go_emotions labels (``client/common.py:19-31``),
and sum-normalize each vector.  On TPU the select+normalize fuses into
the jitted forward, so the device returns ready ``[B, 6]`` prediction
vectors and the host never touches per-label dicts.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from svoc_tpu.models.configs import ROBERTA_GO_EMOTIONS, EncoderConfig
from svoc_tpu.models.encoder import SentimentEncoder, init_params
from svoc_tpu.models.tokenizer import load_tokenizer
from svoc_tpu.utils.metrics import stage_span

#: The 28 go_emotions labels in model-head order (the reference model's
#: label space, https://huggingface.co/SamLowe/roberta-base-go_emotions).
GO_EMOTIONS_LABELS = (
    "admiration", "amusement", "anger", "annoyance", "approval", "caring",
    "confusion", "curiosity", "desire", "disappointment", "disapproval",
    "disgust", "embarrassment", "excitement", "fear", "gratitude", "grief",
    "joy", "love", "nervousness", "optimism", "pride", "realization",
    "relief", "remorse", "sadness", "surprise", "neutral",
)

#: The tracked subset — DIMENSION=6 (``client/common.py:19-31``).
TRACKED_LABELS = (
    "optimism", "anger", "annoyance", "excitement", "nervousness", "remorse",
)

TRACKED_INDICES = tuple(GO_EMOTIONS_LABELS.index(l) for l in TRACKED_LABELS)


@partial(jax.jit, static_argnames=("label_indices", "multi_label"))
def scores_to_vectors(
    logits: jnp.ndarray,
    label_indices: tuple = TRACKED_INDICES,
    multi_label: bool = True,
) -> jnp.ndarray:
    """Logits ``[B, L]`` → sum-normalized tracked vectors ``[B, len(idx)]``.

    ``multi_label=True`` applies per-label sigmoid (go_emotions,
    ``top_k=None`` pipeline semantics); else softmax (SST-2).
    Normalization is the reference's ``normalize`` (sum-to-one,
    ``oracle_scheduler.py:20``).
    """
    scores = jax.nn.sigmoid(logits) if multi_label else jax.nn.softmax(logits, -1)
    sel = scores[:, jnp.asarray(label_indices)]
    return sel / jnp.sum(sel, axis=-1, keepdims=True)


@dataclasses.dataclass
class SentimentPipeline:
    """End-to-end host→device sentiment stage with fixed batch shapes.

    ``gen_classifier()`` equivalent (``oracle_scheduler.py:23-24``) —
    construct once, call with a list of strings, get ``[B, M]`` numpy
    vectors back.
    """

    cfg: EncoderConfig = ROBERTA_GO_EMOTIONS
    seq_len: int = 128
    batch_size: int = 32
    tokenizer_name: Optional[str] = "SamLowe/roberta-base-go_emotions"
    label_indices: tuple = TRACKED_INDICES
    seed: int = 0
    params: Optional[dict] = None
    #: Cast float32 params ONCE at construction (e.g. "bfloat16") so
    #: inference matmuls read half-width weights from HBM instead of
    #: casting per call.  None keeps the stored dtype (training /
    #: conversion-parity use).  Measured +1.5% MFU on v5e
    #: (PERF_EXPERIMENTS.json).
    params_dtype: Optional[str] = None
    #: Optional 1-D device mesh: shard the token batch over its first
    #: axis (data parallelism) with params replicated, so the app-layer
    #: vectorizer scales to a v5e-8 the same way the serving path does
    #: (:mod:`svoc_tpu.parallel.serving`).  The mesh size must divide
    #: ``batch_size``.  None = single-device (default).
    data_mesh: Optional[object] = None
    #: Route ``__call__`` through the sequence-packed forward
    #: (:mod:`svoc_tpu.models.packing`): several comments per fixed row,
    #: ~3× fewer device rows on HN-shaped text, identical results to
    #: float tolerance.  Composes with ``cfg.attention`` "dense" (additive
    #: block-diagonal bias) or "flash" (segment tags in the kernel — no
    #: [R, 1, T, T] bias materialization).
    packed: bool = False
    #: Segments per packed row (only read when ``packed``).
    max_segments: int = 8
    #: ``"int8"`` swaps the block matmuls for W8A8 dynamic-PTQ kernels
    #: (:mod:`svoc_tpu.models.quant`) — 2× the bf16 MXU rate on v5e,
    #: ~4× smaller HBM tree; composes with ``packed`` and ``data_mesh``.
    #: None (default) keeps the float forward.  Serving-only: the
    #: quantized tree is not trainable; it persists via
    #: ``models.convert.save_params``/``load_params`` (a pre-folded tree
    #: passed as ``params`` is used as-is).
    quant: Optional[str] = None

    def __post_init__(self):
        from svoc_tpu.models.forward import resolve_forward, validate_quant

        # ALL config validation up front — before the tree cast and the
        # tokenizer load, so a misconfiguration fails in microseconds.
        if self.packed and self.cfg.attention not in ("dense", "flash"):
            raise ValueError(
                "packed inference supports cfg.attention 'dense' or "
                f"'flash' (got {self.cfg.attention!r})"
            )
        if max(self.label_indices) >= self.cfg.n_labels:
            raise ValueError(
                f"label_indices {self.label_indices} out of range for a "
                f"{self.cfg.n_labels}-label head — pass label_indices "
                f"matching the model (e.g. (0, 1) for SST-2)"
            )
        validate_quant(self.cfg, self.quant)
        if self.quant is None and self.params is not None:
            from svoc_tpu.models.quant import is_quantized_tree

            if is_quantized_tree(self.params):
                # Without this, the float forward dies at trace time
                # with an opaque KeyError('kernel') (ADVICE r3).
                raise ValueError(
                    "params is a pre-quantized (int8) tree but quant is "
                    "None — pass quant='int8' to serve it, or load the "
                    "float checkpoint for the float forward"
                )
        if self.quant and self.params_dtype is not None:
            raise ValueError(
                "params_dtype is not meaningful under quant='int8' — "
                "the fold defines its own dtypes (int8 kernels, f32 "
                "scales/rest); casting a quantized tree would change "
                "its numerics"
            )
        self.model = SentimentEncoder(self.cfg)
        if self.params is None:
            self.params = init_params(self.model, seed=self.seed)
        if self.params_dtype is not None:
            dtype = jnp.dtype(self.params_dtype)
            self.params = jax.tree_util.tree_map(
                lambda a: a.astype(dtype) if a.dtype == jnp.float32 else a,
                self.params,
            )
        self.tokenizer = load_tokenizer(
            self.tokenizer_name,
            self.cfg.vocab_size,
            pad_id=self.cfg.pad_id,
            max_len=self.seq_len,
        )
        if (
            self.tokenizer.vocab_size > self.cfg.vocab_size
            or self.tokenizer.pad_id != self.cfg.pad_id
        ):
            # A cached HF tokenizer that doesn't match the model config
            # would emit ids the embedding gather silently clamps —
            # fall back to a hashing tokenizer sized for this model
            # (native C++ when available, via the same selection logic).
            self.tokenizer = load_tokenizer(
                None,
                self.cfg.vocab_size,
                pad_id=self.cfg.pad_id,
                max_len=self.seq_len,
            )
        multi = self.cfg.head == "sigmoid"
        idx = self.label_indices

        if self.quant == "int8":
            from svoc_tpu.models.quant import is_quantized_tree, quantize_params

            # The float tree is dropped after folding — the pipeline
            # holds only the int8 kernels (+ f32 rest) from here on.  A
            # pre-folded tree (e.g. load_params of a persisted fold) is
            # used as-is.
            if not is_quantized_tree(self.params):
                self.params = quantize_params(self.params, self.cfg)
        apply_fn = resolve_forward(self.cfg, self.quant)

        def forward_fn_body(params, ids, mask):
            logits = apply_fn(params, ids, mask)
            return scores_to_vectors(logits, idx, multi)

        self._batch_sharding = None
        if self.data_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self.data_mesh
            if self.batch_size % mesh.devices.size:
                raise ValueError(
                    f"batch_size {self.batch_size} not divisible by the "
                    f"{mesh.devices.size}-device data mesh"
                )
            self._batch_sharding = NamedSharding(
                mesh, P(mesh.axis_names[0], None)
            )
            # Replicate params across the mesh ONCE — without this,
            # every jitted call would re-broadcast the whole tree
            # (~500 MB for RoBERTa-base f32) to all devices.
            self.params = jax.device_put(self.params, NamedSharding(mesh, P()))
            self._forward = jax.jit(
                forward_fn_body,
                in_shardings=(
                    NamedSharding(mesh, P()),
                    self._batch_sharding,
                    self._batch_sharding,
                ),
            )
        else:
            self._forward = jax.jit(forward_fn_body)

    @property
    def dimension(self) -> int:
        return len(self.label_indices)

    def forward_fn(self):
        """The raw jitted ``(params, ids, mask) → [B, M]`` device fn."""
        return self._forward

    def packed_forward_fn(self):
        """Jitted packed forward: ``(params, ids, pos, seg, cls_pos) →
        [R, S, M]`` vectors (invalid segments produce garbage rows the
        caller masks via ``seg_valid``).  Shape-polymorphic in the
        segment count — S comes from the input arrays, so one callable
        serves every ``max_segments``.  Shares ``self.params`` — the
        packed module's parameter tree is identical
        (:mod:`svoc_tpu.models.packing`)."""
        from svoc_tpu.models.forward import resolve_forward

        multi = self.cfg.head == "sigmoid"
        idx = self.label_indices
        apply_fn = resolve_forward(self.cfg, self.quant, packed=True)

        def body(params, ids, pos, seg, cls_pos):
            logits = apply_fn(params, ids, pos, seg, cls_pos)
            r, s, l = logits.shape
            vecs = scores_to_vectors(logits.reshape(r * s, l), idx, multi)
            return vecs.reshape(r, s, len(idx))

        if self.data_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self.data_mesh, P())
            rows = self._batch_sharding
            return jax.jit(body, in_shardings=(rep, rows, rows, rows, rows))
        return jax.jit(body)

    def call_packed(
        self,
        texts: Sequence[str],
        max_segments: int = 8,
        lineage: Optional[str] = None,
    ) -> np.ndarray:
        """Packed equivalent of ``__call__``: same ``[len(texts), M]``
        result, ~packing-factor fewer forward rows.  Row count is padded
        to ``batch_size`` multiples so jit shapes stay fixed.

        ``lineage`` tags the stage spans with a block lineage id
        (``svoc_tpu.utils.events``); inside a ``fetch`` span the id is
        inherited automatically, so only detached callers (serving
        loops, tools) need to pass it."""
        from svoc_tpu.models.packing import (
            observe_fill_ratios,
            pack_tokens_auto,
            strip_padding,
        )

        if not len(texts):
            return np.zeros((0, self.dimension))
        with stage_span("tokenize", lineage=lineage):
            ids, mask = self.tokenizer(list(texts), self.seq_len)
        with stage_span("pack", lineage=lineage):
            token_lists = strip_padding(ids, mask)
            batch, n = pack_tokens_auto(
                token_lists, self.seq_len, max_segments, self.tokenizer.pad_id
            )
        # Fill-ratio gauges (docs/SERVING.md): how much of the segment
        # and token headroom this pack actually used — the observable
        # behind the serving batcher's fill-the-headroom claim.
        observe_fill_ratios(batch)
        assert n == len(texts), f"packer consumed {n}/{len(texts)} without a row cap"
        forward = self._packed_forward()
        out = np.zeros((len(texts), self.dimension), dtype=np.float64)
        rows = batch.ids.shape[0]
        b = self.batch_size
        for i in range(0, rows, b):
            sl = slice(i, i + b)
            chunk = [batch.ids[sl], batch.pos[sl], batch.seg[sl], batch.cls_pos[sl]]
            n_real = chunk[0].shape[0]
            if n_real < b:  # pad rows — fixed shapes, no recompiles
                chunk = [
                    np.concatenate(
                        [a, np.repeat(a[-1:], b - n_real, axis=0)], axis=0
                    )
                    for a in chunk
                ]
            # The span covers dispatch + the np.asarray host fetch that
            # was already here — no added device sync (deliberate
            # SVOC001 exception).
            with stage_span("forward", lineage=lineage):
                vecs = np.asarray(forward(self.params, *chunk), dtype=np.float64)  # svoclint: disable=SVOC001
            valid = batch.seg_valid[sl] > 0
            out[batch.owner[sl][valid]] = vecs[:n_real][valid]
        return out

    def _packed_forward(self):
        if not hasattr(self, "_packed_cache"):
            self._packed_cache = self.packed_forward_fn()
        return self._packed_cache

    def __call__(
        self, texts: Sequence[str], lineage: Optional[str] = None
    ) -> np.ndarray:
        """``sentiment_analysis`` equivalent: pad to full batches, run
        the jitted forward per chunk, return ``[len(texts), M]``.
        ``lineage`` as in :meth:`call_packed`."""
        if self.packed:
            return self.call_packed(texts, self.max_segments, lineage=lineage)
        out = []
        b = self.batch_size
        for i in range(0, len(texts), b):
            chunk = list(texts[i : i + b])
            n_real = len(chunk)
            chunk += [""] * (b - n_real)  # fixed shapes — no recompiles
            with stage_span("tokenize", lineage=lineage):
                ids, mask = self.tokenizer(chunk, self.seq_len)
            # No explicit device_put: the jitted forward's in_shardings
            # place the raw numpy batch shard-wise in one transfer.
            # The span covers dispatch + the np.asarray host fetch that
            # was already here — no added device sync (deliberate
            # SVOC001 exception).
            with stage_span("forward", lineage=lineage):
                vecs = self._forward(self.params, ids, mask)
                out.append(np.asarray(vecs[:n_real], dtype=np.float64))  # svoclint: disable=SVOC001
        return np.concatenate(out, axis=0) if out else np.zeros((0, self.dimension))
