"""Sequence packing: several comments per fixed-length row.

The reference classifies one comment per padded row
(``client/oracle_scheduler.py:36-40`` via the HF pipeline), and so did
this framework's flagship path — but HN comments are short (the
synthetic source draws 8-60 words; real scraped comments are similar),
so at the fixed ``seq_len=128`` most MXU work is padding.  Packing is
the TPU-first fix: every shape stays static, the attention mask becomes
block-diagonal, and one forward computes several comments' logits.

Three pieces:

- :func:`pack_tokens` — host-side greedy next-fit packer over unpadded
  token lists → fixed-shape :class:`PackedBatch` (ids, per-segment
  restarting positions, segment ids, per-segment CLS gather indices,
  owner mapping back to input order).
- :class:`PackedSentimentEncoder` — a flax module sharing the EXACT
  parameter tree of :class:`svoc_tpu.models.encoder.SentimentEncoder`
  (same submodule names), so converted checkpoints, bf16-resident
  params, and the Megatron TP shardings
  (:func:`svoc_tpu.models.encoder.param_shardings`) apply unchanged.
  It consumes a packed batch and returns ``[R, S, n_labels]`` logits.
- :meth:`svoc_tpu.models.sentiment.SentimentPipeline.call_packed` —
  texts → vectors through the packed path (tokenize, strip padding,
  pack, forward, scatter back by owner).

Numerical parity: a packed segment sees exactly the keys of its own
comment (block-diagonal additive bias) and per-segment positions
restart at ``pad_id + 1`` — the same position ids, layernorm inputs,
and softmax support as the unpacked forward, so logits match the
unpacked encoder to float tolerance (asserted in
``tests/test_packing.py``).

Packing composes with both attention implementations: ``"dense"``
materializes the block-diagonal additive bias ``[R, 1, T, T]``;
``"flash"`` feeds the raw ``[R, T]`` segment ids to the Pallas kernel,
which rebuilds each ``[bq, bk]`` tile's mask from two integer vectors
(:func:`svoc_tpu.ops.pallas_attention._tag_mask`) — no quadratic bias
tensor ever reaches HBM, removing the packed hot path's largest
intermediate.
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from svoc_tpu.models.configs import EncoderConfig
from svoc_tpu.models.encoder import EncoderBlock


class PackedBatch(NamedTuple):
    """Fixed-shape packed token batch (all int32).

    ``R`` rows of ``T`` tokens holding up to ``S`` segments each.
    """

    ids: np.ndarray  #: [R, T] token ids (pad_id where empty)
    pos: np.ndarray  #: [R, T] RoBERTa positions, restarting per segment
    seg: np.ndarray  #: [R, T] 1-based segment id within the row, 0 = padding
    cls_pos: np.ndarray  #: [R, S] row offset of each segment's first token
    seg_valid: np.ndarray  #: [R, S] 1 where the segment exists
    owner: np.ndarray  #: [R, S] index into the packed input list, -1 invalid

    @property
    def n_segments(self) -> int:
        return int(self.seg_valid.sum())


def strip_padding(ids: np.ndarray, mask: np.ndarray) -> List[np.ndarray]:
    """Fixed-shape tokenizer output → per-text unpadded id arrays
    (int32, no Python-int conversion — the native packer concatenates
    them without a per-element copy)."""
    return [row[m > 0] for row, m in zip(ids, mask)]


def pack_tokens(
    token_lists: Sequence[Sequence[int]],
    seq_len: int,
    max_segments: int,
    pad_id: int,
    rows: int | None = None,
) -> Tuple[PackedBatch, int]:
    """Greedy next-fit packing of ``token_lists`` into ``[R, T]`` rows.

    Lists longer than ``seq_len`` are truncated (the unpacked path
    truncates identically at tokenization).  With ``rows=None`` every
    list is consumed and R is whatever it takes; with explicit ``rows``
    packing stops when they are full.  Returns ``(batch, n_consumed)``
    — ``n_consumed`` lets streaming callers resume mid-stream.

    Positions restart per segment at ``pad_id + 1``, matching the
    unpacked encoder's ``cumsum(mask)*mask + pad_id`` scheme.
    """
    if max_segments < 1:
        raise ValueError(f"max_segments must be >= 1, got {max_segments}")
    if rows is not None and rows < 1:
        raise ValueError(f"rows must be >= 1, got {rows}")
    row_ids: List[List[int]] = []
    row_segs: List[List[Tuple[int, int]]] = []  # per row: (owner, start)
    cur_ids: List[int] = []
    cur_segs: List[Tuple[int, int]] = []
    n_consumed = 0

    def flush():
        nonlocal cur_ids, cur_segs
        if cur_segs:
            row_ids.append(cur_ids)
            row_segs.append(cur_segs)
            cur_ids, cur_segs = [], []

    for owner_idx, toks in enumerate(token_lists):
        toks = list(toks[:seq_len])
        if not toks:
            toks = [pad_id]  # degenerate empty text still owns a segment
        if len(cur_ids) + len(toks) > seq_len or len(cur_segs) >= max_segments:
            flush()
            if rows is not None and len(row_ids) >= rows:
                break
        cur_segs.append((owner_idx, len(cur_ids)))
        cur_ids.extend(toks)
        n_consumed += 1
    else:
        flush()  # natural end — consume the trailing partial row

    r = rows if rows is not None else max(1, len(row_ids))
    t, s = seq_len, max_segments
    ids = np.full((r, t), pad_id, dtype=np.int32)
    pos = np.full((r, t), pad_id, dtype=np.int32)
    seg = np.zeros((r, t), dtype=np.int32)
    cls_pos = np.zeros((r, s), dtype=np.int32)
    seg_valid = np.zeros((r, s), dtype=np.int32)
    owner = np.full((r, s), -1, dtype=np.int32)
    for i, (tok_row, segs) in enumerate(zip(row_ids[:r], row_segs[:r])):
        ids[i, : len(tok_row)] = tok_row
        bounds = [start for _, start in segs] + [len(tok_row)]
        for j, (owner_idx, start) in enumerate(segs):
            end = bounds[j + 1]
            seg[i, start:end] = j + 1
            pos[i, start:end] = pad_id + 1 + np.arange(end - start)
            cls_pos[i, j] = start
            seg_valid[i, j] = 1
            owner[i, j] = owner_idx
    return PackedBatch(ids, pos, seg, cls_pos, seg_valid, owner), n_consumed


def fill_ratios(batch: PackedBatch) -> dict:
    """Occupancy of a packed batch: ``segments`` (segments used over
    ``R × S`` slots) and ``tokens`` (real tokens over ``R × T`` id
    slots).  The serving batcher's headroom claim in numbers — BENCH_r05
    measured packing_factor 3.03 against ``max_segments=8``, i.e. the
    segment axis usually runs well under full (docs/SERVING.md)."""
    r, s = batch.seg_valid.shape
    t = batch.ids.shape[1]
    segments_used = int(batch.seg_valid.sum())
    real_tokens = int((batch.seg > 0).sum())
    return {
        "rows": int(r),
        "segments_used": segments_used,
        "segments": round(segments_used / float(max(r * s, 1)), 6),
        "tokens": round(real_tokens / float(max(r * t, 1)), 6),
    }


def observe_fill_ratios(batch: PackedBatch, registry=None) -> dict:
    """:func:`fill_ratios` plus the ``packing_fill_ratio{kind=}`` gauges
    every pack-path caller (``SentimentPipeline.call_packed``, the bench
    comment stream, the serving batcher) exports, so the batcher's
    fill-the-headroom behavior is observable on ``GET /metrics``."""
    if registry is None:
        from svoc_tpu.utils.metrics import registry as registry
    ratios = fill_ratios(batch)
    for kind in ("segments", "tokens"):
        registry.gauge("packing_fill_ratio", labels={"kind": kind}).set(
            ratios[kind]
        )
    return ratios


def pack_labels(batch: PackedBatch, labels: np.ndarray) -> np.ndarray:
    """Scatter per-comment ``labels [N, ...]`` into the packed layout
    ``[R, S, ...]`` via the owner map (zeros where no segment) — the
    label side of a packed fine-tuning batch
    (:func:`svoc_tpu.train.trainer.make_packed_train_step`)."""
    labels = np.asarray(labels)
    if len(labels) == 0:  # all-padding batch (empty streaming tail)
        return np.zeros(batch.owner.shape + labels.shape[1:], labels.dtype)
    safe = np.where(batch.owner >= 0, batch.owner, 0)
    out = labels[safe]
    out[batch.seg_valid == 0] = 0
    return out


def pack_tokens_auto(
    token_lists: Sequence[Sequence[int]],
    seq_len: int,
    max_segments: int,
    pad_id: int,
    rows: int | None = None,
) -> Tuple[PackedBatch, int]:
    """:func:`pack_tokens` via the native C++ packer when it builds
    (``svoc_tpu/runtime/packer.cpp`` — GIL-free, the host hot stage of
    packed serving), bit-identical Python fallback otherwise
    (equality-tested in ``tests/test_runtime.py``)."""
    try:
        from svoc_tpu.runtime import native_pack_tokens_raw

        raw = native_pack_tokens_raw(
            token_lists, seq_len, max_segments, pad_id, rows
        )
    except ImportError:  # pragma: no cover — runtime package stripped
        # counted, never silent: a stripped/broken native packer degrades
        # to the Python packer per BATCH, so the rate of degraded packs
        # is visible on the dashboard rather than only as a latency blur
        from svoc_tpu.utils.metrics import registry as _metrics

        _metrics.counter("pack_native_fallback").add(1)
        raw = None
    if raw is None:
        return pack_tokens(token_lists, seq_len, max_segments, pad_id, rows)
    ids, pos, seg, cls_pos, seg_valid, owner, n = raw
    return PackedBatch(ids, pos, seg, cls_pos, seg_valid, owner), n


class PackedSentimentEncoder(nn.Module):
    """Packed-batch twin of :class:`SentimentEncoder`.

    Identical parameter tree (same submodule names), different input
    contract: ``(ids [R,T], pos_ids [R,T], seg [R,T], cls_pos [R,S])``
    → logits ``[R, S, n_labels]``.  Attention is restricted to the
    block diagonal of ``seg`` (padding attends nothing and is never
    gathered).
    """

    cfg: EncoderConfig

    @nn.compact
    def __call__(
        self,
        ids: jnp.ndarray,
        pos_ids: jnp.ndarray,
        seg: jnp.ndarray,
        cls_pos: jnp.ndarray,
    ) -> jnp.ndarray:
        cfg = self.cfg
        if cfg.attention not in ("dense", "flash"):
            raise ValueError(
                "packed batches support cfg.attention 'dense' or 'flash' "
                f"(got {cfg.attention!r})"
            )

        tok = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype, name="tok_emb")(
            ids
        )
        pos = nn.Embed(
            cfg.max_len + cfg.pad_id + 1, cfg.hidden, dtype=cfg.dtype, name="pos_emb"
        )(pos_ids)
        x = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32, name="ln_emb")(
            tok + pos
        ).astype(cfg.dtype)

        if cfg.attention == "flash":
            # The flash kernel masks per tile straight from the [R, T]
            # segment ids (pallas_attention._tag_mask) — the packed
            # hot path's [R, 1, T, T] bias never materializes in HBM.
            bias, segments = None, seg
        else:
            # Block-diagonal additive bias [R, 1, T, T]: query q sees
            # key k iff both live in the same (real) segment.
            same = (seg[:, :, None] == seg[:, None, :]) & (seg[:, :, None] > 0)
            bias = jnp.where(same[:, None, :, :], 0.0, -1e9).astype(jnp.float32)
            segments = None

        block = nn.remat(EncoderBlock) if cfg.remat else EncoderBlock
        for i in range(cfg.n_layers):
            x = block(cfg, name=f"block_{i}")(x, bias, segments)

        # Per-segment first-token head: gather each segment's BOS hidden
        # state, then the RobertaClassificationHead stack.
        cls = jnp.take_along_axis(x, cls_pos[:, :, None], axis=1)  # [R, S, D]
        cls = jnp.tanh(nn.Dense(cfg.hidden, dtype=cfg.dtype, name="head_dense")(cls))
        return nn.Dense(cfg.n_labels, dtype=jnp.float32, name="head_out")(cls)
