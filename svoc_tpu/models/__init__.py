"""JAX/Flax sentiment models — the TPU replacement for the reference's
CPU-torch HuggingFace pipeline (``client/oracle_scheduler.py:23-40``)."""

from svoc_tpu.models.configs import (  # noqa: F401
    DISTILBERT_SST2,
    ROBERTA_GO_EMOTIONS,
    TINY_TEST,
    EncoderConfig,
)
from svoc_tpu.models.convert import (  # noqa: F401
    load_hf_checkpoint,
    load_params,
    save_params,
)
from svoc_tpu.models.encoder import SentimentEncoder  # noqa: F401
from svoc_tpu.models.sentiment import SentimentPipeline  # noqa: F401
from svoc_tpu.models.tokenizer import HashingTokenizer, load_tokenizer  # noqa: F401

_QUANT_EXPORTS = ("quantize_params", "quantized_forward", "quantized_packed_forward")


def __getattr__(name):
    """Lazy re-export of the int8 serving API — ``svoc_tpu.models.quant``
    pulls in :mod:`svoc_tpu.parallel.encoder_math`, and importing the
    parallel package eagerly from here would create a models↔parallel
    import cycle (parallel's modules import models submodules back)."""
    if name in _QUANT_EXPORTS:
        from svoc_tpu.models import quant

        return getattr(quant, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
