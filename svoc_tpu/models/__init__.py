"""JAX/Flax sentiment models — the TPU replacement for the reference's
CPU-torch HuggingFace pipeline (``client/oracle_scheduler.py:23-40``)."""

from svoc_tpu.models.configs import (  # noqa: F401
    DISTILBERT_SST2,
    ROBERTA_GO_EMOTIONS,
    TINY_TEST,
    EncoderConfig,
)
from svoc_tpu.models.convert import (  # noqa: F401
    load_hf_checkpoint,
    load_params,
    save_params,
)
from svoc_tpu.models.encoder import SentimentEncoder  # noqa: F401
from svoc_tpu.models.sentiment import SentimentPipeline  # noqa: F401
from svoc_tpu.models.tokenizer import HashingTokenizer, load_tokenizer  # noqa: F401
