"""W8A8 dynamic post-training quantization for the sentiment encoder.

A TPU-first serving capability with no counterpart in the reference
(its classifier runs f32 torch on CPU, ``client/oracle_scheduler.py:
23-40``): the v5e MXU executes int8×int8→int32 at TWICE the bf16 rate
(394 vs 197 TOPS), and at the classifier's seq 128 the encoder's FLOPs
are ~97 % Dense matmuls — so quantizing just the six block matmuls
(query/key/value/out/ffn_in/ffn_out) doubles the roofline while
embeddings, layernorms, softmax, residuals and the classification head
stay in bf16/f32.

Scheme — symmetric, zero-point-free, no calibration pass:

- **weights**: per-output-channel int8, ``scale[o] = amax(|W[:, o]|)/127``,
  folded once at load time (:func:`quantize_params`);
- **activations**: per-row (per-token) dynamic int8, scales computed on
  device inside the jitted forward — outlier tokens only widen their own
  row's grid;
- **accumulation**: int32 via ``lax.dot_general(..,
  preferred_element_type=int32)`` (the MXU int8 path); dequantization is
  a rank-1 rescale fused into the bias add.

The quantized forward IS the functional encoder math
(:mod:`svoc_tpu.parallel.encoder_math`): ``encoder_block`` runs with
``dense_fn=qdense`` and nothing else changes, so block wiring, softmax
and layernorm semantics stay pinned to the flax module's in exactly one
place.  Both the unpacked ``(ids, mask)`` contract and the
sequence-packed one (:mod:`svoc_tpu.models.packing`) are provided — the
packing factor and the int8 rate multiply.

Composition: the quantized tree is replicated for data-parallel serving
exactly like the float tree (it is ~4× smaller in HBM).  Tensor
parallelism is intentionally NOT wired here: int8 serving targets the
throughput path where DP over the batch is the right sharding for a
model this size.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from svoc_tpu.models.configs import EncoderConfig
from svoc_tpu.parallel.encoder_math import (
    cls_head,
    embed_tokens,
    encoder_block,
    local_position_ids,
)

#: Kernels quantized inside each encoder block (the MXU-heavy matmuls).
_BLOCK_DENSES = ("query", "key", "value", "out", "ffn_in", "ffn_out")


def quantize_dense(p: Dict) -> Dict:
    """``{kernel [I,O], bias [O]}`` → ``{w_int8, w_scale, bias}`` with
    per-output-channel symmetric scales."""
    w = jnp.asarray(p["kernel"], jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(w), axis=0), 1e-8) / 127.0
    w_int8 = jnp.clip(jnp.round(w / scale[None, :]), -127, 127).astype(jnp.int8)
    return {
        "w_int8": w_int8,
        "w_scale": scale,
        "bias": jnp.asarray(p["bias"], jnp.float32),
    }


def quantize_params(params: Dict, cfg: EncoderConfig) -> Dict:
    """Float param tree → quantized tree: every block Dense becomes an
    int8 triple, every other leaf is kept verbatim (embeddings, norms,
    head).  Structure mirrors the flax tree so the shared encoder math
    indexes it identically."""
    tree = dict(params["params"])
    for i in range(cfg.n_layers):
        bp = dict(tree[f"block_{i}"])
        ap = dict(bp["attention"])
        for name in _BLOCK_DENSES:
            holder = ap if name in ap else bp
            holder[name] = quantize_dense(holder[name])
        bp["attention"] = ap
        tree[f"block_{i}"] = bp
    return {"params": tree}


def is_quantized_tree(params: Dict) -> bool:
    """Whether ``params`` is already a :func:`quantize_params` output
    (any node carrying an ``w_int8`` kernel).  Lets serving load a
    persisted folded tree (``models.convert.save_params`` round-trips
    int8 leaves through ``.npz`` dtype-exactly) instead of re-folding
    at every boot."""

    def walk(node) -> bool:
        if isinstance(node, dict):
            if "w_int8" in node:
                return True
            return any(walk(v) for v in node.values())
        return False

    return walk(params)


def quantized_size_bytes(qparams: Dict) -> int:
    """Total HBM footprint of the quantized tree (int8 kernels + f32
    rest) — ~4× below the f32 tree, ~2× below bf16-resident."""
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(qparams)
    )


def _quantize_rows(x: jnp.ndarray):
    """Per-row dynamic int8 quantization: ``x → (xq int8, s f32)``."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True), 1e-8) / 127.0
    xq = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    return xq, s


def qdense(x: jnp.ndarray, qp: Dict, dtype) -> jnp.ndarray:
    """Dynamically quantized replacement for ``encoder_math.dense``
    (same ``(x, params, dtype)`` signature, so ``encoder_block`` takes
    it as ``dense_fn``).

    Per-row activation scales are computed in f32 on device; the matmul
    runs int8×int8→int32 on the MXU; dequant + bias fold into one
    elementwise epilogue XLA fuses.
    """
    xq, s = _quantize_rows(x)
    acc = jax.lax.dot_general(
        xq,
        qp["w_int8"],
        (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    y = acc.astype(jnp.float32) * (s * qp["w_scale"]) + qp["bias"]
    return y.astype(dtype)


def make_cached_qdense():
    """A :func:`qdense` that quantizes each DISTINCT activation tensor
    once per traced forward.

    ``encoder_block`` calls ``dense_fn(x, …)`` three times on the same
    ``x`` for Q/K/V (``encoder_math.py:102-104``); the naive qdense
    re-ran the amax-reduce + round/clip/cast chain on every call — six
    activation-quantization passes per layer where four distinct
    activations exist, pure HBM traffic at serving batch sizes (part
    of config 10's missing int8 speedup, VERDICT r5 item 5).  The
    cache is keyed by tracer identity and holds a strong reference to
    the key tensor, so a freed tracer's address can never alias a new
    one; scope one instance per traced forward call (a fresh cache per
    trace — never reuse across jit boundaries).
    """
    cache: Dict = {}

    def cached_qdense(x: jnp.ndarray, qp: Dict, dtype) -> jnp.ndarray:
        hit = cache.get(id(x))
        if hit is not None and hit[0] is x:
            _, xq, s = hit
        else:
            xq, s = _quantize_rows(x)
            cache[id(x)] = (x, xq, s)
        acc = jax.lax.dot_general(
            xq,
            qp["w_int8"],
            (((xq.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        y = acc.astype(jnp.float32) * (s * qp["w_scale"]) + qp["bias"]
        return y.astype(dtype)

    return cached_qdense


def _bias_attention(bias, cfg: EncoderConfig):
    """``attention_fn`` with a precomputed additive f32 bias (the packed
    block-diagonal case) — the same softmax chain as
    ``encoder_math.local_attention``'s dense branch."""

    def attn(q, k, v, _kmask):
        d = q.shape[-1]
        scale = jnp.asarray(1.0 / jnp.sqrt(jnp.float32(d)), cfg.dtype)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        probs = jax.nn.softmax(scores.astype(jnp.float32) + bias, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(cfg.dtype), v)

    return attn


def quantized_forward(
    qparams: Dict, ids: jnp.ndarray, mask: jnp.ndarray, cfg: EncoderConfig
) -> jnp.ndarray:
    """Unpacked ``(ids, mask) → logits`` with int8 block matmuls —
    drop-in for ``SentimentEncoder.apply`` on a quantized tree."""
    rest = qparams["params"]
    qd = make_cached_qdense()  # fresh per trace: Q/K/V share one quantize
    x = embed_tokens(ids, local_position_ids(mask, cfg), rest, cfg)
    for i in range(cfg.n_layers):
        x = encoder_block(x, mask, rest[f"block_{i}"], cfg, dense_fn=qd)
    return cls_head(x[:, 0, :], rest, cfg)


def quantized_packed_forward(
    qparams: Dict,
    ids: jnp.ndarray,
    pos_ids: jnp.ndarray,
    seg: jnp.ndarray,
    cls_pos: jnp.ndarray,
    cfg: EncoderConfig,
) -> jnp.ndarray:
    """Sequence-packed twin (``PackedSentimentEncoder`` contract:
    block-diagonal attention, per-segment CLS gather) with int8
    matmuls — the packing factor and the int8 MXU rate multiply."""
    rest = qparams["params"]
    qd = make_cached_qdense()  # fresh per trace: Q/K/V share one quantize
    x = embed_tokens(ids, pos_ids, rest, cfg)
    same = (seg[:, :, None] == seg[:, None, :]) & (seg[:, :, None] > 0)
    bias = jnp.where(same[:, None, :, :], 0.0, -1e9).astype(jnp.float32)
    attn = _bias_attention(bias, cfg)
    for i in range(cfg.n_layers):
        x = encoder_block(
            x, None, rest[f"block_{i}"], cfg, attention_fn=attn, dense_fn=qd
        )
    cls = jnp.take_along_axis(x, cls_pos[:, :, None], axis=1)  # [R, S, D]
    return cls_head(cls, rest, cfg)
