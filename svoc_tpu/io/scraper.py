"""Hacker News comment ingest.

Reference: ``client/scraper.py`` — a headless-Firefox Selenium loop that
loads ``news.ycombinator.com/newcomments``, extracts ``div.commtext``
texts in-page (``client/hn_scraper.js:3-9``), appends them to the
comment DB and sleeps ``rate`` seconds (default 600, ~30 posts/10 min —
``client/README.md:85``), with a catch-up wait derived from the last
stored timestamp on restart (``scraper.py:78-86``).

Here the ingest loop is a small host-side pipeline stage over a
pluggable *source*:

- :class:`SeleniumHNSource` — behavior parity with the reference
  (requires ``selenium`` + Firefox; unavailable in this image, so it is
  import-gated and raises a clear error at construction),
- :class:`SyntheticSource` — deterministic offline comment generator
  for tests/benchmarks and the zero-egress environment.

The loop itself (:func:`run_scraper`) is source-agnostic and can be run
in a thread (the reference runs it as a subprocess, ``main.py:38``).
"""

from __future__ import annotations

import datetime as _dt
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from svoc_tpu.io.comment_store import CommentStore

#: Default scrape period in seconds (``scraper.py:21``).
DEFAULT_RATE_S = 600

HN_URL = "https://news.ycombinator.com/newcomments"
#: The DOM selector extracted in-page (``client/hn_scraper.js:3``).
COMMENT_SELECTOR = "div.commtext"


class SeleniumHNSource:
    """Live HN source with the reference's Selenium behavior."""

    def __init__(self, headless: bool = True, timeout_s: float = 10.0):
        try:
            from selenium import webdriver
            from selenium.webdriver.firefox.options import Options
        except ImportError as e:  # pragma: no cover — selenium not baked in
            raise RuntimeError(
                "SeleniumHNSource needs the 'selenium' package and a "
                "Firefox driver; use SyntheticSource in offline "
                "environments"
            ) from e
        options = Options()
        if headless:
            options.add_argument("--headless")
        self._webdriver = webdriver
        self._driver = webdriver.Firefox(options=options)
        self._timeout_s = timeout_s

    def __call__(self) -> List[str]:  # pragma: no cover — needs a browser
        from selenium.webdriver.common.by import By
        from selenium.webdriver.support import expected_conditions as EC
        from selenium.webdriver.support.ui import WebDriverWait

        d = self._driver
        d.get(HN_URL)
        WebDriverWait(d, self._timeout_s).until(
            EC.presence_of_element_located((By.CSS_SELECTOR, COMMENT_SELECTOR))
        )
        # The same extraction the reference runs in-page
        # (hn_scraper.js:3-9), as a one-line script.
        return d.execute_script(
            "return Array.from(document.querySelectorAll('div.commtext'))"
            ".map(e => e.textContent.trim());"
        )

    def close(self) -> None:  # pragma: no cover
        self._driver.quit()


class SyntheticSource:
    """Deterministic offline comment batches (HN-comment-shaped text)."""

    _VOCAB = (
        "the a this compiler startup latency throughput rust python jax "
        "tpu actually interesting scale database network kernel cache "
        "memory model vector consensus oracle distributed blockchain "
        "performance benchmark thread async await parse build deploy"
    ).split()

    def __init__(self, batch: int = 30, seed: int = 0):
        self.batch = batch
        self._rng = np.random.default_rng(seed)

    def __call__(self) -> List[str]:
        out = []
        for _ in range(self.batch):
            k = int(self._rng.integers(8, 60))
            out.append(" ".join(self._rng.choice(self._VOCAB, size=k)))
        return out


def catch_up_delay_s(
    last_timestamp: Optional[str], rate_s: float, now: Optional[float] = None
) -> float:
    """Seconds to sleep before the first scrape so restarts keep the
    cadence (``scraper.py:78-86``): wait out the remainder of the period
    that started at the last stored comment."""
    if not last_timestamp:
        return 0.0
    try:
        parsed = _dt.datetime.fromisoformat(last_timestamp)
    except ValueError:
        return 0.0
    if parsed.tzinfo is None:
        # sqlite CURRENT_TIMESTAMP stores naive UTC (the reference
        # compares against utcnow, scraper.py:81) — don't let
        # .timestamp() reinterpret it in the local zone.
        parsed = parsed.replace(tzinfo=_dt.timezone.utc)
    last = parsed.timestamp()
    now = time.time() if now is None else now
    elapsed = now - last
    if elapsed < 0 or elapsed >= rate_s:
        return 0.0
    return rate_s - elapsed


def run_scraper(
    store: CommentStore,
    source: Callable[[], Sequence[str]],
    rate_s: float = DEFAULT_RATE_S,
    max_rounds: Optional[int] = None,
    stop_event: Optional[threading.Event] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> int:
    """The scrape loop (``scraper.py:74-94``); returns comments stored.

    ``max_rounds``/``stop_event`` bound the reference's infinite loop
    for embedding in tests and the CLI.
    """
    total = 0
    delay = catch_up_delay_s(store.last_timestamp(), rate_s)
    if delay:
        sleep(delay)
    rounds = 0
    from svoc_tpu.utils.metrics import stage_span

    while max_rounds is None or rounds < max_rounds:
        if stop_event is not None and stop_event.is_set():
            break
        with stage_span("scrape"):
            total += store.save(source())
        rounds += 1
        if max_rounds is not None and rounds >= max_rounds:
            break
        sleep(rate_s)
    return total
