"""Hacker News comment ingest.

Reference: ``client/scraper.py`` — a headless-Firefox Selenium loop that
loads ``news.ycombinator.com/newcomments``, extracts ``div.commtext``
texts in-page (``client/hn_scraper.js:3-9``), appends them to the
comment DB and sleeps ``rate`` seconds (default 600, ~30 posts/10 min —
``client/README.md:85``), with a catch-up wait derived from the last
stored timestamp on restart (``scraper.py:78-86``).

Here the ingest loop is a small host-side pipeline stage over a
pluggable *source*:

- :class:`SeleniumHNSource` — behavior parity with the reference
  (requires ``selenium`` + Firefox; unavailable in this image, so it is
  import-gated and raises a clear error at construction),
- :class:`SyntheticSource` — deterministic offline comment generator
  for tests/benchmarks and the zero-egress environment.

The loop itself (:func:`run_scraper`) is source-agnostic and can be run
in a thread (the reference runs it as a subprocess, ``main.py:38``).
"""

from __future__ import annotations

import datetime as _dt
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from svoc_tpu.io.comment_store import CommentStore

#: Default scrape period in seconds (``scraper.py:21``).
DEFAULT_RATE_S = 600

HN_URL = "https://news.ycombinator.com/newcomments"
#: The DOM selector extracted in-page (``client/hn_scraper.js:3``).
COMMENT_SELECTOR = "div.commtext"


class ScrapeTimeout(RuntimeError):
    """A scrape wait ran out — the portable equivalent of selenium's
    ``TimeoutException`` (which is import-gated in this image)."""


def _timeout_types() -> tuple:
    """Exception classes that mean 'the wait expired': always our own
    :class:`ScrapeTimeout`, plus selenium's when the package exists."""
    try:
        from selenium.common.exceptions import TimeoutException

        return (ScrapeTimeout, TimeoutException)
    except ImportError:
        return (ScrapeTimeout,)


class SeleniumHNSource:
    """Live HN source with the reference's Selenium behavior, hardened
    for graceful degradation (ISSUE 3): a wait timeout or one bad post
    skips THAT unit of work, counts a ``scrape_faults`` metric, and the
    scrape keeps going — a slow HN page must never kill the ingest loop.

    ``driver`` injects a ready webdriver (fake-driver tests, remote
    grids); without it the reference's headless Firefox is built (and
    the selenium import is gated with a clear error).
    """

    def __init__(
        self,
        headless: bool = True,
        timeout_s: float = 10.0,
        driver=None,
    ):
        if driver is None:
            try:
                from selenium import webdriver
                from selenium.webdriver.firefox.options import Options
            except ImportError as e:  # pragma: no cover — selenium not baked in
                raise RuntimeError(
                    "SeleniumHNSource needs the 'selenium' package and a "
                    "Firefox driver; use SyntheticSource in offline "
                    "environments"
                ) from e
            options = Options()
            if headless:
                options.add_argument("--headless")
            driver = webdriver.Firefox(options=options)
        self._driver = driver
        self._timeout_s = timeout_s

    #: The reference's in-page extraction (``hn_scraper.js:3-9``) — one
    #: driver round-trip for the whole page.
    _EXTRACT_SCRIPT = (
        "return Array.from(document.querySelectorAll('div.commtext'))"
        ".map(e => e.textContent.trim());"
    )

    def __call__(self) -> List[str]:
        from svoc_tpu.utils.metrics import registry as _metrics

        d = self._driver
        d.get(HN_URL)
        try:
            posts = self._wait_for_posts()
        except _timeout_types():
            # Whole page empty/slow past the deadline: skip this round
            # (the loop sleeps and retries next period) instead of
            # propagating out of the scraper thread.
            _metrics.counter("scrape_faults", labels={"stage": "page"}).add(1)
            return []
        # Fast path: one in-page script for all posts (the reference's
        # extraction; ~200 elements read per element would be ~200
        # driver round-trips).  A script failure degrades to the
        # per-element loop below, which can skip individual bad posts.
        script = getattr(d, "execute_script", None)
        if script is not None:
            try:
                return [t for t in script(self._EXTRACT_SCRIPT) if t]
            except Exception:
                _metrics.counter(
                    "scrape_faults", labels={"stage": "page"}
                ).add(1)
        out: List[str] = []
        for el in posts:
            try:
                text = self._post_text(el)
            except Exception:
                # One stale/timed-out post (WebDriverWait-style expiry,
                # DOM churn mid-read) skips that post only.
                _metrics.counter(
                    "scrape_faults", labels={"stage": "post"}
                ).add(1)
                continue
            if text:
                out.append(text)
        return out

    def _wait_for_posts(self):
        """The reference's ``WebDriverWait(presence_of_element_located)``
        page wait (``client/scraper.py:25-42``), as a portable poll so
        injected fake drivers exercise it too; raises
        :class:`ScrapeTimeout` on expiry."""
        deadline = time.monotonic() + self._timeout_s
        while True:
            # By.CSS_SELECTOR's literal value — no selenium import needed.
            posts = self._driver.find_elements("css selector", COMMENT_SELECTOR)
            if posts:
                return posts
            if time.monotonic() >= deadline:
                raise ScrapeTimeout(
                    f"no {COMMENT_SELECTOR!r} within {self._timeout_s}s"
                )
            time.sleep(min(0.25, max(self._timeout_s / 10.0, 0.01)))

    @staticmethod
    def _post_text(element) -> str:
        # The same per-node extraction the reference runs in-page
        # (hn_scraper.js:3-9): textContent, trimmed.
        return (element.get_attribute("textContent") or "").strip()

    def close(self) -> None:
        self._driver.quit()


class SyntheticSource:
    """Deterministic offline comment batches (HN-comment-shaped text)."""

    _VOCAB = (
        "the a this compiler startup latency throughput rust python jax "
        "tpu actually interesting scale database network kernel cache "
        "memory model vector consensus oracle distributed blockchain "
        "performance benchmark thread async await parse build deploy"
    ).split()

    def __init__(self, batch: int = 30, seed: int = 0):
        self.batch = batch
        self._rng = np.random.default_rng(seed)

    def __call__(self) -> List[str]:
        out = []
        for _ in range(self.batch):
            k = int(self._rng.integers(8, 60))
            out.append(" ".join(self._rng.choice(self._VOCAB, size=k)))
        return out


def catch_up_delay_s(
    last_timestamp: Optional[str], rate_s: float, now: Optional[float] = None
) -> float:
    """Seconds to sleep before the first scrape so restarts keep the
    cadence (``scraper.py:78-86``): wait out the remainder of the period
    that started at the last stored comment."""
    if not last_timestamp:
        return 0.0
    try:
        parsed = _dt.datetime.fromisoformat(last_timestamp)
    except ValueError:
        return 0.0
    if parsed.tzinfo is None:
        # sqlite CURRENT_TIMESTAMP stores naive UTC (the reference
        # compares against utcnow, scraper.py:81) — don't let
        # .timestamp() reinterpret it in the local zone.
        parsed = parsed.replace(tzinfo=_dt.timezone.utc)
    last = parsed.timestamp()
    now = time.time() if now is None else now
    elapsed = now - last
    if elapsed < 0 or elapsed >= rate_s:
        return 0.0
    return rate_s - elapsed


def run_scraper(
    store: CommentStore,
    source: Callable[[], Sequence[str]],
    rate_s: float = DEFAULT_RATE_S,
    max_rounds: Optional[int] = None,
    stop_event: Optional[threading.Event] = None,
    sleep: Callable[[float], None] = time.sleep,
    fault_plan=None,
) -> int:
    """The scrape loop (``scraper.py:74-94``); returns comments stored.

    ``max_rounds``/``stop_event`` bound the reference's infinite loop
    for embedding in tests and the CLI.

    Degrades instead of dying: a source failure (network flap, browser
    crash, injected chaos) counts one ``scrape_faults{stage="round"}``
    and the loop sleeps on to the next round — ingest is the outermost
    failure domain and must outlive its transport.  ``fault_plan`` is
    the chaos hook (any object with ``fire(op)``, canonically a
    :class:`svoc_tpu.resilience.faults.FaultPlan`): consulted as op
    ``"scrape"`` each round, so chaos runs exercise exactly this
    degradation path.
    """
    total = 0
    delay = catch_up_delay_s(store.last_timestamp(), rate_s)
    if delay:
        sleep(delay)
    rounds = 0
    from svoc_tpu.utils.metrics import registry as _metrics
    from svoc_tpu.utils.metrics import stage_span

    while max_rounds is None or rounds < max_rounds:
        if stop_event is not None and stop_event.is_set():
            break
        with stage_span("scrape"):
            try:
                if fault_plan is not None:
                    fault_plan.fire("scrape")
                batch = source()
            except Exception:
                _metrics.counter(
                    "scrape_faults", labels={"stage": "round"}
                ).add(1)
                batch = ()
            if batch:
                total += store.save(batch)
        rounds += 1
        if max_rounds is not None and rounds >= max_rounds:
            break
        sleep(rate_s)
    return total
