"""Host-side ingest and chain I/O.

- :mod:`svoc_tpu.io.comment_store` — the durable comment database +
  circular window reader (the reference's sqlite layer,
  ``client/scraper.py:44-62`` + ``client/oracle_scheduler.py:44-69``).
- :mod:`svoc_tpu.io.scraper` — Hacker News ingest loop (Selenium-gated)
  with a synthetic offline source for benchmarks and tests.
- :mod:`svoc_tpu.io.chain` — the Starknet adapter: felt252↔float codec,
  account registry, read/write wrappers over a pluggable backend
  (real ``starknet.py`` RPC or the in-memory contract simulator).
"""

from svoc_tpu.io.comment_store import CommentStore
from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend

__all__ = ["CommentStore", "ChainAdapter", "LocalChainBackend"]
