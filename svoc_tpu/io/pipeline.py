"""Double-buffered host→device input pipeline.

The end-to-end loop is ``text → tokenize (host) → forward (device)``;
run serially the two stages add up.  :class:`PrefetchPipeline` overlaps
them with a background producer thread: while the device runs batch k,
the host tokenizes batch k+1 into a bounded queue.  The native C++
tokenizer (:mod:`svoc_tpu.runtime`) releases the GIL during its batch
call, so the overlap is real parallelism, not time-slicing.

This is the streaming equivalent of the reference's wall-clock loop
(``simulation_mode``, ``oracle_scheduler.py:163-171``) rebuilt for
throughput: the reference classifies 30 comments every 5 s; this
pipeline sustains the device's ingest rate.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional, Sequence, Tuple

import numpy as np


class PrefetchPipeline:
    """Iterate device-ready ``(ids, mask)`` batches ahead of consumption.

    Args:
      source: yields batches of texts (e.g. window reads or a scraper
        tail); exhaustion ends the pipeline.
      tokenizer: ``(texts, seq_len) → (ids, mask)`` (any tokenizer from
        :mod:`svoc_tpu.models.tokenizer` / :mod:`svoc_tpu.runtime`).
        ``None`` = raw mode: the source already yields device-ready
        items (e.g. pre-packed batches) that pass straight to
        ``device_put``.
      seq_len: fixed sequence length (static device shapes).
      depth: producer queue depth (2 = classic double buffering).
    """

    def __init__(
        self,
        source: Iterable[Sequence[str]],
        tokenizer: Optional[Callable],
        seq_len: int,
        depth: int = 2,
        device_put: Optional[Callable] = None,
        join_timeout_s: float = 5.0,
        lineage: Optional[str] = None,
    ):
        self._source = iter(source)
        self._tokenizer = tokenizer
        self._seq_len = seq_len
        #: Block lineage (``svoc_tpu.utils.events``): span lineage
        #: inheritance is thread-local and the producer runs on its own
        #: thread, so the caller passes the id explicitly and the
        #: producer's tokenize/h2d spans (and any producer_error event)
        #: stay joinable with the block that spawned the pipeline.
        self._lineage = lineage
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._device_put = device_put
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._join_timeout_s = join_timeout_s
        self._close_lock = threading.Lock()
        self._closed = False
        self._producer_leaked = False
        # Bottleneck instrumentation: where a timed loop's wall clock
        # actually goes is unknowable from throughput alone — these
        # counters split it into host produce time (tokenize + pack +
        # H2D on the producer thread) vs consumer starvation (queue-get
        # wait = the host could not keep the device fed).
        self._produced = 0
        self._produce_s = 0.0
        self._consumer_wait_s = 0.0
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _produce(self) -> None:
        import time

        from svoc_tpu.utils.metrics import stage_span

        try:
            for texts in self._source:
                if self._stop.is_set():
                    break
                t0 = time.perf_counter()
                if self._tokenizer is None:  # raw mode — item is ready
                    batch = texts
                else:
                    with stage_span("tokenize", lineage=self._lineage):
                        batch = self._tokenizer(list(texts), self._seq_len)
                if self._device_put is not None:
                    with stage_span("h2d", lineage=self._lineage):
                        batch = self._device_put(batch)
                self._produced += 1
                self._produce_s += time.perf_counter() - t0
                while not self._stop.is_set():
                    try:
                        self._queue.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced on the consumer side
            self._error = e
            # Flight-recorder record (docs/OBSERVABILITY.md §events): a
            # crashed producer is a first-class incident — the
            # postmortem monitor auto-bundles on it — not just a stats()
            # field nobody reads until the consumer re-raises.
            from svoc_tpu.utils.events import journal as _journal

            _journal.emit(
                "pipeline.producer_error",
                lineage=self._lineage,
                error=repr(e),
                produced=self._produced,
            )
        finally:
            while not self._stop.is_set():
                try:
                    self._queue.put(None, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        return self

    def __next__(self):
        import time

        t0 = time.perf_counter()
        item = self._queue.get()
        self._consumer_wait_s += time.perf_counter() - t0
        if item is None:
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def stats(self) -> dict:
        """``{produced, produce_s, consumer_wait_s, closed,
        producer_leaked, producer_error}`` — produce time is the
        producer thread's busy time per item (tokenize + pack +
        device_put); consumer wait is time the consumer spent blocked on
        an empty queue (≈0 when the device is the bottleneck, ≈the gap
        when the host is).  ``producer_leaked`` means the last
        ``close()`` gave up joining the producer (wedged in a blocking
        tokenizer/device_put) — the thread is daemon-dead weight, not
        silently forgotten; ``producer_error`` surfaces a crashed
        producer even when nothing iterates far enough to re-raise it."""
        return {
            "produced": self._produced,
            "produce_s": round(self._produce_s, 4),
            "consumer_wait_s": round(self._consumer_wait_s, 4),
            "closed": self._closed,
            "producer_leaked": self._producer_leaked,
            "producer_error": (
                repr(self._error) if self._error is not None else None
            ),
        }

    def close(self) -> None:
        """Stop the producer and reap it.  Idempotent: safe to call any
        number of times (``__exit__`` + explicit close + teardown); a
        re-close after a timed-out join re-joins, so a producer that
        eventually unwedges clears the leak flag."""
        with self._close_lock:
            self._closed = True
            self._stop.set()
            # Drain so the producer's blocked put can observe the stop.
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            if not self._thread.is_alive():
                self._producer_leaked = False  # reaped since last close
                return
            self._thread.join(timeout=self._join_timeout_s)
            leaked = self._thread.is_alive()
            if leaked and not self._producer_leaked:
                # Count the leak once per wedge (a later successful
                # close clears the flag, so a re-wedge counts again).
                from svoc_tpu.utils.metrics import registry as _metrics

                _metrics.counter("pipeline_producer_leaks").add(1)
            self._producer_leaked = leaked

    def __enter__(self) -> "PrefetchPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def window_source(
    store, *, window: int, limit: int, max_windows: Optional[int] = None
) -> Iterator[Sequence[str]]:
    """Yield circular comment windows from a
    :class:`svoc_tpu.io.comment_store.CommentStore` (the fetch loop's
    read stage, as a pipeline source)."""
    position = 0
    count = 0
    while max_windows is None or count < max_windows:
        comments, _dates, position = store.read_window(position, window, limit)
        if not comments:
            return
        yield comments
        count += 1
