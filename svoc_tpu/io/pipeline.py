"""Double-buffered host→device input pipeline.

The end-to-end loop is ``text → tokenize (host) → forward (device)``;
run serially the two stages add up.  :class:`PrefetchPipeline` overlaps
them with a background producer thread: while the device runs batch k,
the host tokenizes batch k+1 into a bounded queue.  The native C++
tokenizer (:mod:`svoc_tpu.runtime`) releases the GIL during its batch
call, so the overlap is real parallelism, not time-slicing.

This is the streaming equivalent of the reference's wall-clock loop
(``simulation_mode``, ``oracle_scheduler.py:163-171``) rebuilt for
throughput: the reference classifies 30 comments every 5 s; this
pipeline sustains the device's ingest rate.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional, Sequence, Tuple

import numpy as np


class PrefetchPipeline:
    """Iterate device-ready ``(ids, mask)`` batches ahead of consumption.

    Args:
      source: yields batches of texts (e.g. window reads or a scraper
        tail); exhaustion ends the pipeline.
      tokenizer: ``(texts, seq_len) → (ids, mask)`` (any tokenizer from
        :mod:`svoc_tpu.models.tokenizer` / :mod:`svoc_tpu.runtime`).
        ``None`` = raw mode: the source already yields device-ready
        items (e.g. pre-packed batches) that pass straight to
        ``device_put``.
      seq_len: fixed sequence length (static device shapes).
      depth: producer queue depth (2 = classic double buffering).
    """

    def __init__(
        self,
        source: Iterable[Sequence[str]],
        tokenizer: Optional[Callable],
        seq_len: int,
        depth: int = 2,
        device_put: Optional[Callable] = None,
    ):
        self._source = iter(source)
        self._tokenizer = tokenizer
        self._seq_len = seq_len
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._device_put = device_put
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        # Bottleneck instrumentation: where a timed loop's wall clock
        # actually goes is unknowable from throughput alone — these
        # counters split it into host produce time (tokenize + pack +
        # H2D on the producer thread) vs consumer starvation (queue-get
        # wait = the host could not keep the device fed).
        self._produced = 0
        self._produce_s = 0.0
        self._consumer_wait_s = 0.0
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _produce(self) -> None:
        import time

        from svoc_tpu.utils.metrics import stage_span

        try:
            for texts in self._source:
                if self._stop.is_set():
                    break
                t0 = time.perf_counter()
                if self._tokenizer is None:  # raw mode — item is ready
                    batch = texts
                else:
                    with stage_span("tokenize"):
                        batch = self._tokenizer(list(texts), self._seq_len)
                if self._device_put is not None:
                    with stage_span("h2d"):
                        batch = self._device_put(batch)
                self._produced += 1
                self._produce_s += time.perf_counter() - t0
                while not self._stop.is_set():
                    try:
                        self._queue.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced on the consumer side
            self._error = e
        finally:
            while not self._stop.is_set():
                try:
                    self._queue.put(None, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        return self

    def __next__(self):
        import time

        t0 = time.perf_counter()
        item = self._queue.get()
        self._consumer_wait_s += time.perf_counter() - t0
        if item is None:
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def stats(self) -> dict:
        """``{produced, produce_s, consumer_wait_s}`` — produce time is
        the producer thread's busy time per item (tokenize + pack +
        device_put); consumer wait is time the consumer spent blocked on
        an empty queue (≈0 when the device is the bottleneck, ≈the gap
        when the host is)."""
        return {
            "produced": self._produced,
            "produce_s": round(self._produce_s, 4),
            "consumer_wait_s": round(self._consumer_wait_s, 4),
        }

    def close(self) -> None:
        self._stop.set()
        # Drain so the producer's blocked put can observe the stop.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)

    def __enter__(self) -> "PrefetchPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def window_source(
    store, *, window: int, limit: int, max_windows: Optional[int] = None
) -> Iterator[Sequence[str]]:
    """Yield circular comment windows from a
    :class:`svoc_tpu.io.comment_store.CommentStore` (the fetch loop's
    read stage, as a pipeline source)."""
    position = 0
    count = 0
    while max_windows is None or count < max_windows:
        comments, _dates, position = store.read_window(position, window, limit)
        if not comments:
            return
        yield comments
        count += 1
