"""Starknet chain adapter: typed reads/writes over a pluggable backend.

Reference: ``client/contract.py`` — felt252↔float codec (``:35-53``),
per-oracle ``Account`` registry loaded from ``data/sepolia.json``
(``:61-90``), typed ``call_*`` read wrappers (``:131-190``), sequential
per-oracle signed writes (``:200-264``), and index↔address resolution
(``:95-123``).

The rebuild splits this into:

- :class:`ChainBackend` — the protocol: ``call(fn) -> felts`` and
  ``invoke(caller, fn, **kwargs)``.
- :class:`LocalChainBackend` — the in-memory contract simulator
  (:class:`svoc_tpu.consensus.state.OracleConsensusContract`) speaking
  the same felt calldata; the test/simulation double for the Starknet
  VM (replaces the reference's Sepolia round-trip *and* its Cairo
  test-VM impersonation harness).
- :class:`StarknetBackend` — the real Sepolia path via ``starknet.py``
  with the reference's V3 resource bounds; import-gated so the
  framework works in zero-egress environments.
- :class:`ChainAdapter` — the typed API used by the command layer,
  protocol-identical for both backends.

Addresses are plain ints (the felt address space); the adapter formats
hex like the reference's ``to_hex`` where string forms are exposed.
"""

from __future__ import annotations

import asyncio
import functools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Protocol, Sequence, Tuple

import numpy as np

from svoc_tpu.consensus.state import ContractError, OracleConsensusContract
from svoc_tpu.ops.fixedpoint import (
    encode_matrix,
    encode_vector,
    fwsad_to_float,
    wsad_to_felt,
)

#: Reference V3 transaction resource bounds (``client/contract.py:29-32``).
RESOURCE_BOUND_L1_GAS = (259806, 153060543928007)

_fault_point = None


def _fire_fault_point(name: str, **kwargs) -> None:
    """Fire a named fault point (docs/RESILIENCE.md §fault-surface).

    The adapter's RPC boundaries are part of the chaos fuzzer's
    surface, but ``durability/chainlog.py`` imports this module — a
    top-level import back into the durability package would be
    circular, so the hook binds lazily (the declarations live in
    :mod:`svoc_tpu.durability.faultspace`).  One cached-global check
    per signed tx when disarmed."""
    global _fault_point
    if _fault_point is None:
        from svoc_tpu.durability.faultspace import fault_point

        _fault_point = fault_point
    _fault_point(name, **kwargs)


class ChainCommitError(RuntimeError):
    """A commit loop failed mid-way: earlier txs ARE on chain.

    The reference's sequential per-oracle submit
    (``client/contract.py:200-208``) has no rollback — a failure after
    k transactions leaves k oracle predictions committed.  This error
    carries that accounting so callers can surface it instead of
    guessing from a traceback.
    """

    def __init__(
        self, committed: int, total: int, failed_oracle, cause,
        sent_count: Optional[int] = None,
    ):
        self.committed = committed
        self.total = total
        self.failed_oracle = failed_oracle
        self.cause = cause
        #: Transactions actually landed by the failing ATTEMPT.  Equals
        #: ``committed - start`` on the plain path, but diverges when
        #: quarantine ``skip`` slots sit inside the attempted range
        #: (``committed`` is a fleet INDEX for resume; skipped slots
        #: advance it without sending a tx).  ``None`` when the raiser
        #: did not supply it — consumers must fall back to the index
        #: delta (``committed - start``), NEVER to ``committed``, which
        #: on a resumed attempt would overstate landed txs and credit
        #: a zero-progress failure as breaker progress.
        self.sent_count = sent_count
        super().__init__(
            f"commit failed at oracle {failed_oracle!r} after "
            f"{committed}/{total} transactions: {cause}"
        )


class BatchCommitUnsupported(RuntimeError):
    """A fleet commit cannot run as ONE batched RPC — the caller must
    take the per-tx loop instead (ALWAYS counted:
    ``commit_batch_fallback{reason=}``, docs/RESILIENCE.md
    §batched-commits).  Raised BEFORE any chain mutation or WAL record,
    so falling back is always safe."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        self.detail = detail
        super().__init__(
            f"batched commit unavailable ({reason})"
            + (f": {detail}" if detail else "")
        )


def to_hex(x: int) -> str:
    return f"0x{x:0x}"


def from_hex(x: str) -> int:
    return int(x, 16)


class ChainBackend(Protocol):
    def call(self, function_name: str) -> Any: ...

    def call_as(self, caller: int, function_name: str) -> Any: ...

    def invoke(self, caller: int, function_name: str, /, **kwargs) -> None: ...


class LocalChainBackend:
    """In-memory chain: the contract simulator behind the felt ABI.

    Values cross this boundary exactly as they would cross the real one
    — wsad ints two's-complement-wrapped around the felt prime — so the
    adapter's codec path is exercised identically for sim and Sepolia.
    """

    def __init__(self, contract: OracleConsensusContract):
        self.contract = contract

    # -- reads: mirror the Cairo view entrypoints --------------------------

    def call(self, function_name: str) -> Any:
        c = self.contract
        if function_name == "get_consensus_value":
            return [wsad_to_felt(x) for x in c.get_consensus_value()]
        if function_name == "get_skewness":
            return [wsad_to_felt(x) for x in c.get_skewness()]
        if function_name == "get_kurtosis":
            return [wsad_to_felt(x) for x in c.get_kurtosis()]
        if function_name == "get_first_pass_consensus_reliability":
            return wsad_to_felt(c.get_first_pass_consensus_reliability())
        if function_name == "get_second_pass_consensus_reliability":
            return wsad_to_felt(c.get_second_pass_consensus_reliability())
        if function_name == "consensus_active":
            return c.consensus_active
        if function_name == "get_admin_list":
            return list(c.get_admin_list())
        if function_name == "get_oracle_list":
            return list(c.get_oracle_list())
        if function_name == "get_predictions_dimension":
            return c.get_predictions_dimension()
        if function_name == "get_replacement_propositions":
            return list(c.get_replacement_propositions())
        raise KeyError(f"unknown view function {function_name!r}")

    def call_as(self, caller: int, function_name: str) -> Any:
        if function_name == "get_oracle_value_list":
            # Same encoding the chain would use: wsad values prime-wrapped
            # to felt252 (contract.cairo:772-798 returns FeltVectors).
            return [
                (addr, [wsad_to_felt(x) for x in vec], enabled, reliable)
                for addr, vec, enabled, reliable in (
                    self.contract.get_oracle_value_list(caller)
                )
            ]
        raise KeyError(f"unknown caller-view function {function_name!r}")

    # -- writes: the three invoke entrypoints ------------------------------

    def invoke(self, caller: int, function_name: str, /, **kwargs) -> None:
        c = self.contract
        if function_name == "update_prediction":
            c.update_prediction(caller, kwargs["prediction"], encoding="felt")
        elif function_name == "update_proposition":
            c.update_proposition(caller, kwargs["proposition"])
        elif function_name == "vote_for_a_proposition":
            c.vote_for_a_proposition(
                caller, kwargs["which_admin"], kwargs["support_his_proposition"]
            )
        else:
            raise KeyError(f"unknown invoke function {function_name!r}")

    def update_predictions_batched(
        self,
        callers: Sequence[int],
        predictions: Sequence[Sequence[int]],
    ) -> int:
        """The commit plane's ONE-RPC fleet entrypoint
        (docs/RESILIENCE.md §batched-commits): one backend call carries
        every (caller, felt payload) pair, with the EXACT sequential
        per-tx semantics (a mid-fleet panic raises
        :class:`svoc_tpu.consensus.state.BatchTxError` with the failed
        index; the prefix IS applied — chain semantics, no rollback).

        Unlike :meth:`invoke_update_predictions_batch` (the ≥64-fleet
        throughput path), this uses ``on_uncertified="sequential"``:
        the RPC-count contract is the point, so an uncertifiable batch
        runs the exact engine per tx INSIDE the one call instead of
        bouncing back to N adapter-level RPCs."""
        return self.contract.update_predictions_batch(
            callers,
            predictions,
            encoding="felt",
            on_uncertified="sequential",
        )

    def invoke_update_predictions_batch(
        self,
        callers: Sequence[int],
        predictions: Sequence[Sequence[int]],
        on_uncertified: str = "raise",
    ) -> int:
        """Fleet-scale commit: same sequential-tx semantics as looping
        ``invoke(…, "update_prediction")`` caller by caller, at O(1)
        golden-engine recomputes (:mod:`svoc_tpu.consensus.batch`).
        Only the local simulator offers this — the real chain has no
        batched entrypoint, so :class:`StarknetBackend` keeps the
        per-tx loop.  Default ``on_uncertified="raise"``: the adapter
        reruns its own per-tx loop rather than holding its lock across
        an O(N)-recompute fallback."""
        return self.contract.update_predictions_batch(
            callers,
            predictions,
            encoding="felt",
            on_uncertified=on_uncertified,
        )


class StarknetBackend:
    """Sepolia JSON-RPC backend (``client/contract.py`` semantics).

    Reads go through one ABI-resolved contract on the node client;
    writes re-resolve the contract with the *caller's* account as
    provider and submit a signed ``invoke_v3`` with the reference's
    fixed resource bounds (``client/contract.py:211-264``).
    """

    def __init__(
        self,
        node_url: str,
        deployed_address: int,
        accounts: Dict[int, Any],
        client: Any = None,
    ):
        try:
            from starknet_py.contract import Contract
            from starknet_py.net.client_models import ResourceBounds
            from starknet_py.net.full_node_client import FullNodeClient
        except ImportError as e:  # pragma: no cover — package present in CI mocks
            raise RuntimeError(
                "StarknetBackend needs the 'starknet.py' package; use "
                "LocalChainBackend for simulation"
            ) from e
        self._Contract = Contract
        self._bounds = ResourceBounds(*RESOURCE_BOUND_L1_GAS)
        self.client = client if client is not None else FullNodeClient(node_url=node_url)
        self.deployed_address = deployed_address
        self.accounts = accounts  # address -> starknet Account
        self._read_contract = asyncio.run(
            Contract.from_address(
                provider=self.client, address=deployed_address
            )
        )
        #: ABI is immutable per (caller, address) — cache the resolved
        #: contract per account so a commit cycle costs one RPC per tx,
        #: not two (client/contract.py re-resolves every time; that is
        #: a reference inefficiency, not semantics).
        self._caller_contracts: Dict[int, Any] = {}

    def call(self, function_name: str) -> Any:
        return asyncio.run(
            self._read_contract.functions[function_name].call()
        )[0]

    def _caller_contract(self, caller: int):
        contract = self._caller_contracts.get(caller)
        if contract is None:
            contract = asyncio.run(
                self._Contract.from_address(
                    provider=self.accounts[caller], address=self.deployed_address
                )
            )
            self._caller_contracts[caller] = contract
        return contract

    def call_as(self, caller: int, function_name: str) -> Any:
        contract = self._caller_contract(caller)
        return asyncio.run(contract.functions[function_name].call())[0]

    def invoke(self, caller: int, function_name: str, /, **kwargs) -> None:
        contract = self._caller_contract(caller)
        asyncio.run(
            contract.functions[function_name].invoke_v3(
                **kwargs, l1_resource_bounds=self._bounds
            )
        )


class DeployedContract:
    """Result of :func:`declare_and_deploy` — what ``contract_info.json``
    records (``client/data/contract_info.json:2-4``)."""

    def __init__(self, class_hash: int, address: int):
        self.class_hash = class_hash
        self.address = address

    def contract_info(self, rpc_url: str) -> Dict[str, str]:
        """The ``contract_info.json`` payload for this deployment."""
        return {
            "rpc": rpc_url,
            "declared_address": to_hex(self.class_hash),
            "deployed_address": to_hex(self.address),
        }


def declare_and_deploy(
    account: Any,
    cfg: Any,
    compiled_contract: str,
    compiled_contract_casm: Optional[str] = None,
    auto_estimate: bool = True,
) -> DeployedContract:
    """Declare the Sierra/CASM contract and deploy an instance with the
    consensus configuration frozen in the constructor calldata — the
    reference's manual Argent/starkli flow
    (``contract/README.md:41-66``) as one call.

    ``account`` is the paying ``starknet.py`` Account; ``cfg`` a
    :class:`svoc_tpu.io.deploy.DeployConfig`.  Both transactions are
    awaited to acceptance; the result carries the class hash and the
    deployed address (what ``contract_info.json`` stores).
    """
    try:
        from starknet_py.contract import Contract
    except ImportError as e:  # pragma: no cover — package present in CI mocks
        raise RuntimeError(
            "declare_and_deploy needs the 'starknet.py' package; use "
            "LocalChainBackend for simulation"
        ) from e

    from svoc_tpu.io.deploy import constructor_args

    async def _run():
        declare_result = await Contract.declare_v3(
            account=account,
            compiled_contract=compiled_contract,
            compiled_contract_casm=compiled_contract_casm,
            auto_estimate=auto_estimate,
        )
        await declare_result.wait_for_acceptance()
        deploy_result = await declare_result.deploy_v3(
            constructor_args=constructor_args(cfg),
            auto_estimate=auto_estimate,
        )
        await deploy_result.wait_for_acceptance()
        return declare_result, deploy_result

    declare_result, deploy_result = asyncio.run(_run())
    return DeployedContract(
        class_hash=int(declare_result.class_hash),
        address=int(deploy_result.deployed_contract.address),
    )


def load_account_data(path: str) -> Tuple[List[dict], List[dict]]:
    """Parse the ``data/sepolia.json`` layout (``client/contract.py:61-71``,
    template at ``client/README.md:38-77``): parallel hex-string lists
    ``admins_addresses``/``admins_private_keys`` and
    ``oracles_addresses``/``oracles_private_keys`` (3 admins, 8 oracles
    in the reference deployment)."""
    with open(path) as f:
        data = json.load(f)
    admins = [
        {"address": a, "private_key": k}
        for a, k in zip(
            data["admins_addresses"], data["admins_private_keys"], strict=True
        )
    ]
    oracles = [
        {"address": a, "private_key": k}
        for a, k in zip(
            data["oracles_addresses"], data["oracles_private_keys"], strict=True
        )
    ]
    return admins, oracles


def load_contract_info(path: str) -> Tuple[str, int, int]:
    """Parse ``data/contract_info.json`` (``client/README.md:22-30``):
    ``(rpc_url, declared_address, deployed_address)``."""
    with open(path) as f:
        info = json.load(f)
    return (
        info["rpc"],
        from_hex(info["declared_address"]),
        from_hex(info["deployed_address"]),
    )


def build_starknet_accounts(
    client: Any, admins: Sequence[dict], oracles: Sequence[dict]
) -> Dict[int, Any]:
    """``Account`` objects keyed by int address for every admin and
    oracle entry (``client/contract.py:73-84``)."""
    from starknet_py.net.account.account import Account
    from starknet_py.net.models.chains import StarknetChainId
    from starknet_py.net.signer.stark_curve_signer import KeyPair

    accounts: Dict[int, Any] = {}
    for entry in list(admins) + list(oracles):
        accounts[from_hex(entry["address"])] = Account(
            client=client,
            address=entry["address"],
            key_pair=KeyPair.from_private_key(entry["private_key"]),
            chain=StarknetChainId.SEPOLIA,
        )
    return accounts


def starknet_backend_from_files(
    contract_info_path: str, accounts_path: str
) -> "StarknetBackend":
    """The full reference bootstrap (``retrieve_account_data``,
    ``client/contract.py:61-90``): RPC client from ``contract_info.json``,
    per-identity accounts from ``sepolia.json``, ABI-resolved contract."""
    from starknet_py.net.full_node_client import FullNodeClient

    rpc, _declared, deployed = load_contract_info(contract_info_path)
    client = FullNodeClient(node_url=rpc)
    admins, oracles = load_account_data(accounts_path)
    accounts = build_starknet_accounts(client, admins, oracles)
    return StarknetBackend(rpc, deployed, accounts, client=client)


def _atomic(fn):
    """Serialize one adapter operation (backend call/invoke + its cache
    write) on the adapter lock.  Deliberately NOT applied to the
    composite loops (``update_all_the_predictions``, ``resume``): their
    inner ops each lock individually, so a long chain commit never
    monopolizes the adapter — interleaving at transaction granularity
    is exactly what the real chain permits anyway."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


class ChainAdapter:
    """The typed chain API (``call_*`` / ``invoke_*`` parity).

    Thread-safe at operation granularity: each read or signed tx is
    atomic under the adapter lock (protecting the in-memory contract
    simulator's state machine and the read cache), while composites
    interleave at tx granularity like the real chain."""

    #: rel₂ trajectory ring size (~4 h of 1-per-minute resumes).
    REL2_HISTORY = 256

    def __init__(self, backend: ChainBackend):
        self.backend = backend
        #: Last-read cache, the ``globalState.remote_*`` equivalent
        #: (``client/common.py:43-55``) — rehydrated by ``resume``.
        self.cache: Dict[str, Any] = {}
        #: (monotonic_s, rel₂) samples appended on every second-pass
        #: reliability read.  The LEVEL of rel₂ cannot detect a
        #: coordinated majority capture (after takeover it reads the
        #: adversary band's dispersion — healthy); the TRAJECTORY shows
        #: the approach (docs/ALGORITHM.md §5 breakdown curve), so the
        #: console and web UI surface the trend.
        self.rel2_history: deque = deque(maxlen=self.REL2_HISTORY)
        self._lock = threading.RLock()

    def cache_snapshot(self) -> Dict[str, Any]:
        """Consistent copy of the read cache for UI rendering — safe
        against a concurrent ``resume`` rehydrating it key by key."""
        with self._lock:
            return dict(self.cache)

    # -- reads (client/contract.py:131-190) --------------------------------

    @_atomic
    def call_consensus(self) -> List[float]:
        v = [fwsad_to_float(x) for x in self.backend.call("get_consensus_value")]
        self.cache["consensus"] = v
        return v

    @_atomic
    def call_skewness(self) -> List[float]:
        v = [fwsad_to_float(x) for x in self.backend.call("get_skewness")]
        self.cache["skewness"] = v
        return v

    @_atomic
    def call_kurtosis(self) -> List[float]:
        v = [fwsad_to_float(x) for x in self.backend.call("get_kurtosis")]
        self.cache["kurtosis"] = v
        return v

    @_atomic
    def call_first_pass_consensus_reliability(self) -> float:
        v = fwsad_to_float(
            self.backend.call("get_first_pass_consensus_reliability")
        )
        self.cache["reliability_first_pass"] = v
        return v

    @_atomic
    def call_second_pass_consensus_reliability(self) -> float:
        v = fwsad_to_float(
            self.backend.call("get_second_pass_consensus_reliability")
        )
        self.cache["reliability_second_pass"] = v
        self.rel2_history.append((time.monotonic(), v))
        return v

    @_atomic
    def peek_second_pass_reliability(self) -> float:
        """Same read as :meth:`call_second_pass_consensus_reliability`
        but WITHOUT feeding the rel₂ trajectory ring (or the cache) —
        for high-frequency machine readers like the fleet supervisor at
        auto-loop cadence.  The ring is sized for ~1-per-minute
        operator reads (``REL2_HISTORY``); a 5 s supervision loop
        appending to it would shrink the 30-minute capture-slide alarm
        window to minutes and mask a slow coordinated slide."""
        return fwsad_to_float(
            self.backend.call("get_second_pass_consensus_reliability")
        )

    def rel2_trend(self, window_s: float = 1800.0) -> Dict[str, Any]:
        """Trajectory summary of the second-pass reliability over the
        trailing ``window_s``: ``delta`` (latest − window start),
        ``falling`` (delta below −0.05 — the operator alarm condition:
        capture approaches as a rel₂ SLIDE, docs/ALGORITHM.md §5),
        ``n`` samples considered, and the ``history`` values."""
        with self._lock:
            samples = list(self.rel2_history)
        now = time.monotonic()
        window = [v for t, v in samples if now - t <= window_s]
        if len(window) < 2:
            return {"delta": 0.0, "falling": False, "n": len(window),
                    "history": window}
        delta = window[-1] - window[0]
        return {
            "delta": delta,
            "falling": delta < -0.05,
            "n": len(window),
            "history": window,
        }

    @_atomic
    def call_consensus_active(self) -> bool:
        v = bool(self.backend.call("consensus_active"))
        self.cache["consensus_active"] = v
        return v

    @_atomic
    def call_admin_list(self) -> List:
        v = self.backend.call("get_admin_list")
        self.cache["admin_list"] = v
        return v

    @_atomic
    def call_oracle_list(self) -> List:
        v = self.backend.call("get_oracle_list")
        self.cache["oracle_list"] = v
        return v

    @_atomic
    def call_dimension(self) -> int:
        v = int(self.backend.call("get_predictions_dimension"))
        self.cache["dimension"] = v
        return v

    @_atomic
    def call_replacement_propositions(self) -> List:
        v = self.backend.call("get_replacement_propositions")
        self.cache["replacement_propositions"] = v
        return v

    @_atomic
    def call_oracle_value_list(self, caller) -> List:
        """Admin-only raw dump, decoded: ``(address, [floats], enabled,
        reliable)`` per oracle (``client/contract.py:188-190``)."""
        v = [
            (addr, [fwsad_to_float(x) for x in vec], enabled, reliable)
            for addr, vec, enabled, reliable in self.backend.call_as(
                caller, "get_oracle_value_list"
            )
        ]
        self.cache["oracle_value_list"] = v
        return v

    @_atomic
    def call_oracle_value_list_wsad(self, caller) -> List:
        """Like :meth:`call_oracle_value_list` but with EXACT wsad ints
        (felt calldata two's-complement-decoded, no float round trip) —
        the console's ``wsad_to_string`` rendering needs the stored
        integer: ~28 % of wsad values lose an ulp through
        float-and-back, which truncated display turns into a whole
        wrong digit (0.007000 → '0.006')."""
        from svoc_tpu.ops.fixedpoint import felt_to_wsad

        return [
            (addr, [felt_to_wsad(int(x)) for x in vec], enabled, reliable)
            for addr, vec, enabled, reliable in self.backend.call_as(
                caller, "get_oracle_value_list"
            )
        ]

    @_atomic
    def get_the_predictions(self) -> List[List[int]]:
        """The EXACT felt vectors currently stored on chain, one per
        oracle slot — the WAL reconciler's landed/stranded witness
        (docs/RESILIENCE.md §durability): a commit intent whose payload
        digest matches its slot's read landed before the crash; a
        mismatch means the slot still holds the previous block's value
        and the tx is stranded.  Admin-gated like
        ``get_oracle_value_list`` (the raw per-oracle dump is the only
        entrypoint that exposes stored values); bulk by design — the
        reconciler reads the fleet ONCE per cycle instead of paying
        two RPCs per slot.  Propagates chain errors — the reconciler
        classifies those as *unknown*, never as stranded."""
        admins = self.backend.call("get_admin_list")
        if not admins:
            raise RuntimeError("contract has no admins to read values as")
        rows = self.backend.call_as(admins[0], "get_oracle_value_list")
        return [[int(x) for x in vec] for _addr, vec, _en, _rel in rows]

    def get_the_prediction(self, slot: int) -> List[int]:
        """One slot of :meth:`get_the_predictions`; raises
        ``IndexError`` for an out-of-range slot."""
        rows = self.get_the_predictions()
        if not 0 <= int(slot) < len(rows):
            raise IndexError(f"slot {slot} outside [0, {len(rows)})")
        return rows[int(slot)]

    # -- index/address resolution (client/contract.py:95-123) --------------

    def address_to_oracle_index(self, address) -> int:
        return self.call_oracle_list().index(address)

    def oracle_index_to_address(self, index: int):
        return self.call_oracle_list()[index]

    def address_to_admin_index(self, address) -> int:
        return self.call_admin_list().index(address)

    def admin_index_to_address(self, index: int):
        return self.call_admin_list()[index]

    # -- writes (client/contract.py:200-264) -------------------------------

    @staticmethod
    def _count_rpc(mode: str, n: int = 1) -> None:
        """Commit-plane RPC accounting (``chain_commit_rpcs{mode=}``,
        process registry — ``bench_hotpath.py`` and ``make
        hotpath-smoke`` assert the batched plane pays 1 per claim-cycle
        where the tx plane pays N).  Lazy import: chain I/O must stay
        importable without the metrics plane."""
        from svoc_tpu.utils.metrics import registry as _metrics

        _metrics.counter("chain_commit_rpcs", labels={"mode": mode}).add(n)

    @staticmethod
    def _count_fallback(reason: str) -> None:
        """The batched commit plane degrading is counted under the same
        family as retry.py's resume machinery
        (``commit_batch_fallback{reason=}``, docs/RESILIENCE.md
        §batched-commits) — fallbacks are counted, never silent."""
        from svoc_tpu.utils.metrics import registry as _metrics

        _metrics.counter(
            "commit_batch_fallback", labels={"reason": reason}
        ).add(1)

    @_atomic
    def invoke_update_prediction(self, oracle_address, prediction) -> None:
        _fire_fault_point(
            "chain.tx.pre_invoke", payload={"fn": "update_prediction"}
        )
        self._count_rpc("tx")
        self.backend.invoke(
            oracle_address,
            "update_prediction",
            prediction=encode_vector(prediction),
        )

    #: Fleets at or above this size take the backend's batched commit
    #: when it has one (the local simulator); below it the per-tx loop
    #: keeps the reference's tx-granular interleaving observable.
    BATCH_COMMIT_THRESHOLD = 64

    def update_all_the_predictions(
        self,
        predictions: Sequence,
        *,
        batch: Optional[bool] = None,
        start: int = 0,
        skip: Sequence[int] = (),
        lineage: Optional[str] = None,
        on_intent: Optional[Callable[[int, Any, List[int]], None]] = None,
        on_landed: Optional[Callable[[int], None]] = None,
    ) -> int:
        """One signed tx per oracle, in oracle-list order
        (``client/contract.py:200-208``); returns the tx count *sent by
        this call*.

        Each account signs sequentially (its nonce space advances one tx
        at a time; the next oracle's tx is only submitted after the
        previous returned).  A failure mid-loop raises
        :class:`ChainCommitError` with the partial-commit count — the
        earlier transactions are on chain and are NOT rolled back.

        ``start`` resumes a partially-committed fleet: oracles before
        ``start`` are skipped (their txs are already on chain — see
        :func:`svoc_tpu.resilience.retry.commit_fleet_with_resume`).
        ``ChainCommitError.committed`` is always ABSOLUTE (the failed
        oracle's fleet index, counting the resumed prefix), so
        ``start=e.committed`` re-sends exactly the stranded suffix and
        never duplicates a landed tx.

        ``skip`` holds ABSOLUTE fleet indices whose tx must not be
        sent at all (the quarantine gate's refusals,
        docs/ROBUSTNESS.md): skipped slots are passed over without a
        transaction and WITHOUT counting into the returned tx count,
        while ``committed``/resume indices keep counting them as fleet
        positions — ``start=e.committed`` resume semantics are
        unchanged.  A non-empty ``skip`` forces the per-tx loop (the
        batched entrypoint commits a contiguous caller range).

        ``batch=None`` auto-selects the backend's batched fleet commit
        (same sequential semantics, O(1) golden recomputes — see
        :meth:`svoc_tpu.consensus.state.OracleConsensusContract.update_predictions_batch`)
        when the remaining suffix is ≥ ``BATCH_COMMIT_THRESHOLD``;
        ``True``/``False`` force it on/off.

        ``lineage`` tags the ``commit`` stage span with the fleet
        block's lineage id (``svoc_tpu.utils.events``) so the span is
        joinable into the block's audit record.

        ``on_intent(idx, oracle, felts)`` / ``on_landed(idx)`` are the
        commit-intent WAL's per-tx hooks (docs/RESILIENCE.md
        §durability): the intent hook runs IMMEDIATELY before each tx
        with the exact felt payload about to be signed, the landed hook
        immediately after the invoke returns.  Hooks force the per-tx
        loop (intent granularity IS the tx granularity).  A hook
        exception propagates unwrapped — a WAL that cannot persist the
        intent must stop the commit ("no durable intent, no tx"), and
        that is an infrastructure failure, not the oracle's.
        """
        from svoc_tpu.utils.metrics import stage_span

        with stage_span("commit", lineage=lineage):
            return self._update_all_the_predictions(
                predictions, batch=batch, start=start, skip=skip,
                on_intent=on_intent, on_landed=on_landed,
            )

    @_atomic
    def _invoke_prediction_felts(self, oracle_address, felts: List[int]) -> None:
        """Pre-encoded twin of :meth:`invoke_update_prediction` — the
        WAL path encodes once, journals the felts, then signs the SAME
        payload (digest in the log must equal digest on the wire)."""
        # The signed-tx RPC boundary: an injected ``error`` here is the
        # transport fault the retry/resume machinery must absorb; a
        # ``kill`` leaves a durable intent whose tx never went out.
        _fire_fault_point(
            "chain.tx.pre_invoke", payload={"fn": "update_prediction"}
        )
        self._count_rpc("tx")
        self.backend.invoke(
            oracle_address, "update_prediction", prediction=felts
        )

    def update_predictions_batched(
        self,
        predictions: Sequence,
        *,
        start: int = 0,
        skip: Sequence[int] = (),
        lineage: Optional[str] = None,
        wal=None,
    ) -> int:
        """ONE chain RPC carrying the fleet's whole payload
        (docs/RESILIENCE.md §batched-commits): the batched commit plane
        behind ``commit_mode="batched"``.  Identical chain state,
        journal events, and failure accounting as the per-tx loop —
        only the RPC and WAL-record granularity change (N→1 and
        2N→2 per clean cycle).

        Raises :class:`BatchCommitUnsupported` — BEFORE any mutation or
        WAL record — when the plane cannot run as one RPC: the backend
        has no ``update_predictions_batched`` entrypoint (Sepolia's
        per-account signing, chaos wrappers) or ``skip`` holds
        quarantined slots (the batched entrypoint commits a contiguous
        caller range).  The caller counts the fallback
        (``commit_batch_fallback{reason=}``) and reruns per tx.

        ``wal`` (a :class:`svoc_tpu.durability.wal.WALCycle`): the
        cycle-open record already carries the full payload matrix, so
        ONE fsynced ``intent_batch`` covers the whole attempt before
        the RPC and one ``landed_batch`` records it after — on a
        mid-batch failure the applied prefix is recorded durably before
        the error propagates, and the restart reconciler classifies
        ``landed_batch`` slots exactly like per-tx ``landed`` ones.

        A mid-fleet failure raises :class:`ChainCommitError` with the
        per-tx path's exact accounting (``committed`` = absolute failed
        index, ``sent_count`` = txs this attempt landed); a malformed
        prediction is THAT tx's failure after the prefix commits, as in
        the per-tx loop.
        """
        skip_set = frozenset(int(i) for i in skip)
        if skip_set:
            raise BatchCommitUnsupported(
                "skip_slots",
                f"{len(skip_set)} quarantined slot(s) force tx granularity",
            )
        batched_invoke = getattr(
            self.backend, "update_predictions_batched", None
        )
        if batched_invoke is None:
            raise BatchCommitUnsupported(
                "unsupported", type(self.backend).__name__
            )
        from svoc_tpu.utils.metrics import stage_span

        with stage_span("commit", lineage=lineage):
            oracles = self.call_oracle_list()
            total = min(len(oracles), len(predictions))
            if not 0 <= start <= total:
                raise ValueError(f"start={start} outside [0, {total}]")
            # Vectorized felt encode, per-tx error semantics: the first
            # malformed row truncates the batch — its prefix commits,
            # then the failure surfaces at that tx's absolute index
            # with the original codec exception as cause.
            encoded = encode_matrix(
                np.asarray(predictions, dtype=np.float64)[start:total],
                on_error="none",
            )
            felts: List[List[int]] = []
            codec_failure = None
            for t, row in enumerate(encoded, start=start):
                if row is None:
                    try:
                        encode_vector(predictions[t])
                        cause: Exception = ValueError(
                            "prediction has no felt encoding"
                        )
                    except Exception as e:  # noqa: BLE001 — the real codec error
                        cause = e
                    codec_failure = (t, cause)
                    break
                felts.append(row)
            slots = list(range(start, start + len(felts)))
            sent = 0
            if felts:
                if wal is not None:
                    # One durable intent for the whole batch ("no
                    # durable intent, no tx" at batch granularity); WAL
                    # append failures propagate unwrapped, before the
                    # RPC, exactly like the per-tx hook contract.
                    wal.intent_batch(slots)
                from svoc_tpu.consensus.state import (
                    BatchNotCertified,
                    BatchTxError,
                )

                # The one-RPC boundary of the batched plane: the batch
                # intent is durable, the RPC has not gone out yet.
                _fire_fault_point(
                    "chain.batch.pre_rpc", payload={"n": len(felts)}
                )
                self._count_rpc("batch")
                # Bounded work on the local simulator (one certified
                # sweep, or the exact engine in-place for uncertifiable
                # batches) — held under the adapter lock like the
                # throughput batch path.
                with self._lock:
                    try:
                        sent = batched_invoke(
                            oracles[start : start + len(felts)], felts
                        )
                    except BatchNotCertified as e:
                        # A "raise"-mode backend refused BEFORE any
                        # mutation; the already-journaled batch intent
                        # is harmless (the reconciler digest-classifies
                        # intents without landed records).
                        raise BatchCommitUnsupported(
                            "uncertified", str(e)
                        ) from e
                    except BatchTxError as e:
                        if wal is not None and e.index > 0:
                            wal.landed_batch(slots[: e.index])
                        raise ChainCommitError(
                            committed=start + e.index,
                            total=total,
                            failed_oracle=e.oracle_address,
                            cause=e.cause,
                            sent_count=e.index,
                        ) from e
                if wal is not None:
                    wal.landed_batch(slots)
            if codec_failure is not None:
                t, cause = codec_failure
                raise ChainCommitError(
                    committed=start + sent,
                    total=total,
                    failed_oracle=oracles[t],
                    cause=cause,
                    sent_count=sent,
                ) from cause
            return sent

    def _update_all_the_predictions(
        self,
        predictions: Sequence,
        *,
        batch: Optional[bool] = None,
        start: int = 0,
        skip: Sequence[int] = (),
        on_intent: Optional[Callable[[int, Any, List[int]], None]] = None,
        on_landed: Optional[Callable[[int], None]] = None,
    ) -> int:
        oracles = self.call_oracle_list()
        total = min(len(oracles), len(predictions))
        if not 0 <= start <= total:
            raise ValueError(f"start={start} outside [0, {total}]")
        skip_set = frozenset(int(i) for i in skip)
        if skip_set and not all(0 <= i < total for i in skip_set):
            raise ValueError(f"skip indices {sorted(skip_set)} outside [0, {total})")
        batched_invoke = getattr(
            self.backend, "invoke_update_predictions_batch", None
        )
        wal_hooks = on_intent is not None or on_landed is not None
        if batch is None:
            batch = (
                not skip_set
                and not wal_hooks
                and batched_invoke is not None
                and total - start >= self.BATCH_COMMIT_THRESHOLD
            )
        if batch and skip_set:
            raise ValueError(
                "batch commit cannot skip slots (contiguous caller "
                "range) — use batch=False with skip"
            )
        if batch and wal_hooks:
            raise ValueError(
                "batch commit cannot journal per-tx intents — use "
                "batch=False with on_intent/on_landed"
            )
        if batch:
            if batched_invoke is None:
                raise ValueError(
                    "backend has no batched commit (Sepolia submits one "
                    "signed tx per oracle) — use batch=False"
                )
            from svoc_tpu.consensus.state import BatchNotCertified, BatchTxError

            # Per-tx codec semantics: a malformed prediction (NaN, junk)
            # is THAT tx's failure after the prefix commits, exactly as
            # in the per-tx loop — not a whole-batch abort.  Indices
            # here are ABSOLUTE fleet positions (the resumed prefix
            # counts), matching ChainCommitError's accounting.
            felts = []
            codec_failure = None
            for t, p in enumerate(predictions[start:total], start=start):
                try:
                    felts.append(encode_vector(p))
                except Exception as e:
                    codec_failure = (t, e)
                    break
            self._count_rpc("batch")
            # The fast path is bounded work (one device sweep + one
            # golden recompute) — safe to hold the adapter lock for.
            # An UNCERTIFIED batch raises before any mutation, and the
            # O(N)-golden-recompute fallback runs through the ordinary
            # per-tx loop below instead, which locks per transaction —
            # a long commit must never monopolize the adapter
            # (the _atomic design note).
            fell_through = False
            with self._lock:
                try:
                    committed = batched_invoke(
                        oracles[start : start + len(felts)], felts
                    )
                except BatchTxError as e:
                    raise ChainCommitError(
                        committed=start + e.index,
                        total=total,
                        failed_oracle=e.oracle_address,
                        cause=e.cause,
                        sent_count=e.index,
                    ) from e
                except BatchNotCertified:
                    # counted, never silent: the throughput batch path
                    # degrading to the exact per-tx loop is the same
                    # contract surface as retry.py's resume machinery
                    self._count_fallback("uncertified")
                    fell_through = True  # exact per-tx loop below
            if not fell_through:
                if codec_failure is not None:
                    t, cause = codec_failure
                    raise ChainCommitError(
                        committed=start + committed,
                        total=total,
                        failed_oracle=oracles[t],
                        cause=cause,
                        sent_count=committed,
                    ) from cause
                return committed
        n = 0
        for idx in range(start, total):
            if idx in skip_set:
                continue  # quarantined slot: no tx, no count
            oracle, prediction = oracles[idx], predictions[idx]
            felts = None
            if wal_hooks:
                # Encode BEFORE the intent hook: a codec failure is
                # this tx's failure (as on the plain path) and must not
                # leave a journaled intent for a payload that can never
                # be signed.
                try:
                    felts = encode_vector(prediction)
                except Exception as e:
                    raise ChainCommitError(
                        committed=idx,
                        total=total,
                        failed_oracle=oracle,
                        cause=e,
                        sent_count=n,
                    ) from e
                if on_intent is not None:
                    on_intent(idx, oracle, felts)  # WAL errors propagate
            try:
                if felts is not None:
                    self._invoke_prediction_felts(oracle, felts)
                else:
                    self.invoke_update_prediction(oracle, prediction)
            except ChainCommitError:
                raise
            except Exception as e:
                raise ChainCommitError(
                    committed=idx,
                    total=total,
                    failed_oracle=oracle,
                    cause=e,
                    sent_count=n,
                ) from e
            if on_landed is not None:
                on_landed(idx)
            n += 1
        return n

    @_atomic
    def invoke_update_proposition(
        self,
        admin_address,
        old_oracle_index: Optional[int] = None,
        new_oracle_address: Optional[int] = None,
    ) -> None:
        if (old_oracle_index is None) != (new_oracle_address is None):
            raise ValueError(
                "old_oracle_index and new_oracle_address must be both set "
                "or both None"
            )
        proposition = (
            None
            if old_oracle_index is None
            else (old_oracle_index, new_oracle_address)
        )
        self.backend.invoke(
            admin_address, "update_proposition", proposition=proposition
        )

    @_atomic
    def invoke_vote_for_a_proposition(
        self, admin_address, which_admin: int, support: bool
    ) -> None:
        self.backend.invoke(
            admin_address,
            "vote_for_a_proposition",
            which_admin=which_admin,
            support_his_proposition=support,
        )

    def resume(self) -> Dict[str, Any]:
        """Composite chain read-back (the ``resume`` command,
        ``client/web_interface.py:205-225``): refresh every cached view."""
        self.call_consensus_active()
        self.call_consensus()
        self.call_first_pass_consensus_reliability()
        self.call_second_pass_consensus_reliability()
        self.call_skewness()
        self.call_kurtosis()
        self.call_admin_list()
        self.call_oracle_list()
        self.call_dimension()
        try:
            self.call_replacement_propositions()
        except ContractError:
            # Contract deployed with replacement disabled; anything else
            # (RPC failures, codec bugs) propagates like the other reads.
            self.cache["replacement_propositions"] = None
        return self.cache_snapshot()
