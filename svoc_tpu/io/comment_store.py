"""Durable comment store + circular window reader.

Schema parity with the reference scraper's sqlite table
(``client/scraper.py:44-55``): ``comments(id INTEGER PRIMARY KEY
AUTOINCREMENT, comment TEXT NOT NULL, timestamp DATETIME DEFAULT
CURRENT_TIMESTAMP)``, so an existing reference database file can be
opened directly.

The circular window reader mirrors ``read_window_from_db``
(``client/oracle_scheduler.py:44-69``) including its quirks, which are
kept because the simulation cursor semantics depend on them:

- the cursor first advances by ``window`` *before* reading
  (``position = (position + PREDICTION_WINDOW) % N``),
- wraps to 0 whenever another full window would run past the end,
- the SQL fetch is capped at ``limit`` rows (the reference hard-codes
  ``LIMIT 30`` against a window constant of 50 — both are explicit
  parameters here, with the reference values as defaults).

``:memory:`` stores work too (handy for tests and the synthetic
pipeline); the connection is per-store and thread-confined like the
reference's short-lived connections.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import List, Optional, Sequence, Tuple

#: Reference constants (``client/common.py:15-16``, ``oracle_scheduler.py:61``).
PREDICTION_WINDOW = 50
SQL_FETCH_LIMIT = 30


class CommentStore:
    """SQLite-backed comment store with the reference's schema."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._init_db()

    def _init_db(self) -> None:
        with self._lock:
            self._conn.execute(
                """
                CREATE TABLE IF NOT EXISTS comments (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    comment TEXT NOT NULL,
                    timestamp DATETIME DEFAULT CURRENT_TIMESTAMP
                )
                """
            )
            self._conn.commit()

    def save(self, comments: Sequence[str]) -> int:
        """``save_to_db`` (``scraper.py:57-62``); returns rows inserted."""
        rows = [(c,) for c in comments if c]
        with self._lock:
            self._conn.executemany(
                "INSERT INTO comments (comment) VALUES (?)", rows
            )
            self._conn.commit()
        return len(rows)

    def count(self) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                "SELECT COUNT(id) FROM comments"
            ).fetchone()
        return int(n)

    def last_timestamp(self) -> Optional[str]:
        """Latest ingest time — the scraper's catch-up cursor
        (``scraper.py:78-86``)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT timestamp FROM comments ORDER BY id DESC LIMIT 1"
            ).fetchone()
        return row[0] if row else None

    def read_window(
        self,
        position: int,
        window: int = PREDICTION_WINDOW,
        limit: int = SQL_FETCH_LIMIT,
    ) -> Tuple[List[str], List[str], int]:
        """Circular window read (``oracle_scheduler.py:44-69``).

        Returns ``(comments, timestamps, new_position)``; the caller
        stores ``new_position`` as the simulation cursor
        (``globalState.simulation_step`` semantics).
        """
        n = self.count()
        if n == 0:
            return [], [], 0
        position = (position + window) % n
        if position + window >= n:
            position = 0
        with self._lock:
            rows = self._conn.execute(
                "SELECT comment, timestamp FROM comments "
                "WHERE id >= ? ORDER BY id ASC LIMIT ?",
                (position, limit),
            ).fetchall()
        return [r[0] for r in rows], [r[1] for r in rows], position

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    def __enter__(self) -> "CommentStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
