"""Contract deployment calldata.

The consensus configuration is frozen at deploy time in the Cairo
constructor's calldata (``contract/src/contract.cairo:236-265``; worked
example at ``contract/README.md:41-66``).  Layout, in order:

``[n_admins, *admins, enable_oracle_replacement, required_majority,
n_failing_oracles, constrained, unconstrained_max_spread(fwsad),
dimension, n_oracles, *oracles]``

:func:`constructor_calldata` builds that list from a typed config (the
shape :class:`svoc_tpu.consensus.state.OracleConsensusContract` takes),
and :func:`parse_constructor_calldata` inverts it — used to
cross-check a deployed contract against a local simulator, and
round-trip-tested against the reference test deployments.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

from svoc_tpu.ops.fixedpoint import fwsad_to_float, float_to_fwsad


@dataclass(frozen=True)
class DeployConfig:
    admins: Sequence[int]
    oracles: Sequence[int]
    enable_oracle_replacement: bool = True
    required_majority: int = 2
    n_failing_oracles: int = 2
    constrained: bool = True
    unconstrained_max_spread: float = 0.0
    dimension: int = 2


def constructor_calldata(cfg: DeployConfig) -> List[int]:
    """``cfg`` → felt calldata list (``contract.cairo:236-265`` order)."""
    return [
        len(cfg.admins),
        *[int(a) for a in cfg.admins],
        int(cfg.enable_oracle_replacement),
        int(cfg.required_majority),
        int(cfg.n_failing_oracles),
        int(cfg.constrained),
        float_to_fwsad(cfg.unconstrained_max_spread),
        int(cfg.dimension),
        len(cfg.oracles),
        *[int(o) for o in cfg.oracles],
    ]


def constructor_args(cfg: DeployConfig) -> dict:
    """``cfg`` → ABI-typed constructor kwargs for ``starknet.py``'s
    ``deploy_v3`` (the typed view of :func:`constructor_calldata` —
    starknet.py serializes the Spans with their length prefixes, so the
    wire calldata equals the felt list)."""
    return {
        "admins": [int(a) for a in cfg.admins],
        "enable_oracle_replacement": bool(cfg.enable_oracle_replacement),
        "required_majority": int(cfg.required_majority),
        "n_failing_oracles": int(cfg.n_failing_oracles),
        "constrained": bool(cfg.constrained),
        "unconstrained_max_spread": float_to_fwsad(cfg.unconstrained_max_spread),
        "dimension": int(cfg.dimension),
        "oracles": [int(o) for o in cfg.oracles],
    }


def parse_constructor_calldata(calldata: Sequence[int]) -> DeployConfig:
    """Inverse of :func:`constructor_calldata` (validates lengths)."""
    data = [int(x) for x in calldata]
    i = 0
    n_admins = data[i]; i += 1
    admins = data[i : i + n_admins]; i += n_admins
    enable = bool(data[i]); i += 1
    majority = data[i]; i += 1
    n_failing = data[i]; i += 1
    constrained = bool(data[i]); i += 1
    max_spread = fwsad_to_float(data[i]); i += 1
    dimension = data[i]; i += 1
    n_oracles = data[i]; i += 1
    oracles = data[i : i + n_oracles]; i += n_oracles
    if i != len(data):
        raise ValueError(
            f"calldata has {len(data)} felts, layout consumed {i}"
        )
    return DeployConfig(
        admins=admins,
        oracles=oracles,
        enable_oracle_replacement=enable,
        required_majority=majority,
        n_failing_oracles=n_failing,
        constrained=constrained,
        unconstrained_max_spread=max_spread,
        dimension=dimension,
    )


def simulator_from_calldata(calldata: Sequence[int]):
    """Deploy an in-memory contract simulator from chain calldata — the
    local twin of a real deployment."""
    from svoc_tpu.consensus.state import OracleConsensusContract

    cfg = parse_constructor_calldata(calldata)
    return OracleConsensusContract(
        admins=list(cfg.admins),
        oracles=list(cfg.oracles),
        enable_oracle_replacement=cfg.enable_oracle_replacement,
        required_majority=cfg.required_majority,
        n_failing_oracles=cfg.n_failing_oracles,
        constrained=cfg.constrained,
        unconstrained_max_spread=cfg.unconstrained_max_spread,
        dimension=cfg.dimension,
    )
