"""Oracle fleet simulation: generators, bootstrap model, Monte-Carlo bench."""

from svoc_tpu.sim.generators import (  # noqa: F401
    beta_mode,
    generate_beta_oracles,
    generate_gaussian_oracles,
    generate_kumaraswamy_oracles,
    kumaraswamy_mode,
)
from svoc_tpu.sim.montecarlo import (  # noqa: F401
    benchmark,
    benchmark_unconstrained,
    launch_benchmark,
)
from svoc_tpu.sim.multimodal import (  # noqa: F401
    benchmark_multimodal,
    em_mixture,
    generate_multimodal_oracles,
    multimodal_breakdown_curve,
    multimodal_consensus,
    select_k,
)
from svoc_tpu.sim.oracle import gen_oracle_predictions  # noqa: F401
