"""The stochastic bootstrap oracle model, vmapped over the fleet.

Reference: ``gen_oracles_predictions`` (``client/oracle_scheduler.py:
73-92``) — for each of N oracles, the first ``n_failing`` produce
``uniform(0,1)^M`` (the adversarial/failing model) and the rest average
a random ``subset_size``-element bootstrap sample of the current
sentiment-analysis window; the fleet is then shuffled to hide which
oracles failed.

Here the whole fleet is generated in one fused graph: ``vmap`` over the
oracle axis with per-oracle PRNG keys, gathers into the shared window,
fixed shapes throughout.  At N=1024 this is a [N, S] gather + mean —
bandwidth-trivial, and shardable over the oracle axis
(:mod:`svoc_tpu.parallel`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gen_oracle_predictions(
    key,
    window: jnp.ndarray,
    n_oracles: int,
    n_failing: int,
    subset_size: int = 10,
):
    """Generate the fleet's predictions from a sentiment window.

    Args:
      key: PRNG key.
      window: ``[W, M]`` sentiment vectors (the prediction window,
        ``common.py:15-16``).
      n_oracles / n_failing: fleet shape (``common.py:8-9`` defaults 7/2).
      subset_size: bootstrap subset (``BOOTSTRAPING_SUBSET=10``).

    Returns:
      ``(values [n_oracles, M], honest_mask [n_oracles])`` post-shuffle.
    """
    w, m = window.shape
    n_honest = n_oracles - n_failing
    k_fail, k_boot, k_perm = jax.random.split(key, 3)

    failing_vals = jax.random.uniform(k_fail, (n_failing, m))

    def one_bootstrap(k):
        # random.sample semantics: without replacement
        # (oracle_scheduler.py:85)
        idx = jax.random.choice(k, w, shape=(subset_size,), replace=False)
        return jnp.mean(window[idx], axis=0)

    honest_vals = jax.vmap(one_bootstrap)(jax.random.split(k_boot, n_honest))

    values = jnp.concatenate([failing_vals, honest_vals], axis=0)
    honest = jnp.arange(n_oracles) >= n_failing
    perm = jax.random.permutation(k_perm, n_oracles)
    return values[perm], honest[perm]
