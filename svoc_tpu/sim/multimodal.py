"""Multimodal (mixture-model) oracle consensus — beyond-reference.

The reference *documents* this scenario and stops: K poles
``e_k``, each honest oracle follows pole k with probability ``p_k``
(``documentation/README.md:90-103`` — "w ~ Mult(1, p)", "f(x) ~
sum_k N(e_k, sigma_k) x 1_w") and then states "Currently, we do not
provide an algorithm for this specific modelization", leaving the
interpretation question open ("Take the biggest pole? Average of all
poles?").

This module provides the algorithm, TPU-first:

- :func:`generate_multimodal_oracles` — the documented generative
  model: honest oracles draw a pole from ``Mult(1, p)`` and sample
  ``N(e_k, sigma_k)`` (clipped to the constrained state space
  ``]0,1[^M`` when asked); failing oracles are uniform, identities
  shuffled — exactly the failure model of the unimodal fleets
  (``documentation/README.md:105-114``).
- :func:`em_mixture` — spherical-Gaussian EM with STATIC shapes: K
  components, fixed iteration count via ``lax.scan``, responsibilities
  by log-sum-exp — one fused XLA program, no data-dependent control
  flow, vmappable over Monte-Carlo trials.
- :func:`multimodal_consensus` — the estimator: EM fit, then the same
  fixed-count masking contract as the on-chain two-pass (the worst
  ``n_failing`` oracles by scaled distance-to-nearest-pole are flagged
  unreliable), a restricted re-estimate over the survivors, and BOTH
  answers to the reference's open question as policies:
  ``policy="dominant"`` returns the heaviest pole's center (robust
  default — an average of disagreeing poles is a value no oracle
  believes), ``policy="average"`` returns the weight-averaged center.

The Monte-Carlo comparison (:func:`benchmark_multimodal`) quantifies
why the mixture estimator exists: on a bimodal fleet the unimodal
two-pass rule (``contract.cairo:370-503`` semantics) centers between
the poles — its essence is supported by *neither* information source —
while the mixture estimator recovers the dominant pole.  See
``tests/test_multimodal.py`` for the pinned cells and
``examples/multimodal_demo.py`` for the runnable table.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "MixtureFit",
    "MultimodalResult",
    "generate_multimodal_oracles",
    "em_mixture",
    "multimodal_consensus",
    "select_k",
    "benchmark_multimodal",
    "multimodal_breakdown_curve",
]


def generate_multimodal_oracles(
    key,
    n_oracles: int,
    n_failing: int,
    poles,
    sigma,
    weights=None,
    constrained: bool = True,
):
    """The reference's documented multimodal generative model.

    Args:
        poles: ``[K, dim]`` pole centers ``e_k``.
        sigma: scalar, ``[K]``, or ``[K, dim]`` spread per pole.
        weights: ``[K]`` pole probabilities ``p`` (uniform when None).
        constrained: clip draws into the contract's open interval
            ``]0,1[^M`` (the Beta-modelled state space).

    Returns ``(values[n_oracles, dim], honest[n_oracles] bool,
    pole_of[n_oracles] int32)`` — ``pole_of`` is −1 for failing
    oracles; all three shuffled consistently so identities are hidden.
    """
    poles = jnp.asarray(poles, jnp.float32)
    k_components, dim = poles.shape
    sigma = jnp.broadcast_to(
        jnp.asarray(sigma, jnp.float32), (k_components, dim)
    )
    if weights is None:
        weights = jnp.full((k_components,), 1.0 / k_components, jnp.float32)
    else:
        weights = jnp.asarray(weights, jnp.float32)
        weights = weights / jnp.sum(weights)

    n_honest = n_oracles - n_failing
    k_pole, k_norm, k_unif, k_perm = jax.random.split(key, 4)
    pole_of_honest = jax.random.choice(
        k_pole, k_components, shape=(n_honest,), p=weights
    )
    noise = jax.random.normal(k_norm, (n_honest, dim))
    honest_vals = poles[pole_of_honest] + noise * sigma[pole_of_honest]
    failing_vals = jax.random.uniform(k_unif, (n_failing, dim))
    if constrained:
        eps = 1e-4
        honest_vals = jnp.clip(honest_vals, eps, 1.0 - eps)
        failing_vals = jnp.clip(failing_vals, eps, 1.0 - eps)

    values = jnp.concatenate([failing_vals, honest_vals], axis=0)
    honest = jnp.arange(n_oracles) >= n_failing
    pole_of = jnp.concatenate(
        [jnp.full((n_failing,), -1, jnp.int32), pole_of_honest.astype(jnp.int32)]
    )
    perm = jax.random.permutation(k_perm, n_oracles)
    return values[perm], honest[perm], pole_of[perm]


class MixtureFit(NamedTuple):
    """EM fit state: spherical Gaussians, one scalar spread each."""

    means: jnp.ndarray  # [K, dim]
    sigmas: jnp.ndarray  # [K]
    weights: jnp.ndarray  # [K]
    resp: jnp.ndarray  # [N, K] posterior responsibilities
    log_likelihood: jnp.ndarray  # scalar, mean per-point


def _log_resp(values, means, sigmas, weights):
    """``[N, K]`` log p(k | x_i) up to the per-point normalizer, and the
    per-point log-evidence (for the mean log-likelihood)."""
    dim = values.shape[1]
    d2 = jnp.sum((values[:, None, :] - means[None, :, :]) ** 2, axis=-1)
    log_pdf = (
        -0.5 * d2 / (sigmas[None, :] ** 2)
        - dim * jnp.log(sigmas[None, :])
        - 0.5 * dim * jnp.log(2.0 * jnp.pi)
    )
    joint = log_pdf + jnp.log(weights[None, :])
    evidence = jax.scipy.special.logsumexp(joint, axis=1, keepdims=True)
    return joint - evidence, evidence[:, 0]


@partial(jax.jit, static_argnames=("k_components", "n_iters"))
def em_mixture(
    values: jnp.ndarray,
    k_components: int,
    n_iters: int = 30,
    seed: int = 0,
    min_sigma: float = 1e-3,
) -> MixtureFit:
    """Spherical-Gaussian mixture EM, fully static for XLA.

    Initialization is k-means++-style but with a FIXED draw count (one
    ``lax.scan`` over K: each next center is the point farthest—in
    min-distance terms—from the centers chosen so far, seeded by a
    uniform first pick).  The EM loop is a second ``lax.scan`` with a
    fixed iteration count; spreads are floored at ``min_sigma`` so a
    component collapsing onto duplicated points cannot NaN the fit.
    """
    n, dim = values.shape
    key = jax.random.PRNGKey(seed)

    # -- init: farthest-point traversal (deterministic given seed) ----
    first = jax.random.randint(key, (), 0, n)
    init_means = jnp.zeros((k_components, dim), values.dtype)
    init_means = init_means.at[0].set(values[first])

    def pick(carry, k):
        means, min_d2 = carry
        d2 = jnp.sum((values - means[k - 1][None, :]) ** 2, axis=-1)
        min_d2 = jnp.minimum(min_d2, d2)
        nxt = jnp.argmax(min_d2)
        means = means.at[k].set(values[nxt])
        return (means, min_d2), None

    (init_means, _), _ = jax.lax.scan(
        pick,
        (init_means, jnp.full((n,), jnp.inf, values.dtype)),
        jnp.arange(1, k_components),
    )

    global_sigma = jnp.maximum(jnp.std(values), min_sigma)
    state0 = (
        init_means,
        jnp.full((k_components,), global_sigma, values.dtype),
        jnp.full((k_components,), 1.0 / k_components, values.dtype),
    )

    def em_step(state, _):
        means, sigmas, weights = state
        log_r, evidence = _log_resp(values, means, sigmas, weights)
        r = jnp.exp(log_r)  # [N, K]
        nk = jnp.sum(r, axis=0) + 1e-9  # [K]
        means = (r.T @ values) / nk[:, None]
        d2 = jnp.sum((values[:, None, :] - means[None, :, :]) ** 2, axis=-1)
        sigmas = jnp.sqrt(jnp.sum(r * d2, axis=0) / (nk * dim) + 1e-12)
        sigmas = jnp.maximum(sigmas, min_sigma)
        weights = nk / jnp.sum(nk)
        return (means, sigmas, weights), jnp.mean(evidence)

    (means, sigmas, weights), lls = jax.lax.scan(
        em_step, state0, None, length=n_iters
    )
    log_r, evidence = _log_resp(values, means, sigmas, weights)
    return MixtureFit(
        means=means,
        sigmas=sigmas,
        weights=weights,
        resp=jnp.exp(log_r),
        log_likelihood=jnp.mean(evidence),
    )


class MultimodalResult(NamedTuple):
    essence: jnp.ndarray  # [dim] — per the chosen policy
    pole_means: jnp.ndarray  # [K, dim] restricted re-estimate
    pole_weights: jnp.ndarray  # [K] share of RELIABLE oracles per pole
    pole_sigmas: jnp.ndarray  # [K]
    reliable: jnp.ndarray  # [N] bool — fixed-count mask
    pole_of: jnp.ndarray  # [N] int32 argmax-responsibility assignment
    fit: MixtureFit


@partial(
    jax.jit, static_argnames=("k_components", "n_failing", "n_iters", "policy")
)
def multimodal_consensus(
    values: jnp.ndarray,
    k_components: int,
    n_failing: int,
    n_iters: int = 30,
    policy: str = "dominant",
    seed: int = 0,
) -> MultimodalResult:
    """Mixture-aware two-pass consensus over a multimodal fleet.

    First pass: EM mixture fit; every oracle is scored by its scaled
    distance to the NEAREST pole (``min_k ||x - mu_k|| / sigma_k``) and
    the worst ``n_failing`` are flagged unreliable — the same
    fixed-count masking contract as the on-chain estimator
    (``contract.cairo:399-400``), which keeps shapes static and
    matches the reference's "exactly alpha percent fail" model.

    Second pass: pole means/weights are re-estimated over the reliable
    set only (restricted soft M-step), and the essence is produced per
    ``policy`` — ``"dominant"``: the heaviest pole's center (the
    robust answer to the reference's open question: an average of
    disagreeing poles is a value no oracle holds); ``"average"``: the
    weight-averaged center (the document's other candidate, kept for
    comparison).
    """
    if policy not in ("dominant", "average"):
        raise ValueError(f"policy {policy!r} not in dominant|average")
    fit = em_mixture(values, k_components, n_iters=n_iters, seed=seed)

    d = jnp.linalg.norm(
        values[:, None, :] - fit.means[None, :, :], axis=-1
    )  # [N, K]
    scaled = d / fit.sigmas[None, :]
    score = jnp.min(scaled, axis=1)  # distance to nearest pole
    # The shared fixed-count masking helper — same ranking + tie order
    # as the on-chain estimator (contract.cairo:345-363).
    from ..ops.sort import reliability_mask

    reliable = reliability_mask(score, n_failing)

    # Restricted soft re-estimate over the reliable set.
    r = fit.resp * reliable[:, None]
    nk = jnp.sum(r, axis=0) + 1e-9
    pole_means = (r.T @ values) / nk[:, None]
    dim = values.shape[1]
    d2 = jnp.sum((values[:, None, :] - pole_means[None, :, :]) ** 2, axis=-1)
    pole_sigmas = jnp.sqrt(jnp.sum(r * d2, axis=0) / (nk * dim) + 1e-12)
    pole_weights = nk / jnp.sum(nk)

    if policy == "dominant":
        essence = pole_means[jnp.argmax(pole_weights)]
    else:
        essence = jnp.sum(pole_weights[:, None] * pole_means, axis=0)

    return MultimodalResult(
        essence=essence,
        pole_means=pole_means,
        pole_weights=pole_weights,
        pole_sigmas=pole_sigmas,
        reliable=reliable,
        pole_of=jnp.argmax(fit.resp, axis=1).astype(jnp.int32),
        fit=fit,
    )


def select_k(
    values: jnp.ndarray,
    k_max: int = 8,
    n_iters: int = 30,
    seed: int = 0,
    min_support: int = 3,
) -> tuple:
    """Pick the pole count by BIC over ``K = 1..k_max``.

    The operator-facing answer to "how many poles does this fleet
    have?": each candidate K is one static-shape EM fit (compiled
    once, cached per K), scored by ``BIC = −2·N·mean_ll + p·ln N``
    with ``p = K·dim + K + (K−1)`` free parameters (means, spreads,
    weights).  Returns ``(best_k, bics)`` where ``bics[k-1]`` is the
    score for K=k (lower is better, ``inf`` = disqualified).

    Raw BIC is asymptotic and fails openly on small fleets: a
    component can collapse onto 1-2 points with its spread at the
    ``min_sigma`` floor, gaining ~``dim·ln(1/σ)`` log-likelihood per
    captured point and out-scoring the ``p·ln N`` penalty, so a
    7-oracle unimodal fleet would "select" K=6.  Two guards keep the
    answer meaningful:

    - a pole must be SUPPORTED: candidate Ks are capped at
      ``N // min_support`` (a "pole" followed by fewer than
      ``min_support`` oracles is not a pole — and the cap also bounds
      the console's compile sweep);
    - a fit whose smallest soft count ``n_k`` falls below 2 is
      disqualified (scored ``inf``) — that component is a collapsed
      singleton, not structure;
    - poles must be IDENTIFIABLE: a fit where two means are closer
      than ``2·(σ_i + σ_j)`` (≈4σ for equal spreads) is disqualified —
      overlapping components are one pole split in two;
    - selection is PARSIMONIOUS: a larger K wins only on *very strong*
      evidence, ``ΔBIC > 10`` against the incumbent (the Kass–Raftery
      scale) — on a 7-point fleet a lucky 2+5 split can edge BIC by
      ~2, which is noise, not a second pole.

    A unimodal fleet then selects K=1: the mixture machinery degrades
    gracefully to the reference's original single-pole model.
    """
    import math

    n, dim = values.shape
    k_max = max(1, min(k_max, n // max(min_support, 1) or 1))
    bics = []
    for k in range(1, k_max + 1):
        fit = em_mixture(values, k, n_iters=n_iters, seed=seed)
        if k > 1:
            if float(jnp.min(jnp.sum(fit.resp, axis=0))) < 2.0:
                bics.append(float("inf"))
                continue
            pair_d = jnp.linalg.norm(
                fit.means[:, None, :] - fit.means[None, :, :], axis=-1
            )
            sep = 2.0 * (fit.sigmas[:, None] + fit.sigmas[None, :])
            off_diag = ~jnp.eye(k, dtype=bool)
            if bool(jnp.any((pair_d < sep) & off_diag)):
                bics.append(float("inf"))
                continue
        p = k * dim + k + (k - 1)
        bics.append(-2.0 * float(fit.log_likelihood) * n + p * math.log(n))
    best_k = 1
    for k in range(2, len(bics) + 1):
        if bics[k - 1] < bics[best_k - 1] - 10.0:
            best_k = k
    return best_k, bics


def _pole_recovery_error(est_means, true_poles):
    """Mean over TRUE poles of the distance to the nearest estimated
    pole — permutation-free (label switching cannot inflate it)."""
    d = jnp.linalg.norm(
        true_poles[:, None, :] - est_means[None, :, :], axis=-1
    )
    return jnp.mean(jnp.min(d, axis=1))


@partial(
    jax.jit,
    static_argnames=("n_oracles", "n_failing", "k_components", "policy"),
)
def _multimodal_trials(
    keys,
    poles,
    sigma,
    weights,
    *,
    n_oracles: int,
    n_failing: int,
    k_components: int,
    policy: str,
):
    from ..consensus.kernel import ConsensusConfig, consensus_step

    cfg = ConsensusConfig(n_failing=n_failing, constrained=True)
    dominant = jnp.argmax(weights)

    def nearest(essence):
        d = jnp.linalg.norm(poles - essence[None, :], axis=-1)
        return jnp.min(d), jnp.argmin(d)

    def trial(key):
        values, honest, _ = generate_multimodal_oracles(
            key, n_oracles, n_failing, poles, sigma, weights
        )
        mm = multimodal_consensus(
            values, k_components, n_failing, policy=policy
        )
        uni = consensus_step(values, cfg)
        mm_near, mm_which = nearest(mm.essence)
        uni_near, uni_which = nearest(uni.essence)
        ident = jnp.all(mm.reliable == honest)
        pole_err = _pole_recovery_error(mm.pole_means, poles)
        return (
            mm_near,
            uni_near,
            mm_which == dominant,
            uni_which == dominant,
            ident,
            pole_err,
        )

    outs = jax.vmap(trial)(keys)
    mm_near, uni_near, mm_dom, uni_dom, ident, pole_err = outs
    return (
        jnp.mean(mm_near),
        jnp.mean(uni_near),
        jnp.mean(mm_dom.astype(jnp.float32)),
        jnp.mean(uni_dom.astype(jnp.float32)),
        jnp.mean(ident.astype(jnp.float32)),
        jnp.mean(pole_err),
    )


@partial(
    jax.jit,
    static_argnames=("n_oracles", "n_failing", "k_components"),
)
def _coordinated_trials(
    keys,
    poles,
    sigma,
    weights,
    adv_point,
    adv_spread,
    *,
    n_oracles: int,
    n_failing: int,
    k_components: int,
):
    dominant_pole = poles[jnp.argmax(weights)]

    def trial(key):
        k_gen, k_adv = jax.random.split(key)
        values, honest, _ = generate_multimodal_oracles(
            k_gen, n_oracles, n_failing, poles, sigma, weights
        )
        # Replace the uniform adversaries with a COORDINATED cluster: a
        # tight fake pole at adv_point (the attack the uniform failure
        # model of documentation/README.md:105-114 cannot mount).
        adv = adv_point[None, :] + adv_spread * jax.random.normal(
            k_adv, (n_oracles, values.shape[1])
        )
        # Same constrained state space as every other oracle draw: the
        # contract rejects values outside ]0,1[^M, so the modeled
        # attack must stay inside it too.
        adv = jnp.clip(adv, 1e-4, 1.0 - 1e-4)
        values = jnp.where(honest[:, None], values, adv)
        mm = multimodal_consensus(values, k_components, n_failing)
        err = jnp.linalg.norm(mm.essence - dominant_pole)
        on_honest = err < jnp.linalg.norm(mm.essence - adv_point)
        return err, on_honest

    err, on_honest = jax.vmap(trial)(keys)
    return jnp.mean(err), jnp.mean(on_honest.astype(jnp.float32))


def multimodal_breakdown_curve(
    key,
    poles,
    sigma,
    weights=None,
    n_oracles: int = 64,
    fractions=(0.1, 0.2, 0.3, 0.35, 0.45, 0.55),
    adv_point=None,
    adv_spread: float = 0.01,
    k_trials: int = 200,
) -> dict:
    """Breakdown of the MIXTURE estimator under coordinated adversaries.

    The adversaries form their own tight fake pole (the attack that
    actually threatens a clustering estimator — uniform failures just
    score badly against every pole and get masked).  The estimator fits
    K+1 components (the honest Ks plus one for the fake pole it must be
    allowed to represent) and masks the worst ``n_failing``; its
    essence follows the heaviest RELIABLE pole.  Expected phenomenology,
    measured here: while the adversary fraction is below the dominant
    honest pole's share the essence stays on the honest pole (the fake
    pole is fully masked — unlike the unimodal median there is no
    gradual drag); once the adversary cluster outweighs the dominant
    honest pole the dominance argmax flips and the essence jumps to the
    fake pole — a cliff at ``frac ≈ max_k w_k · (1 − frac)``, i.e. the
    mixture estimator's breakdown point is the dominant pole's own
    weight, NOT N/2.

    Returns ``{fraction: {"essence_err": ..., "on_honest_pole_pct":
    ...}}`` with errors measured against the dominant honest pole.
    """
    poles = jnp.asarray(poles, jnp.float32)
    if weights is None:
        weights = jnp.full((poles.shape[0],), 1.0 / poles.shape[0])
    else:
        weights = jnp.asarray(weights, jnp.float32)
        weights = weights / jnp.sum(weights)
    if adv_point is None:
        adv_point = jnp.full((poles.shape[1],), 0.95, jnp.float32)
    else:
        adv_point = jnp.asarray(adv_point, jnp.float32)
    out = {}
    for frac in fractions:
        n_failing = int(round(frac * n_oracles))
        keys = jax.random.split(jax.random.fold_in(key, n_failing), k_trials)
        err, on_honest = _coordinated_trials(
            keys,
            poles,
            jnp.asarray(sigma, jnp.float32),
            weights,
            adv_point,
            adv_spread,
            n_oracles=n_oracles,
            n_failing=n_failing,
            k_components=int(poles.shape[0]) + 1,
        )
        out[frac] = {
            "essence_err": float(err),
            "on_honest_pole_pct": float(on_honest) * 100.0,
        }
    return out


def benchmark_multimodal(
    key,
    poles,
    sigma,
    weights=None,
    n_oracles: int = 64,
    n_failing: int = 4,
    k_components: int | None = None,
    k_trials: int = 300,
    policy: str = "dominant",
) -> dict:
    """Monte-Carlo cell comparing the mixture estimator against the
    unimodal two-pass kernel on the documented multimodal model
    (methodology of ``documentation/README.md:222-246``: K trials,
    mean metrics).

    Two metrics make the comparison well-posed even when a trial's
    sample split disagrees with the population weights:

    - ``*_nearest_pole_error`` — distance from the essence to the
      nearest TRUE pole: "is the consensus a value some information
      source actually holds?".  With balanced, well-separated poles
      the unimodal smooth-median lands BETWEEN them (error ≈ half the
      pole distance, supported by no oracle) while the mixture
      estimator stays on a pole (error ≈ sigma).
    - ``*_dominant_pole_pct`` — how often the essence lies nearest the
      population-dominant pole: meaningful at asymmetric weights with
      enough oracles for the sample split to concentrate.

    Plus the mixture estimator's exact-identification rate and its
    permutation-free pole-recovery error.
    """
    poles = jnp.asarray(poles, jnp.float32)
    if weights is None:
        weights = jnp.full((poles.shape[0],), 1.0 / poles.shape[0])
    else:
        weights = jnp.asarray(weights, jnp.float32)
        weights = weights / jnp.sum(weights)
    if k_components is None:
        k_components = int(poles.shape[0])
    keys = jax.random.split(key, k_trials)
    mm_near, uni_near, mm_dom, uni_dom, ident, pole_err = _multimodal_trials(
        keys,
        poles,
        jnp.asarray(sigma, jnp.float32),
        weights,
        n_oracles=n_oracles,
        n_failing=n_failing,
        k_components=k_components,
        policy=policy,
    )
    return {
        "mixture_nearest_pole_error": float(mm_near),
        "unimodal_nearest_pole_error": float(uni_near),
        "mixture_dominant_pole_pct": float(mm_dom) * 100.0,
        "unimodal_dominant_pole_pct": float(uni_dom) * 100.0,
        "identification_success_pct": float(ident) * 100.0,
        "pole_recovery_error": float(pole_err),
    }
