"""Synthetic oracle-fleet generators with adversarial failure injection.

JAX-native equivalents of the reference's numpy prototypes in
``contract/drafts/beta_kumaraswamy_algorithm_demo copy.ipynb``
(``generate_beta_oracles`` / ``generate_2d_beta_oracles``) and
``contract/drafts/gaussian_distribution_for_tests.ipynb``
(``generate_2d_gaussian_oracles``), following the failure model of
``documentation/README.md:105-114``: a failing oracle is a uniform
draw over ]0,1[ (or a wide uniform in the unconstrained case), and the
fleet is shuffled so the failing identities are hidden.

All generators are fixed-shape and vmap-friendly: they return
``(values [n, dim], honest_mask [n])`` where ``honest_mask`` marks the
non-failing oracles *after* the shuffle (the ground truth that the
detection benchmark tries to recover).
"""

from __future__ import annotations

import zlib

import jax
import jax.numpy as jnp


def claim_seed(base_seed: int, claim_id) -> int:
    """Per-claim seed derivation for the multi-claim fabric
    (docs/FABRIC.md): ``N`` claims sharing one ``base_seed`` each get
    an independent, replayable oracle stream.

    Same discipline as the fault plan's injection keys
    (``resilience/faults.py``): the claim id is folded in via
    ``zlib.crc32(repr(claim_id))`` — NOT ``hash()``, which Python
    randomizes per process and would silently break cross-process
    replay — and mixed with the base seed by the plan's polynomial so
    nearby base seeds and nearby claim ids both decorrelate.  The
    result fits a ``jax.random.PRNGKey`` / ``np.random.default_rng``
    seed and is a pure function of ``(base_seed, claim_id)``.
    """
    crc = zlib.crc32(repr(claim_id).encode())
    mixed = (int(base_seed) * 1_000_003 + crc) & 0xFFFFFFFFFFFFFFFF
    # Fold to 32 bits: PRNGKey wants a word-sized seed, and the crc in
    # the low word alone would make claim streams independent of the
    # base seed for base_seed=0.
    return ((mixed >> 32) ^ mixed) & 0xFFFFFFFF


#: ``fold_in`` salt separating the per-claim key stream from the
#: per-oracle streams the claim keys later fold (``fold_in(claim_key,
#: 0)`` is the failing-slot permutation, ``i + 1`` the oracle streams —
#: the ``_fleet_body`` contract of ``parallel/sharded.py``).  crc32 of
#: a stable string — NOT ``hash()``, which Python randomizes per
#: process — masked to an int32-safe word so ``fold_in`` accepts it.
FLEET_CLAIM_SALT = zlib.crc32(b"svoc.fleet.claim") & 0x7FFFFFFF


def claim_fleet_keys(key, n_claims: int):
    """Per-claim PRNG keys ``[n_claims, 2]`` for the sharded claim-cube
    fleet (:mod:`svoc_tpu.parallel.claim_shard`): each claim's stream
    is keyed by its GLOBAL claim index off a crc32-salted fold of the
    base key, so the generated fleet cube is bitwise identical however
    — and whether — the (claim × oracle) mesh shards it.  The claim
    axis twin of the global-oracle-index keying the oracle-sharded
    ``_fleet_body`` already guarantees."""
    salted = jax.random.fold_in(key, FLEET_CLAIM_SALT)
    return jax.vmap(lambda i: jax.random.fold_in(salted, i))(
        jnp.arange(n_claims)
    )


def beta_mode(a: float, b: float) -> float:
    """Mode of Beta(a, b) — the essence under the constrained model
    (notebook ``beta_mode``; ``documentation/README.md:72-76``)."""
    return (a - 1.0) / (a + b - 2.0)


def kumaraswamy_mode(a: float, b: float) -> float:
    """Mode of Kumaraswamy(a, b) (notebook ``kumaraswamy_mode``)."""
    return ((a - 1.0) / (a * b - 1.0)) ** (1.0 / a)


def _shuffle(key, values: jnp.ndarray, honest: jnp.ndarray):
    """Shuffle oracles so failing identities are hidden
    (``np.random.shuffle`` in the notebook / ``oracle_scheduler.py:90``)."""
    perm = jax.random.permutation(key, values.shape[0])
    return values[perm], honest[perm]


def generate_beta_oracles(
    key,
    n_oracles: int,
    n_failing: int,
    a,
    b,
    dim: int = 1,
    fail_lo: float = 0.0,
    fail_hi: float = 1.0,
):
    """Beta-distributed honest oracles + uniform failing oracles.

    ``a``/``b`` may be scalars or per-dimension arrays (the notebook's
    2-D variant passes per-axis parameters).  ``fail_lo``/``fail_hi``
    bound the adversary draw — the defaults are the reference's
    symmetric ]0,1[ model; a narrow off-center band models a
    coordinated bias attack (:func:`generate_biased_beta_oracles`).
    """
    k_beta, k_unif, k_perm = jax.random.split(key, 3)
    a = jnp.broadcast_to(jnp.asarray(a, jnp.float32), (dim,))
    b = jnp.broadcast_to(jnp.asarray(b, jnp.float32), (dim,))
    honest_vals = jax.random.beta(
        k_beta, a[None, :], b[None, :], shape=(n_oracles - n_failing, dim)
    )
    failing_vals = jax.random.uniform(
        k_unif, (n_failing, dim), minval=fail_lo, maxval=fail_hi
    )
    values = jnp.concatenate([failing_vals, honest_vals], axis=0)
    honest = jnp.arange(n_oracles) >= n_failing
    return _shuffle(k_perm, values, honest)


def generate_biased_beta_oracles(
    key,
    n_oracles: int,
    n_failing: int,
    a,
    b,
    dim: int = 1,
    bias_lo: float = 0.85,
    bias_hi: float = 1.0,
):
    """Beta honest oracles + COORDINATED biased adversaries.

    The reference's failure model (uniform over ]0,1[,
    ``documentation/README.md:105-114``) is symmetric about the same
    center the honest mass concentrates on, so it cannot displace a
    median even in the majority — this variant models the attack that
    CAN: adversaries draw from a narrow corner band
    ``[bias_lo, bias_hi]^dim``, all pushing the same direction.  Used
    by :func:`svoc_tpu.sim.montecarlo.fleet_breakdown_curve` to measure
    the estimator's actual breakdown point (≈ N/2, the theoretical
    bound for any median-based rule).
    """
    return generate_beta_oracles(
        key, n_oracles, n_failing, a, b, dim=dim,
        fail_lo=bias_lo, fail_hi=bias_hi,
    )


def generate_kumaraswamy_oracles(
    key,
    n_oracles: int,
    n_failing: int,
    a,
    b,
    dim: int = 1,
):
    """Kumaraswamy(a, b) honest oracles via inverse-CDF sampling:
    ``X = (1 − (1 − U)^{1/b})^{1/a}``."""
    k_u, k_unif, k_perm = jax.random.split(key, 3)
    a = jnp.broadcast_to(jnp.asarray(a, jnp.float32), (dim,))
    b = jnp.broadcast_to(jnp.asarray(b, jnp.float32), (dim,))
    u = jax.random.uniform(
        k_u, (n_oracles - n_failing, dim), minval=1e-7, maxval=1.0 - 1e-7
    )
    honest_vals = (1.0 - (1.0 - u) ** (1.0 / b[None, :])) ** (1.0 / a[None, :])
    failing_vals = jax.random.uniform(k_unif, (n_failing, dim))
    values = jnp.concatenate([failing_vals, honest_vals], axis=0)
    honest = jnp.arange(n_oracles) >= n_failing
    return _shuffle(k_perm, values, honest)


def generate_gaussian_oracles(
    key,
    n_oracles: int,
    n_failing: int,
    mu,
    sigma,
    failing_spread: float = 10.0,
):
    """Unconstrained fleet: honest ~ N(mu, diag(sigma²)), failing ~
    uniform over ``mu ± failing_spread`` (the Gaussian fixture generator,
    ``gaussian_distribution_for_tests.ipynb``, used mu=[20,12],
    sigma=[3,2])."""
    mu = jnp.asarray(mu, jnp.float32)
    sigma = jnp.asarray(sigma, jnp.float32)
    dim = mu.shape[0]
    k_norm, k_unif, k_perm = jax.random.split(key, 3)
    honest_vals = (
        mu[None, :]
        + sigma[None, :] * jax.random.normal(k_norm, (n_oracles - n_failing, dim))
    )
    failing_vals = mu[None, :] + jax.random.uniform(
        k_unif, (n_failing, dim), minval=-failing_spread, maxval=failing_spread
    )
    values = jnp.concatenate([failing_vals, honest_vals], axis=0)
    honest = jnp.arange(n_oracles) >= n_failing
    return _shuffle(k_perm, values, honest)
