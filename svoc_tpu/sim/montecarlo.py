"""Monte-Carlo statistical benchmark of the consensus estimator.

TPU-native reproduction of the reference's estimator-quality benchmark
(``documentation/README.md:177-341``; notebook ``benchmark`` /
``launch_benchmark`` in ``beta_kumaraswamy_algorithm_demo copy.ipynb``):

- K independent trials, each drawing an oracle fleet with
  ``n_failing`` adversarial (uniform) members;
- *identification success* = the failing oracles are **exactly**
  identified by the rank-of-deviation-from-median rule
  (``documentation/README.md:204-209``);
- *reliability* = ``1 − 2·E‖median_identified − median_truth‖`` where
  both are restricted (masked) medians (``README.md:211-236``).

The reference runs K=300 python-loop trials; here a trial is a pure
function and the whole benchmark is one ``vmap``-ed, jit-compiled graph
over a key batch — K=10⁵ trials are cheap on a single TPU chip.

The published tables use the *true* component-wise median
(``np.median``), not the contract's smooth median — both identifiers
are provided (:func:`identify_failing_oracles` matches the notebook;
``use_kernel=True`` routes detection through the actual on-chain
two-pass rule of :mod:`svoc_tpu.consensus.kernel`).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict

import jax
import jax.numpy as jnp

from svoc_tpu.consensus.kernel import ConsensusConfig, consensus_step
from svoc_tpu.ops.stats import rank_array
from svoc_tpu.sim.generators import (
    generate_beta_oracles,
    generate_biased_beta_oracles,
    generate_gaussian_oracles,
)


def true_median(values: jnp.ndarray) -> jnp.ndarray:
    """``np.median`` semantics, component-wise over axis 0."""
    n = values.shape[0]
    s = jnp.sort(values, axis=0)
    if n % 2 == 1:
        return s[n // 2]
    return (s[n // 2 - 1] + s[n // 2]) / 2.0


def restricted_median(
    values: jnp.ndarray, mask: jnp.ndarray, m: int
) -> jnp.ndarray:
    """``np.median`` over the ``m`` unmasked rows (``m`` static).

    Mirrors the notebook's ``restricted_median`` (``documentation/
    README.md:211-213``): masked rows are pushed to +inf before the
    sort, so rows ``[0, m)`` of the sorted block are the active set.
    """
    x = jnp.where(mask[:, None], values, jnp.inf)
    s = jnp.sort(x, axis=0)
    if m % 2 == 1:
        return s[m // 2]
    return (s[m // 2 - 1] + s[m // 2]) / 2.0


def identify_failing_oracles(values: jnp.ndarray, n_failing: int) -> jnp.ndarray:
    """Healthy-oracle mask via rank of deviation from the median
    (``documentation/README.md:204-209``; ``oracle_scheduler.py:94-111``)."""
    med = true_median(values)
    dev = jnp.linalg.norm(values - med[None, :], axis=-1)
    _, ranks = rank_array(dev)
    return ranks >= n_failing


@partial(jax.jit, static_argnames=("n_oracles", "n_failing", "dim", "use_kernel"))
def _benchmark_trials(
    keys,
    a,
    b,
    *,
    n_oracles: int,
    n_failing: int,
    dim: int,
    use_kernel: bool,
):
    m = n_oracles - n_failing

    def trial(key):
        values, honest = generate_beta_oracles(
            key, n_oracles, n_failing, a, b, dim=dim
        )
        if use_kernel:
            out = consensus_step(
                values, ConsensusConfig(n_failing=n_failing, constrained=True)
            )
            guess = out.reliable
        else:
            guess = identify_failing_oracles(values, n_failing)
        success = jnp.all(guess == honest)
        pred = restricted_median(values, guess, m)
        truth = restricted_median(values, honest, m)
        dist = jnp.linalg.norm(pred - truth)
        return success, dist

    success, dist = jax.vmap(trial)(keys)
    return jnp.mean(success.astype(jnp.float32)), jnp.mean(dist)


def benchmark(
    key,
    a,
    b,
    n_oracles: int,
    n_failing: int,
    k_trials: int = 300,
    dim: int = 1,
    use_kernel: bool = False,
) -> Dict[str, float]:
    """One benchmark cell (notebook ``benchmark``, ``documentation/
    README.md:222-239``).  Returns percentages like the published tables."""
    keys = jax.random.split(key, k_trials)
    success_rate, mean_dist = _benchmark_trials(
        keys,
        jnp.asarray(a, jnp.float32),
        jnp.asarray(b, jnp.float32),
        n_oracles=n_oracles,
        n_failing=n_failing,
        dim=dim,
        use_kernel=use_kernel,
    )
    return {
        "identification_success_pct": float(success_rate) * 100.0,
        "reliability_pct": (1.0 - 2.0 * float(mean_dist)) * 100.0,
    }


def masked_mean(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Mean over unmasked rows — the unconstrained second-pass
    estimator (``nd_average`` over the reliable set,
    ``contract.cairo:406-420``)."""
    w = mask[:, None].astype(values.dtype)
    return jnp.sum(values * w, axis=0) / jnp.maximum(jnp.sum(w), 1.0)


@partial(
    jax.jit,
    static_argnames=(
        "n_oracles",
        "n_failing",
        "use_kernel",
        "max_spread",
        "failing_spread",
    ),
)
def _unconstrained_trials(
    keys,
    mu,
    sigma,
    *,
    n_oracles: int,
    n_failing: int,
    use_kernel: bool,
    max_spread: float,
    failing_spread: float,
):
    def trial(key):
        values, honest = generate_gaussian_oracles(
            key,
            n_oracles,
            n_failing,
            mu,
            sigma,
            failing_spread=failing_spread,
        )
        if use_kernel:
            out = consensus_step(
                values,
                ConsensusConfig(
                    n_failing=n_failing,
                    constrained=False,
                    max_spread=max_spread,
                ),
            )
            guess = out.reliable
            rel2 = out.reliability_second_pass
        else:
            guess = identify_failing_oracles(values, n_failing)
            rel2 = jnp.nan
        success = jnp.all(guess == honest)
        # Mean second pass (contract.cairo:406-420): the unconstrained
        # estimator is the average of the oracles believed honest.
        pred = masked_mean(values, guess)
        truth = masked_mean(values, honest)
        dist = jnp.linalg.norm(pred - truth)
        return success, dist, rel2

    success, dist, rel2 = jax.vmap(trial)(keys)
    return (
        jnp.mean(success.astype(jnp.float32)),
        jnp.mean(dist),
        jnp.mean(rel2),
    )


def benchmark_unconstrained(
    key,
    mu,
    sigma,
    n_oracles: int,
    n_failing: int,
    k_trials: int = 300,
    max_spread: float = 10.0,
    failing_spread: float = 10.0,
    use_kernel: bool = False,
) -> Dict[str, float]:
    """Estimator-quality Monte-Carlo for the UNCONSTRAINED (Gaussian,
    R^M) case — the ``gaussian_algorithm_demo.ipynb`` experiment the
    reference never tabulated (its published tables are Beta-only).

    Same trial structure as :func:`benchmark`, with the unconstrained
    estimator semantics of ``contract.cairo:370-434``: detection by rank
    of deviation, **mean** (not median) second pass, and reliability
    normalized by ``max_spread`` — ``1 − E‖pred − truth‖ / max_spread``,
    the Monte-Carlo analogue of the on-chain
    ``1 − min(ms, √(mean qr)) / ms`` (``contract.cairo:365-368``).
    With ``use_kernel=True`` detection runs through the actual two-pass
    kernel and the mean on-chain second-pass reliability is reported.
    """
    keys = jax.random.split(key, k_trials)
    success_rate, mean_dist, mean_rel2 = _unconstrained_trials(
        keys,
        jnp.asarray(mu, jnp.float32),
        jnp.asarray(sigma, jnp.float32),
        n_oracles=n_oracles,
        n_failing=n_failing,
        use_kernel=use_kernel,
        max_spread=float(max_spread),
        failing_spread=float(failing_spread),
    )
    out = {
        "identification_success_pct": float(success_rate) * 100.0,
        "reliability_pct": (1.0 - float(mean_dist) / max_spread) * 100.0,
        "mean_estimator_error": float(mean_dist),
    }
    if use_kernel:
        out["mean_onchain_reliability2_pct"] = float(mean_rel2) * 100.0
    return out


@partial(
    jax.jit,
    static_argnames=("n_oracles", "n_failing", "dim", "k_trials", "biased"),
)
def _fleet_trials(key, a, b, *, n_oracles, n_failing, dim, k_trials, biased=False):
    m = n_oracles - n_failing
    gen = generate_biased_beta_oracles if biased else generate_beta_oracles

    def trial(key):
        values, honest = gen(key, n_oracles, n_failing, a, b, dim=dim)
        out = consensus_step(
            values, ConsensusConfig(n_failing=n_failing, constrained=True)
        )
        guess = out.reliable
        exact = jnp.all(guess == honest)
        miscls = jnp.sum(guess != honest)
        pred = restricted_median(values, guess, m)
        truth = restricted_median(values, honest, m)
        dist = jnp.linalg.norm(pred - truth)
        return exact, miscls, dist, out.reliability_second_pass

    keys = jax.random.split(key, k_trials)
    exact, miscls, dist, rel2 = jax.vmap(trial)(keys)
    return (
        jnp.mean(exact.astype(jnp.float32)),
        jnp.mean(miscls.astype(jnp.float32)),
        jnp.mean(dist),
        jnp.mean(rel2),
    )


def fleet_benchmark(
    key,
    n_oracles: int,
    n_failing: int,
    a: float = 20.0,
    b: float = 20.0,
    k_trials: int = 200,
    dim: int = 6,
    biased: bool = False,
) -> Dict[str, float]:
    """Estimator quality at PRODUCT scale — the framework's pitch is a
    1024-oracle fleet, whose detection statistics the reference's
    published N∈{7,20} tables (``documentation/README.md:241-341``) say
    nothing about.  Detection runs through the actual on-chain two-pass
    kernel at the product dimension (6 tracked labels).

    Beyond the reference's exact-identification metric (all N flags
    right — ever harsher as N grows: one swapped pair fails the trial),
    the fleet table reports ``mean_misclassified`` (average # of wrong
    flags per trial, the per-oracle error rate × N) so near-misses are
    visible, and the mean on-chain second-pass reliability.

    The interesting cells bracket the estimator's breakdown point: the
    first-pass center is the component-wise smooth median of ALL
    oracles (``contract.cairo:450-470``), which adversaries dominate
    once ``n_failing > N/2`` — identification collapses by design, and
    the table documents it (e.g. 768/1024).
    """
    exact, miscls, dist, rel2 = _fleet_trials(
        key,
        jnp.asarray(a, jnp.float32),
        jnp.asarray(b, jnp.float32),
        n_oracles=n_oracles,
        n_failing=n_failing,
        dim=dim,
        k_trials=k_trials,
        biased=biased,
    )
    return {
        "identification_success_pct": float(exact) * 100.0,
        "mean_misclassified": float(miscls),
        "misclassified_rate_pct": float(miscls) / n_oracles * 100.0,
        "reliability_pct": (1.0 - 2.0 * float(dist)) * 100.0,
        "mean_onchain_reliability2_pct": float(rel2) * 100.0,
    }


def _fleet_sweep(
    key, n_oracles, rows, *, biased, k_trials, a, b, dim, print_fn, label_fn
):
    """Shared row sweep behind the acceptance grid and the breakdown
    curve: one independent key and one :func:`fleet_benchmark` call per
    (result-key, n_failing) row."""
    results = {}
    for i, (result_key, n_failing) in enumerate(rows):
        r = fleet_benchmark(
            jax.random.fold_in(key, i),
            n_oracles,
            n_failing,
            a=a,
            b=b,
            k_trials=k_trials,
            dim=dim,
            biased=biased,
        )
        results[result_key] = r
        print_fn(
            f"N={n_oracles} {label_fn(result_key, n_failing)} | "
            f"misflag rate {r['misclassified_rate_pct']:6.2f} % | "
            f"reliability {r['reliability_pct']:7.2f} % | rel2(chain) "
            f"{r['mean_onchain_reliability2_pct']:6.2f} %"
        )
    return results


def fleet_acceptance_grid(
    key,
    n_oracles: int = 1024,
    failing_list=(2, 64, 256, 768),
    k_trials: int = 200,
    a: float = 20.0,
    b: float = 20.0,
    dim: int = 6,
    print_fn: Callable[[str], None] = print,
) -> Dict[int, Dict[str, float]]:
    """The fleet-scale acceptance table (rows = adversary count) —
    published in ``docs/ALGORITHM.md`` and pinned by
    ``tests/test_sim.py`` at sampling tolerance."""
    return _fleet_sweep(
        key,
        n_oracles,
        [(n, n) for n in failing_list],
        biased=False,
        k_trials=k_trials,
        a=a,
        b=b,
        dim=dim,
        print_fn=print_fn,
        label_fn=lambda _k, n: f"failing={n:<4}",
    )


def fleet_breakdown_curve(
    key,
    n_oracles: int = 1024,
    fractions=(0.1, 0.25, 0.4, 0.45, 0.49, 0.51, 0.55),
    k_trials: int = 100,
    a: float = 20.0,
    b: float = 20.0,
    dim: int = 6,
    print_fn: Callable[[str], None] = print,
) -> Dict[float, Dict[str, float]]:
    """The estimator's TRUE breakdown point, measured.

    Uniform adversaries (the reference's failure model) are symmetric
    about the honest center and never displace the median, so the
    acceptance table stays benign even at 75 % adversarial.  This curve
    uses COORDINATED biased adversaries
    (:func:`svoc_tpu.sim.generators.generate_biased_beta_oracles` — a
    narrow corner band, all pushing one direction): below N/2 the
    first-pass median stays with the honest mass and detection holds;
    crossing N/2 the median jumps INTO the adversary band and the
    estimator inverts (it marks the honest minority as outliers) — the
    theoretical breakdown bound for any median-based rule, visible here
    as a cliff between 49 % and 51 %.
    """
    return _fleet_sweep(
        key,
        n_oracles,
        [(frac, int(round(frac * n_oracles))) for frac in fractions],
        biased=True,
        k_trials=k_trials,
        a=a,
        b=b,
        dim=dim,
        print_fn=print_fn,
        label_fn=lambda frac, n: f"biased={frac:5.0%} ({n:4d})",
    )


def launch_benchmark(
    key,
    n_oracles: int,
    n_failing: int,
    k_trials: int = 300,
    print_fn: Callable[[str], None] = print,
    use_kernel: bool = False,
):
    """The published benchmark grid (``documentation/README.md:241-246``):
    a ∈ {10,20,30,100} × b ∈ {(15,30), (a,a), (a,a³), (a³,−a³)…} — the
    degenerate b cells (negative / overflowing parameters) are replaced
    by their intended symmetric form, matching the (a,a) rows actually
    cited in BASELINE.md."""
    results = {}
    cell = 0
    for a in [10, 20, 30, 100]:
        print_fn("---")
        for b in [15.0, float(a)]:
            cell += 1
            r = benchmark(
                jax.random.fold_in(key, cell),  # independent draws per cell
                float(a),
                b,
                n_oracles,
                n_failing,
                k_trials=k_trials,
                use_kernel=use_kernel,
            )
            results[(a, b)] = r
            print_fn(
                f"a={a} | b={b:<8} | identification success: "
                f"{r['identification_success_pct']:0.2f} % | reliability : "
                f"{r['reliability_pct']:0.2f} %"
            )
    return results
