"""Persistent on-disk XLA compilation cache (docs/PARALLELISM.md
§compile-plane, docs/RESILIENCE.md §compile-cache).

A process restart (crash recovery, deploy, drain/restart) loses every
compiled executable: PR 8 made the CHAIN and journal state survive a
kill, but the restarted process still re-paid the whole compile
universe before serving its first request.  This module points JAX's
persistent compilation cache (``jax_compilation_cache_dir``) at a
directory UNDER the durability base dir, so compiled programs survive
the same kill/restart cycle the WAL and snapshots do — a warm restart's
backend compiles become millisecond cache retrievals
(``bench_coldstart.py`` measures the ratio honestly on this host).

Versioning: the cache lives under a SALT subdirectory covering the jax
version and a digest of the repo's kernel-relevant sources
(:func:`kernel_revision`).  JAX's own cache key already covers the
serialized HLO, so a kernel edit would naturally miss — the salt exists
so a jax upgrade or kernel rewrite INVALIDATES the old entries loudly
(the stale salt dir is deleted at enable time) instead of leaving dead
weight under the durability dir forever.

Size cap: :func:`evict_cache` drops least-recently-USED entries (JAX
maintains a ``*-atime`` touch file per entry) until the directory fits
``max_bytes``; :meth:`~svoc_tpu.durability.recovery.RecoveryManager`
runs it on its snapshot cadence.  The cache dir is durable state but
NOT journal state: WAL rotation and trace rotation never touch it
(docs/RESILIENCE.md).
"""

from __future__ import annotations

import hashlib
import os
import shutil
import threading
from typing import Dict, List, Optional, Tuple

from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry

#: Subdirectory of the durability base dir holding every salt's cache.
CACHE_DIRNAME = "xla_cache"

#: Default size cap (bytes) for :func:`enable_persistent_cache` — a few
#: hundred claim-cube programs at CPU sizes; TPU executables are larger
#: but the cap is an operator knob, not a constant of nature.
DEFAULT_MAX_BYTES = 256 * 1024 * 1024

#: The kernel-relevant sources the salt digests: the modules whose
#: edits change what the dispatched consensus programs COMPUTE (a
#: rename elsewhere must not invalidate a warm fleet's cache).
KERNEL_SOURCES = (
    "consensus/kernel.py",
    "consensus/batch.py",
    "ops/sort.py",
    "ops/stats.py",
    "ops/select.py",
    "ops/pallas_consensus.py",
    "robustness/sanitize.py",
    "parallel/claim_shard.py",
)

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_state_lock = threading.Lock()
#: The enabled cache dir (None until :func:`enable_persistent_cache`);
#: status surfaces read it, nothing on a hot path does.
_enabled_dir: Optional[str] = None


def kernel_revision() -> str:
    """sha256 over the kernel-relevant sources (sorted, content only)
    — the repo half of the cache salt.  A missing file contributes its
    name (a deleted kernel module IS a revision change)."""
    digest = hashlib.sha256()
    for rel in sorted(KERNEL_SOURCES):
        digest.update(rel.encode())
        path = os.path.join(_PKG_ROOT, rel)
        try:
            with open(path, "rb") as f:
                digest.update(f.read())
        except OSError:
            digest.update(b"<absent>")
    return digest.hexdigest()


def cache_salt() -> str:
    """``jax<version>-k<kernel digest>`` — the versioned subdirectory
    name.  jax's own cache key also covers its version; the salt makes
    the invalidation VISIBLE (stale dirs deleted, not just missed)."""
    import jax

    return f"jax{jax.__version__}-k{kernel_revision()[:12]}"


def persistent_cache_dir(base_dir: str) -> str:
    """The salted cache directory under ``base_dir`` (not created)."""
    return os.path.join(base_dir, CACHE_DIRNAME, cache_salt())


def enabled_cache_dir() -> Optional[str]:
    """The directory a prior :func:`enable_persistent_cache` pointed
    JAX at, or None — the status/snapshot surfaces' view."""
    with _state_lock:
        return _enabled_dir


def enable_persistent_cache(
    base_dir: str,
    *,
    max_bytes: int = DEFAULT_MAX_BYTES,
    metrics: Optional[MetricsRegistry] = None,
) -> Optional[str]:
    """Point JAX's persistent compilation cache under ``base_dir``.

    Creates the salted dir, DELETES sibling stale-salt dirs (the
    versioned invalidation — an old jax/kernel revision's entries can
    never be read again), drops the min-compile-time/min-entry-size
    thresholds to zero (this host's CPU compiles are fast but a restart
    re-pays ALL of them — restart warmth is the contract, not disk
    thrift; the size cap bounds the disk side), and runs one eviction
    pass.  Idempotent; re-enabling with the same base dir is a no-op
    refresh.  Returns the cache dir, or None when the jax config
    surface is absent (API drift degrades to a counted no-op, never a
    crash — serving works uncached)."""
    reg = metrics or _default_registry
    target = persistent_cache_dir(base_dir)
    try:
        os.makedirs(target, exist_ok=True)
        parent = os.path.dirname(target)
        for name in os.listdir(parent):
            stale = os.path.join(parent, name)
            if stale != target and os.path.isdir(stale):
                shutil.rmtree(stale, ignore_errors=True)
                reg.counter(
                    "compile_cache_invalidated", labels={"salt": name}
                ).add(1)
        import jax

        jax.config.update("jax_compilation_cache_dir", target)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_enable_compilation_cache", True)
        # jax caches its cache OBJECT on first use and does not watch
        # the config: re-pointing the dir (a second enable, tests, a
        # manager built after an earlier one) silently keeps writing to
        # the OLD dir without this reset (measured on jax 0.4.37).
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        _cc.reset_cache()
    except (OSError, ImportError, AttributeError, ValueError) as e:
        # ImportError included: jax.experimental.compilation_cache is a
        # private-ish surface that has moved between jax versions — a
        # relocation must degrade to uncached serving, never crash
        # RecoveryManager construction (i.e. crash recovery itself).
        reg.counter(
            "compile_cache_errors", labels={"op": "enable"}
        ).add(1)
        import logging

        logging.getLogger(__name__).warning(
            "persistent compilation cache NOT enabled (%s: %s); serving "
            "continues uncached — restarts stay cold",
            type(e).__name__,
            e,
        )
        return None
    with _state_lock:
        global _enabled_dir
        _enabled_dir = target
    evict_cache(target, max_bytes, metrics=reg)
    return target


def _entries(cache_dir: str) -> List[Tuple[str, float, int]]:
    """``(entry_path, last_used, bytes)`` per cache entry.  JAX writes
    a ``<key>-cache`` payload plus a ``<key>-atime`` touch file it
    refreshes on every hit; last-used falls back to the payload's mtime
    for entries whose atime twin is missing (a torn write — still
    evictable)."""
    out: List[Tuple[str, float, int]] = []
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return out
    present = set(names)
    for name in names:
        if name.endswith("-atime"):
            continue
        path = os.path.join(cache_dir, name)
        try:
            size = os.path.getsize(path)
            atime_name = None
            if name.endswith("-cache"):
                candidate = name[: -len("-cache")] + "-atime"
                if candidate in present:
                    atime_name = candidate
            if atime_name is not None:
                last_used = os.path.getmtime(
                    os.path.join(cache_dir, atime_name)
                )
            else:
                last_used = os.path.getmtime(path)
        except OSError:
            continue
        out.append((path, last_used, size))
    return out


def evict_cache(
    cache_dir: str,
    max_bytes: int,
    *,
    metrics: Optional[MetricsRegistry] = None,
) -> Dict[str, float]:
    """Least-recently-used eviction down to ``max_bytes``; returns the
    post-eviction stats.  Evictions are counted
    (``compile_cache_evictions``) and the resident size is a gauge
    (``compile_cache_bytes``) — a cache silently thrashing its cap
    would otherwise read as mysterious cold-start regressions."""
    reg = metrics or _default_registry
    entries = sorted(_entries(cache_dir), key=lambda e: e[1])
    total = sum(size for _p, _t, size in entries)
    evicted = 0
    while entries and total > max_bytes:
        path, _last_used, size = entries.pop(0)
        try:
            os.remove(path)
            atime = path[: -len("-cache")] + "-atime" if path.endswith(
                "-cache"
            ) else None
            if atime and os.path.exists(atime):
                os.remove(atime)
        except OSError:
            continue
        total -= size
        evicted += 1
    if evicted:
        reg.counter("compile_cache_evictions").add(evicted)
    reg.gauge("compile_cache_bytes").set(float(max(0, total)))
    return {"bytes": float(max(0, total)), "evicted": float(evicted)}


def cache_stats(cache_dir: Optional[str] = None) -> Dict[str, float]:
    """``{entries, bytes}`` for the enabled (or given) cache dir — the
    durability status panel's view.  Zeros when nothing is enabled."""
    cache_dir = cache_dir if cache_dir is not None else enabled_cache_dir()
    if not cache_dir:
        return {"entries": 0.0, "bytes": 0.0}
    entries = _entries(cache_dir)
    return {
        "entries": float(len(entries)),
        "bytes": float(sum(size for _p, _t, size in entries)),
    }
