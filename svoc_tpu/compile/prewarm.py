"""The AOT prewarm worker: compile the shape universe ahead of traffic.

Cold start is the one latency the serving tier could not hide: the
first request landing on a new (bucket, group, variant) pays trace +
lower + XLA backend compile INSIDE a serving step — ~0.7 s on this
host's CPU for one claim-cube program against a ~5 ms steady-state
dispatch (``bench_coldstart.py``), and far worse on a real TPU's Mosaic
pipeline.  The worker walks the enumerated universe
(:mod:`svoc_tpu.compile.universe`) in priority order and, per key:

1. **AOT-compiles** through ``fn.lower(shapes...).compile()`` on the
   SAME module-level jitted callables the router dispatches
   (:func:`svoc_tpu.consensus.batch.jit_dispatcher` — a parallel
   re-jit would fill a different jit cache and the first dispatch
   would recompile anyway), timing each into the
   ``prewarm_compile_seconds`` histogram and populating the persistent
   compilation cache when one is enabled
   (:mod:`svoc_tpu.compile.cache`);
2. **primes the dispatch path** with one all-padding dummy cube
   (``claim_mask`` all-False — every output row is the kernel's forced
   invalid/zero state) through the PUBLIC dispatch wrappers, so the
   first real request doesn't even pay the re-lowering: trace cache,
   jit dispatch cache, and (on a pallas/sharded route) the Mosaic /
   shard_map caches are all hot.

Accounting: every key ends in ``compile_prewarm{outcome=}`` —
``compiled`` / ``primed`` / ``skipped`` / ``error`` /
``budget_exhausted`` — and the
worker NEVER journals: warmup must be invisible to seeded replay
fingerprints (the ``make coldstart-smoke`` gate), so its only traces
are metrics and compiled code.  The time budget bounds the walk;
priority order means the cut falls on twin variants, not the
serving-critical head.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from svoc_tpu.compile.universe import (
    CompileKey,
    enumerate_universe,
    registry_groups,
    universe_summary,
)
from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry

_log = logging.getLogger(__name__)

PREWARM_COUNTER = "compile_prewarm"
PREWARM_HISTOGRAM = "prewarm_compile_seconds"


@dataclasses.dataclass(frozen=True)
class PrewarmConfig:
    """The worker's knobs.  ``budget_s=None`` walks the whole universe
    (restart prewarms are cheap — persistent-cache retrievals);
    ``prime=False`` stops after the AOT compile (populates the
    persistent cache but leaves re-lowering to the first dispatch —
    the bench's mid point).  Priming is the ONLY warmup a sharded or
    pallas-routed key has (the AOT branch covers the unsharded XLA
    twins), so ``prime=False`` counts such keys ``skipped`` and leaves
    them cold rather than pretending."""

    budget_s: Optional[float] = None
    prime: bool = True
    include_twins: bool = True

    def __post_init__(self):
        if self.budget_s is not None and self.budget_s <= 0:
            raise ValueError("budget_s must be > 0 (or None)")


class PrewarmWorker:
    """Walks one router's compile universe; owns no thread until
    :meth:`start` and never outlives :meth:`wait`.

    The router's construction-pinned resolution (impl / mesh / donate /
    gate fusion) is read ONCE here, at worker construction — the worker
    inherits the replay-pinning discipline (docs/FABRIC.md §replay)
    rather than re-resolving knobs per key.
    """

    def __init__(
        self,
        router,
        registry,
        *,
        metrics: Optional[MetricsRegistry] = None,
        config: Optional[PrewarmConfig] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.router = router
        self.registry = registry
        self.config = config or PrewarmConfig()
        self._metrics = metrics or _default_registry
        self._clock = clock
        self._lock = threading.Lock()
        self._warm: set = set()
        self._universe: Optional[List[CompileKey]] = None
        #: (N, M, cfg) group -> its PRIMARY keys (the pinned variant's
        #: bucket ladder), cached at enumeration so the defer gate's
        #: per-submit ``group_cold`` reads a list instead of re-deriving
        #: dataclasses on the serving path.
        self._primary: Dict[Any, List[CompileKey]] = {}
        self._thread: Optional[threading.Thread] = None
        self._done = threading.Event()
        self._started = False
        self._report: Optional[Dict[str, Any]] = None

    # -- the universe --------------------------------------------------------

    def universe(
        self,
        refresh: bool = False,
        include_twins: Optional[bool] = None,
    ) -> List[CompileKey]:
        """Enumerate (and cache) the router's compile universe from the
        LIVE registry — claims added after construction are picked up
        by ``refresh=True`` (the next :meth:`warm_all` call does).
        ``include_twins`` overrides the config default PER WALK: the
        synchronous recovery walk excludes twins for time-to-serve, and
        the subsequent background walk re-includes them — one worker,
        two walk shapes (the config would otherwise pin the first
        caller's choice for the process lifetime)."""
        with self._lock:
            if self._universe is not None and not refresh:
                return list(self._universe)
        router = self.router
        keys = enumerate_universe(
            registry_groups(self.registry),
            max_claims_per_batch=router.max_claims_per_batch,
            sanitized_dispatch=router.sanitized_dispatch,
            donate=router._donate,
            impl=router.consensus_impl,
            mesh=router.mesh_spec,
            mesh_claim_size=(
                router._shard.claim_size if router._shard else 1
            ),
            include_twins=(
                include_twins
                if include_twins is not None
                else self.config.include_twins
            ),
        )
        primary = {
            group: self._primary_keys(*group)
            for group in {k.group() for k in keys}
        }
        with self._lock:
            self._universe = keys
            self._primary = primary
        return list(keys)

    # -- warmth queries (router + serving frontend) --------------------------

    def is_warm(self, key: CompileKey) -> bool:
        with self._lock:
            return key in self._warm

    @property
    def active(self) -> bool:
        """True while a started walk has not finished — the serving
        frontend's cold-shape deferral window (a worker that was never
        started defers nothing: without a warmup in flight, waiting
        would never end)."""
        return self._started and not self._done.is_set()

    def _primary_keys(self, n_oracles: int, dimension: int, cfg):
        """The keys the PINNED router can actually dispatch for one
        (N, M, cfg) group: the primary variant (the router's gate
        fusion / donate / impl / mesh) across the bucket ladder.  Twin
        variants exist in the universe for the NEXT restart's possible
        config flips — this process can never dispatch them, so the
        defer gate must not wait on them."""
        from svoc_tpu.compile.universe import bucket_ladder, dispatch_key

        router = self.router
        sharded = router.mesh_spec is not None
        ladder = bucket_ladder(
            router.max_claims_per_batch,
            multiple_of=router._shard.claim_size if router._shard else 1,
        )
        return [
            dispatch_key(
                sanitized=router.sanitized_dispatch,
                sharded=sharded,
                bucket=bucket,
                n_oracles=n_oracles,
                dimension=dimension,
                cfg=cfg,
                donate=router._donate,
                impl=router.consensus_impl,
                mesh=router.mesh_spec,
            )
            for bucket in ladder
        ]

    def group_cold(self, n_oracles: int, dimension: int, cfg) -> bool:
        """Whether a (N, M, cfg) dispatch group can still hit a cold
        compile while the walk is in flight — the claim-level question
        the serving frontend's defer gate asks.  Gates on the PRIMARY
        keys only (the variants the construction-pinned router can
        actually dispatch): the walk warms those in its head phases,
        so the defer window closes as soon as the group's real dispatch
        surface is compiled, not when the restart-insurance twins at
        the tail of the walk finish."""
        if not self.active:
            return False
        group = (n_oracles, dimension, cfg)
        with self._lock:
            primary = self._primary.get(group)
            if primary is not None:
                # Membership checks under the lock — no per-request
                # copy of the warm set on the submit path.
                return any(k not in self._warm for k in primary)
        # A claim registered after enumeration: its keys join the
        # NEXT walk; until then it is genuinely cold.
        primary = self._primary_keys(*group)
        with self._lock:
            self._primary.setdefault(group, primary)
            return any(k not in self._warm for k in primary)

    def claim_cold(self, spec) -> bool:
        return self.group_cold(
            spec.n_oracles, spec.dimension, spec.consensus_config()
        )

    # -- one key -------------------------------------------------------------

    def step(self, key: CompileKey) -> str:
        """Warm ONE key; returns the recorded outcome.  Deliberately a
        jit-compile in a caller's loop (SVOC003's hazard is recompiles
        on the DISPATCH path; compiling ahead of it is this module's
        whole purpose) and deliberately construction-time work even
        when driven from a background thread mid-serving."""
        if key.donate:
            # The donated twin warns once per compiled shape on
            # backends whose output layouts can't alias the cube (CPU)
            # — expected noise here exactly as on the device-resident
            # router; install the shared filter BEFORE the AOT compile
            # (the warning fires at compile time, not dispatch).
            from svoc_tpu.fabric.router import _filter_donation_warning_once

            _filter_donation_warning_once()
        try:
            outcome = self._warm_one(key)
        except Exception as e:  # noqa: BLE001 — a broken shape must not kill the walk
            outcome = "error"
            _log.warning(
                "prewarm failed for %s (%s: %s); the first real "
                "dispatch of this shape will compile inline instead",
                key.label(),
                type(e).__name__,
                e,
            )
        self._metrics.counter(
            PREWARM_COUNTER, labels={"outcome": outcome}
        ).add(1)
        if outcome in ("compiled", "primed"):
            with self._lock:
                self._warm.add(key)
        return outcome

    def _warm_one(self, key: CompileKey) -> str:
        import jax
        import jax.numpy as jnp

        from svoc_tpu.consensus.batch import _PAD_VALUE, jit_dispatcher

        sanitized = key.kind.endswith("sanitized")
        sharded = key.kind.startswith("sharded_")
        lo, hi = self._bounds(key) if sanitized else (None, None)
        compiled_aot = False
        if not sharded and key.impl == "xla":
            # AOT through the very jit objects the router calls; the
            # wall time (a fresh XLA compile OR a persistent-cache
            # retrieval — the histogram tells them apart by magnitude)
            # is the per-shape compile latency the bench reports.
            fn = jit_dispatcher(sanitized, key.donate)
            sds = jax.ShapeDtypeStruct
            values = sds(
                (key.bucket, key.n_oracles, key.dimension), jnp.float32
            )
            mask = sds((key.bucket,), jnp.bool_)
            t0 = self._clock()
            if sanitized:
                lowered = fn.lower(values, mask, key.cfg, lo, hi)
            else:
                ok = sds((key.bucket, key.n_oracles), jnp.bool_)
                lowered = fn.lower(values, ok, mask, key.cfg)
            lowered.compile()
            self._metrics.histogram(PREWARM_HISTOGRAM).observe(
                max(0.0, self._clock() - t0)
            )
            compiled_aot = True
        if not self.config.prime:
            # Without priming, only the AOT branch did real work: a
            # sharded or pallas-routed key compiled NOTHING and must
            # not be marked warm (the defer gate and warmth counters
            # would lie about it) — counted ``skipped`` instead.
            return "compiled" if compiled_aot else "skipped"
        self._prime(key, sanitized, sharded, lo, hi, _PAD_VALUE)
        return "compiled" if compiled_aot else "primed"

    def _prime(self, key, sanitized, sharded, lo, hi, pad_value) -> None:
        """One dummy dispatch through the PUBLIC wrappers — the exact
        call the router makes, on an all-padding cube whose outputs the
        kernel forces invalid.  Discarded after the device sync; no
        journal, no state."""
        import jax
        import jax.numpy as jnp

        from svoc_tpu.consensus.batch import (
            claims_consensus_gated,
            claims_consensus_sanitized,
        )

        values = jnp.full(
            (key.bucket, key.n_oracles, key.dimension),
            pad_value,
            dtype=jnp.float32,
        )
        mask = jnp.zeros((key.bucket,), dtype=bool)
        if sharded:
            shard = self.router._shard
            if shard is None:
                raise RuntimeError(
                    f"{key.label()} is a sharded key but the router "
                    "has no mesh — stale universe"
                )
            ok = jnp.ones((key.bucket, key.n_oracles), dtype=bool)
            if sanitized:
                out = shard.dispatch_sanitized(
                    values, mask, key.cfg, lo, hi
                )
            else:
                out = shard.dispatch_gated(values, ok, mask, key.cfg)
        elif sanitized:
            out = claims_consensus_sanitized(
                values,
                mask,
                key.cfg,
                lo,
                hi,
                consensus_impl=key.impl,
                metrics=self._metrics,
                donate=key.donate,
            )
        else:
            ok = jnp.ones((key.bucket, key.n_oracles), dtype=bool)
            out = claims_consensus_gated(
                values,
                ok,
                mask,
                key.cfg,
                consensus_impl=key.impl,
                metrics=self._metrics,
                donate=key.donate,
            )
        jax.block_until_ready(out)

    @staticmethod
    def _bounds(key: CompileKey):
        from svoc_tpu.robustness.sanitize import SanitizeConfig

        bounds = SanitizeConfig.for_consensus(key.cfg.constrained)
        return bounds.lo, bounds.hi

    # -- the walk ------------------------------------------------------------

    def warm_all(
        self,
        budget_s: Optional[float] = None,
        include_twins: Optional[bool] = None,
    ) -> Dict[str, Any]:
        """Walk the (refreshed) universe in priority order under the
        time budget; returns the JSON-safe report.  Reentrant-safe for
        a second call after new claims register or with a different
        ``include_twins`` — warmed keys are skipped, not recompiled."""
        self._started = True
        self._done.clear()
        keys: List[CompileKey] = []
        # The enumeration sits inside the finally too: an enumeration
        # error on the background thread must still set _done, or
        # ``active`` stays True forever and every cold group's requests
        # are deferred eternally (the gate would truthfully report a
        # walk that will never finish — worse than any compile).
        try:
            keys = self.universe(refresh=True, include_twins=include_twins)
        except BaseException:
            self._done.set()
            raise
        return self._walk(keys, budget_s)

    def _walk(
        self, keys: List[CompileKey], budget_s: Optional[float]
    ) -> Dict[str, Any]:
        """The walk proper, over ALREADY-ENUMERATED keys — shared by
        :meth:`warm_all` and the background thread :meth:`start`
        spawns (which enumerated before going live for the defer gate,
        and must not pay the registry scan twice)."""
        budget = budget_s if budget_s is not None else self.config.budget_s
        started_at = self._clock()
        outcomes: Dict[str, int] = {}
        try:
            for i, key in enumerate(keys):
                if self.is_warm(key):
                    continue
                if budget is not None and (
                    self._clock() - started_at
                ) > budget:
                    remaining = sum(
                        1 for k in keys[i:] if not self.is_warm(k)
                    )
                    self._metrics.counter(
                        PREWARM_COUNTER,
                        labels={"outcome": "budget_exhausted"},
                    ).add(remaining)
                    outcomes["budget_exhausted"] = (
                        outcomes.get("budget_exhausted", 0) + remaining
                    )
                    break
                outcome = self.step(key)
                outcomes[outcome] = outcomes.get(outcome, 0) + 1
        finally:
            self._done.set()
        report = {
            "universe": universe_summary(keys),
            "outcomes": outcomes,
            "warmed": len(self._warm),
            "elapsed_s": round(self._clock() - started_at, 4),
            "budget_s": budget,
        }
        with self._lock:
            self._report = report
        return report

    def start(
        self,
        budget_s: Optional[float] = None,
        include_twins: Optional[bool] = None,
    ) -> threading.Thread:
        """Run :meth:`warm_all` on a background daemon thread (the
        serving deployment's mode: the tier serves — and defers cold
        shapes — while the universe compiles).  Idempotent while a
        walk is live."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self._thread
        # Enumerate BEFORE going live: the defer gate reads the
        # universe, and a gate that opens before the walk knows its
        # keys would let a cold shape slip into the first micro-batch.
        # The thread walks THESE keys (claims registered in the
        # microseconds between here and the walk join the next one) —
        # no second enumeration on the background path.
        keys = self.universe(refresh=True, include_twins=include_twins)
        self._started = True
        self._done.clear()
        thread = threading.Thread(
            target=self._walk,
            args=(keys, budget_s),
            name="svoc-prewarm",
            daemon=True,
        )
        with self._lock:
            self._thread = thread
        thread.start()
        return thread

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the walk finishes; True iff it did."""
        return self._done.wait(timeout)

    def stats(self) -> Dict[str, Any]:
        """The snapshot/`/api/state` view: warmed count, universe size,
        liveness, the last report."""
        with self._lock:
            universe = self._universe
            report = self._report
            warmed = len(self._warm)
        return {
            "active": self.active,
            "warmed": warmed,
            "universe": len(universe) if universe is not None else None,
            "report": report,
        }


def warm_keys(
    keys,
    *,
    budget_s: float = 0.0,
    clock=None,
    metrics=None,
) -> Dict[str, int]:
    """Budgeted AOT walk over an explicit key list — the standalone twin
    of :meth:`PrewarmWorker.step` for configurations with NO live router
    behind them (the reconfiguration plane's PREPARE phase warms the
    PENDING config's universe, :func:`svoc_tpu.compile.universe
    .pending_universe`, before any replica drains).

    Only the unsharded XLA keys AOT-compile (``jit_dispatcher.lower()
    .compile()`` — the same jit objects the post-transition routers will
    call, so the jit cache they populate is THE cache that makes the
    first post-resume dispatch warm); sharded and pallas-routed keys
    are counted ``skipped`` — they compile inside their mesh/pallas
    dispatch context at first use, exactly like :meth:`PrewarmWorker
    ._warm_one`'s non-priming path.  Never journals, never dispatches:
    a prewarmed-then-aborted transition leaves no replay-relevant trace
    (docs/RECONFIG.md §abort).

    ``budget_s <= 0`` means unbudgeted; otherwise the walk stops at the
    deadline and the remainder is counted ``deferred`` (never silently
    dropped — the first real dispatch compiles them).
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    from svoc_tpu.consensus.batch import jit_dispatcher
    from svoc_tpu.robustness.sanitize import SanitizeConfig
    from svoc_tpu.utils.metrics import registry as _registry

    clock = clock if clock is not None else _time.monotonic
    metrics = metrics if metrics is not None else _registry
    deadline = clock() + budget_s if budget_s > 0 else None
    out = {"compiled": 0, "skipped": 0, "deferred": 0}
    keys = list(keys)
    for i, key in enumerate(keys):
        if deadline is not None and clock() >= deadline:
            out["deferred"] = len(keys) - i
            break
        sharded = key.kind.startswith("sharded_")
        if sharded or key.impl != "xla":
            out["skipped"] += 1
            continue
        sanitized = key.kind.endswith("sanitized")
        fn = jit_dispatcher(sanitized, key.donate)
        sds = jax.ShapeDtypeStruct
        values = sds((key.bucket, key.n_oracles, key.dimension), jnp.float32)
        mask = sds((key.bucket,), jnp.bool_)
        t0 = clock()
        if sanitized:
            bounds = SanitizeConfig.for_consensus(key.cfg.constrained)
            lowered = fn.lower(values, mask, key.cfg, bounds.lo, bounds.hi)
        else:
            ok = sds((key.bucket, key.n_oracles), jnp.bool_)
            lowered = fn.lower(values, ok, mask, key.cfg)
        lowered.compile()
        metrics.histogram(PREWARM_HISTOGRAM).observe(max(0.0, clock() - t0))
        out["compiled"] += 1
    return out
