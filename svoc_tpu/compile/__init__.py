"""The compile plane (docs/PARALLELISM.md §compile-plane).

PRs 6–13 bounded the SHAPE universe the jitted dispatchers can see
(pow2 claim buckets, construction-pinned impl/mesh/commit-mode) but
nothing ever compiled AHEAD of traffic: the first request landing on a
new shape paid the full trace+compile inside a serving step, and every
process restart (the PR 8 crash/recovery story) paid the whole universe
again.  This package closes that gap:

- :mod:`svoc_tpu.compile.universe` — enumerate the reachable compile
  keys from LIVE config (registry groups × pow2 buckets × resolved
  impl/mesh/donate), never by guessing;
- :mod:`svoc_tpu.compile.prewarm` — the AOT warmup worker that walks
  that universe through ``jax.jit(...).lower(...).compile()`` on the
  SAME jitted callables the router dispatches, with a bounded time
  budget and ``compile_prewarm{outcome=}`` accounting;
- :mod:`svoc_tpu.compile.cache` — the persistent on-disk XLA
  compilation cache under the durability base dir (versioned salt,
  size-capped eviction) that makes recovery restarts warm.

The plane is OBSERVATION + AHEAD-OF-TIME work only: it never journals,
never changes numerics, and seeded replay fingerprints are
byte-identical with it on or off (``make coldstart-smoke`` is the
gate).

Re-exports are PEP 562 LAZY: ``universe``/``prewarm`` import
``consensus.batch`` (and therefore jax) at module level, while
``cache`` deliberately keeps jax inside function bodies — an eager
``__init__`` would make ``from svoc_tpu.compile.cache import ...`` (the
RecoveryManager constructor path, reachable from jax-free durable-plane
consumers) pay the multi-second jax import for nothing.
"""

from typing import TYPE_CHECKING

_EXPORTS = {
    "cache_salt": "svoc_tpu.compile.cache",
    "cache_stats": "svoc_tpu.compile.cache",
    "enable_persistent_cache": "svoc_tpu.compile.cache",
    "evict_cache": "svoc_tpu.compile.cache",
    "kernel_revision": "svoc_tpu.compile.cache",
    "persistent_cache_dir": "svoc_tpu.compile.cache",
    "PrewarmConfig": "svoc_tpu.compile.prewarm",
    "PrewarmWorker": "svoc_tpu.compile.prewarm",
    "CompileKey": "svoc_tpu.compile.universe",
    "dispatch_key": "svoc_tpu.compile.universe",
    "enumerate_universe": "svoc_tpu.compile.universe",
    "registry_groups": "svoc_tpu.compile.universe",
}

__all__ = sorted(_EXPORTS)

if TYPE_CHECKING:  # pragma: no cover — the eager twins, for tooling
    from svoc_tpu.compile.cache import (  # noqa: F401
        cache_salt,
        cache_stats,
        enable_persistent_cache,
        evict_cache,
        kernel_revision,
        persistent_cache_dir,
    )
    from svoc_tpu.compile.prewarm import (  # noqa: F401
        PrewarmConfig,
        PrewarmWorker,
    )
    from svoc_tpu.compile.universe import (  # noqa: F401
        CompileKey,
        dispatch_key,
        enumerate_universe,
        registry_groups,
    )


def __getattr__(name: str):
    module_name = _EXPORTS.get(name)
    if module_name is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    import importlib

    return getattr(importlib.import_module(module_name), name)
