"""Shape-universe enumeration: the reachable compile keys, derived.

A compiled claim-cube program is keyed on (pow2 claim bucket ×
(n_oracles, dimension, consensus config) group × dispatch kind ×
donate twin × impl × mesh) — everything else the router varies per
cycle is DYNAMIC data by construction (docs/FABRIC.md §replay,
SVOC003).  PRs 6–13 bounded that universe; this module makes it
ENUMERABLE from live config so the prewarm worker
(:mod:`svoc_tpu.compile.prewarm`) can walk it ahead of traffic instead
of guessing:

- the (N, M, cfg) groups come from the :class:`ClaimRegistry`'s live
  claims (the same grouping ``ClaimRouter._step_inner`` computes),
- the bucket set is every power of two up to the router's
  ``max_claims_per_batch`` (mesh-rounded exactly like
  :func:`~svoc_tpu.consensus.batch.pow2_bucket` at dispatch),
- the dispatch kind / donate flag / impl / mesh are the ROUTER'S
  resolved, construction-pinned values — never re-resolved here.

Order IS priority: serving-critical shapes first (the bucket the
CURRENT claim count actually dispatches, per group), then the remaining
buckets ascending (cold-start traffic grows through small buckets
first), then the twin variants an operator could flip to
(``device_resident`` donate twins, the other gate fusion mode) — a
bounded prewarm budget cuts from the tail, never the head.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple

from svoc_tpu.consensus.batch import pow2_bucket
from svoc_tpu.consensus.kernel import ConsensusConfig

#: Dispatch kinds the fabric/serving hot path can compile.  ``gated``
#: is the pull-mode router's dispatch (host gate verdicts re-used on
#: device), ``sanitized`` the serving tier's fused gate+consensus
#: program; the ``sharded_*`` twins are the same programs inside the
#: pinned claim mesh's ``shard_map``.
KINDS = ("gated", "sanitized", "sharded_gated", "sharded_sanitized")


@dataclasses.dataclass(frozen=True)
class CompileKey:
    """One compiled program's identity, as the router dispatches it.

    ``cfg`` is the kernel's static configuration (already hashable —
    the jit static arg); the sanitize bounds of a ``sanitized`` key are
    NOT part of the identity because they are a pure function of
    ``cfg.constrained`` (``SanitizeConfig.for_consensus``) — one gate
    per constrained mode per process, never per-request data."""

    kind: str
    bucket: int
    n_oracles: int
    dimension: int
    cfg: ConsensusConfig
    donate: bool = False
    impl: str = "xla"
    mesh: Optional[str] = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"kind {self.kind!r} is not one of {KINDS}"
            )
        if self.bucket < 1:
            raise ValueError("bucket must be >= 1")

    def group(self) -> Tuple[int, int, ConsensusConfig]:
        """The router's (N, M, cfg) dispatch-group key."""
        return (self.n_oracles, self.dimension, self.cfg)

    def label(self) -> str:
        """Compact metrics/log label: ``gated:c8n7m6[+donate]``."""
        suffix = "+donate" if self.donate else ""
        mesh = f"@{self.mesh}" if self.mesh else ""
        return (
            f"{self.kind}:c{self.bucket}n{self.n_oracles}"
            f"m{self.dimension}{suffix}{mesh}"
        )


def dispatch_key(
    *,
    sanitized: bool,
    sharded: bool,
    bucket: int,
    n_oracles: int,
    dimension: int,
    cfg: ConsensusConfig,
    donate: bool,
    impl: str,
    mesh: Optional[str],
) -> CompileKey:
    """The key for ONE router dispatch, from the router's own flags —
    the single constructor both the router's warmth accounting and the
    prewarm universe share, so they can never disagree on identity."""
    kind = ("sharded_" if sharded else "") + (
        "sanitized" if sanitized else "gated"
    )
    return CompileKey(
        kind=kind,
        bucket=bucket,
        n_oracles=n_oracles,
        dimension=dimension,
        cfg=cfg,
        donate=donate,
        impl=impl,
        mesh=mesh if sharded else None,
    )


def registry_groups(registry) -> Dict[Tuple[int, int, ConsensusConfig], int]:
    """Live (N, M, cfg) dispatch groups → unpaused claim count, exactly
    the grouping ``ClaimRouter._step_inner`` builds per cycle (paused
    claims keep their registration but draw no dispatches)."""
    groups: Dict[Tuple[int, int, ConsensusConfig], int] = {}
    for state in registry.states():
        if state.paused:
            continue
        spec = state.spec
        key = (spec.n_oracles, spec.dimension, spec.consensus_config())
        groups[key] = groups.get(key, 0) + 1
    return groups


def bucket_ladder(
    cap: int, *, floor: int = 1, multiple_of: int = 1
) -> List[int]:
    """Every bucket the router can dispatch for up to ``cap`` claims:
    pow2 (mesh-rounded) buckets ascending, deduplicated."""
    if cap < 1:
        raise ValueError("cap must be >= 1")
    out: List[int] = []
    n = 1
    while True:
        bucket = pow2_bucket(n, floor=floor, multiple_of=multiple_of)
        if bucket not in out:
            out.append(bucket)
        if n >= cap:
            break
        n *= 2
    return out


def enumerate_universe(
    groups: Dict[Tuple[int, int, ConsensusConfig], int],
    *,
    max_claims_per_batch: int,
    sanitized_dispatch: bool,
    donate: bool,
    impl: str,
    mesh: Optional[str] = None,
    mesh_claim_size: int = 1,
    include_twins: bool = True,
) -> List[CompileKey]:
    """The priority-ordered compile universe for one router's live
    config.  ``groups`` is :func:`registry_groups`' output; the flag
    arguments are the router's construction-pinned resolution (impl,
    mesh, donate, gate fusion) — the universe DERIVES from config, it
    never resolves anything itself.

    Phases (order is priority; a budgeted walk cuts from the tail):

    1. per group, the bucket the CURRENT claim count dispatches, in the
       router's own kind/donate variant — the serving-critical head;
    2. the remaining bucket ladder ascending, same variant;
    3. twin variants (the other gate fusion, the donate flip) for every
       bucket — an operator flipping ``device_resident`` or
       ``sanitized_dispatch`` on the next restart still restarts warm.

    Twins are enumerated for the UNSHARDED path only: the sharded
    programs neither donate (the dispatcher manages its buffers) nor
    pre-build gate variants the mesh wasn't constructed for.
    """
    sharded = mesh is not None
    ordered_groups = sorted(
        groups.items(), key=lambda kv: (kv[0][0], kv[0][1], repr(kv[0][2]))
    )
    ladder = bucket_ladder(
        max_claims_per_batch,
        multiple_of=mesh_claim_size if sharded else 1,
    )

    def key(group, bucket, *, sanitized, donate_flag) -> CompileKey:
        n, m, cfg = group
        return dispatch_key(
            sanitized=sanitized,
            sharded=sharded,
            bucket=bucket,
            n_oracles=n,
            dimension=m,
            cfg=cfg,
            donate=donate_flag and not sharded,
            impl=impl,
            mesh=mesh,
        )

    out: List[CompileKey] = []
    seen = set()

    def push(k: CompileKey) -> None:
        if k not in seen:
            seen.add(k)
            out.append(k)

    # Phase 1 — serving-critical: what the next cycle will dispatch.
    for group, count in ordered_groups:
        live = max(1, min(count, max_claims_per_batch))
        bucket = pow2_bucket(
            live, multiple_of=mesh_claim_size if sharded else 1
        )
        push(key(group, bucket, sanitized=sanitized_dispatch,
                 donate_flag=donate))
    # Phase 2 — the rest of the ladder, primary variant.
    for group, _count in ordered_groups:
        for bucket in ladder:
            push(key(group, bucket, sanitized=sanitized_dispatch,
                     donate_flag=donate))
    # Phase 3 — twins (unsharded only; see docstring).
    if include_twins and not sharded:
        for group, _count in ordered_groups:
            for bucket in ladder:
                push(key(group, bucket, sanitized=not sanitized_dispatch,
                         donate_flag=donate))
                push(key(group, bucket, sanitized=sanitized_dispatch,
                         donate_flag=not donate))
                push(key(group, bucket, sanitized=not sanitized_dispatch,
                         donate_flag=not donate))
    return out


def pending_universe(
    specs,
    *,
    max_claims_per_batch: int,
    sanitized_dispatch: bool,
    donate: bool,
    impl: str,
    mesh: Optional[str] = None,
    mesh_claim_size: int = 1,
    include_twins: bool = False,
) -> List[CompileKey]:
    """The compile universe for a configuration that is NOT live yet —
    what the reconfiguration plane's PREPARE phase prewarms
    (docs/RECONFIG.md): the (N, M, cfg) groups come from the plan's
    effective :class:`~svoc_tpu.fabric.registry.ClaimSpec` set instead
    of a live registry, and the impl/mesh flags are the PENDING
    resolution, so the post-transition fleet dispatches warm on its
    first cycle.  Twins default OFF — a transition prewarms the exact
    target config, not the whole operator option space."""
    groups: Dict[Tuple[int, int, ConsensusConfig], int] = {}
    for spec in specs:
        key = (spec.n_oracles, spec.dimension, spec.consensus_config())
        groups[key] = groups.get(key, 0) + 1
    return enumerate_universe(
        groups,
        max_claims_per_batch=max_claims_per_batch,
        sanitized_dispatch=sanitized_dispatch,
        donate=donate,
        impl=impl,
        mesh=mesh,
        mesh_claim_size=mesh_claim_size,
        include_twins=include_twins,
    )


def universe_summary(keys: Iterable[CompileKey]) -> Dict[str, object]:
    """JSON-safe digest of an enumerated universe (bench artifacts,
    the ``/api/state`` compile section): size, per-kind counts, bucket
    span."""
    keys = list(keys)
    kinds: Dict[str, int] = {}
    for k in keys:
        kinds[k.kind] = kinds.get(k.kind, 0) + 1
    return {
        "keys": len(keys),
        "kinds": kinds,
        "buckets": sorted({k.bucket for k in keys}),
        "groups": len({k.group() for k in keys}),
    }
