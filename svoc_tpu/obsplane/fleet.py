"""The fleet observability plane (docs/OBSERVABILITY.md §fleet-plane):
cross-replica hop-chain tracing, merged fleet telemetry + SLOs, and
seeded anomaly detection — **replay-invisible by construction**.

Every record this plane produces rides the ``obs`` observation channel
(:class:`~svoc_tpu.obsplane.timeline.ObservationLog` — PR 16's third
line shape), NEVER the fingerprinted event journal: the replay
fingerprint digests journal records including their seqs, so one
fleet-plane journal event would shift sibling seqs and break the
ON-vs-OFF byte-identity `make fleet-obs-smoke` certifies.  That rule
extends to the machinery the plane reuses: the fleet SLO evaluator and
the anomaly-triggered profiler are constructed over a journal-shaped
SHIM (:class:`_ObsJournal`) that turns their ``slo.alert`` /
``profile.captured`` emissions into observation records tagged
``scope=fleet`` — same taxonomy, different channel.

Three pillars:

- **hop chains** (:mod:`svoc_tpu.obsplane.hopchain`) — the router
  mints a :class:`HopContext` per routing decision and the plane
  records both sides of every hop on per-source observation sidecars
  (``fleet-obs.jsonl`` next to the cluster trace for the router,
  ``obs*.jsonl`` in each replica's durable dir).  The sidecars are
  deliberately SEPARATE, non-fsynced files: hop records are derived
  telemetry with no durability contract, while the flight-recorder
  files fsync per line (replica/cluster writers pin ``fsync=True``) —
  putting telemetry on the durability hot path would spend the 5 %
  overhead budget on fsyncs (`bench_obs.py` fleet arm guards this).
- **aggregation** (:class:`FleetAggregator`) — per-source
  :class:`MetricsRegistry` state merges into one registry: counters
  SUM per (family, labels); gauges keep a ``replica=`` label;
  histograms merge per-bucket counts (matching grids — a mismatched
  grid keeps its ``replica=`` label instead of corrupting the sum);
  timers sum count/total and keep the max.  Retired stacks fold in
  under ``replica="<key>@retired"`` as the element-wise MAX of the
  last in-process scrape and the recovered durable authority — both
  are true lower bounds on the dead process's work, and the max keeps
  every fleet counter monotone through a kill → failover (the
  regression `tests/test_fleet_obs.py` pins).  ``fleet_accounting``'s
  ``unaccounted`` field still reports the in-flight gap the durable
  authority alone would show.
- **anomaly detection** (:mod:`svoc_tpu.obsplane.anomaly`) — sampled
  on the router's step cadence over the merged degradation families;
  sustained breaches auto-trigger :meth:`ProfileCapture.maybe_capture`
  and a postmortem bundle carrying the fleet's observation accounting.

``enabled`` resolves ONCE at construction (``SVOC_FLEET_PLANE`` env >
``PERF_DECISIONS.json`` ``fleet_plane`` routing > off — the SVOC011
pinning discipline, same as the cost plane); disabled, every hook is
one attribute check and the router's byte stream is untouched.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from svoc_tpu.obsplane.anomaly import AnomalyConfig, AnomalyDetector
from svoc_tpu.obsplane.hopchain import HopContext
from svoc_tpu.obsplane.profiler import ProfileCapture
from svoc_tpu.obsplane.timeline import ObservationLog
from svoc_tpu.utils.metrics import MetricsRegistry

#: The request-accounting families whose MERGED totals the plane tracks
#: per step — the monotonicity regression and the fleet SLOs read these.
ACCOUNTING_FAMILIES = (
    "serving_admitted",
    "serving_completed",
    "serving_dropped",
    "serving_cached",
    "serving_shed",
    "cluster_forwarded",
    "cluster_unavailable",
)


def _decisions_fleet_plane() -> Optional[str]:
    """The committed ``fleet_plane`` routing, or None."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "PERF_DECISIONS.json",
    )
    try:
        with open(path) as f:
            decisions = json.load(f)
        value = decisions.get("fleet_plane")
        return value if isinstance(value, str) else None
    except (OSError, ValueError, AttributeError):
        return None


def resolve_fleet_plane_enabled(enabled: Optional[bool] = None) -> bool:
    """Construction-time resolution: explicit arg > ``SVOC_FLEET_PLANE``
    env (`1/on/true` vs `0/off/false`) > PERF_DECISIONS.json
    ``fleet_plane`` > off."""
    if enabled is not None:
        return bool(enabled)
    env = os.environ.get("SVOC_FLEET_PLANE", "").strip().lower()
    if env in ("1", "on", "true", "yes"):
        return True
    if env in ("0", "off", "false", "no"):
        return False
    return _decisions_fleet_plane() == "on"


class _ObsJournal:
    """Journal-shaped shim over the observation channel: the fleet SLO
    evaluator and the anomaly profiler ``emit()`` through this, so
    their ``slo.alert``/``profile.captured`` events become ``obs``
    records tagged ``scope=fleet`` — never fingerprinted journal
    entries.  This is what lets fleet alerts fire in EVERY smoke leg
    (including the fingerprint-identity legs) without breaking ON/OFF
    byte-identity."""

    def __init__(self, obslog: ObservationLog):
        self._obslog = obslog

    def emit(self, event_type: str, *, lineage: Optional[str] = None, **data):
        self._obslog.record(event_type, lineage=lineage, scope="fleet", **data)


def _entry_key(name: str, labels: Dict[str, str]) -> str:
    return name + "\x00" + json.dumps(labels or {}, sort_keys=True)


class FleetAggregator:
    """Pure merge math over per-source registry state (module
    docstring): ``merge()`` is side-effect-free on its inputs, and the
    retired ledger is the aggregator's only state."""

    def __init__(self):
        self._retired: Dict[str, List[dict]] = {}

    def retire(self, key: str, counters: List[dict]) -> None:
        """Fold a retired stack's final counter snapshot in; merged
        under ``replica="<key>@retired"`` from now on."""
        self._retired[key] = [
            {
                "name": e["name"],
                "labels": dict(e.get("labels") or {}),
                "count": float(e.get("count", 0.0)),
            }
            for e in counters
        ]

    def retired_keys(self) -> List[str]:
        return sorted(self._retired)

    def merge(self, sources: Dict[str, MetricsRegistry]) -> MetricsRegistry:
        """One fresh merged registry over ``sources`` + the retired
        ledger.  Safe against concurrent writers on the sources (each
        source's state is snapshotted under its own lock)."""
        out = MetricsRegistry()
        for sid in sorted(sources):
            reg = sources[sid]
            for entry in reg.counters_snapshot():
                out.counter(
                    entry["name"], labels=entry["labels"] or None
                ).add(entry["count"])
            with reg._lock:
                gauges = list(reg.gauges.items())
                timers = list(reg.timers.items())
                hists = list(reg.histograms.items())
                label_map = dict(reg._labels)
            for key, g in gauges:
                name, lbl = label_map.get(key, (key, {}))
                out.gauge(name, labels={**lbl, "replica": sid}).set(g.get())
            for key, t in timers:
                name, lbl = label_map.get(key, (key, {}))
                with t._lock:
                    n, total_s, max_s = t.n, t.total_s, t.max_s
                dst = out.timer(name, labels=lbl or None)
                with dst._lock:
                    dst.n += n
                    dst.total_s += total_s
                    dst.max_s = max(dst.max_s, max_s)
            for key, h in hists:
                name, lbl = label_map.get(key, (key, {}))
                with h._lock:
                    buckets = h.buckets
                    counts = list(h._counts)
                    hsum, hcount = h._sum, h._count
                    hmin, hmax = h._min, h._max
                dst = out.histogram(name, labels=lbl or None, buckets=buckets)
                if dst.buckets != buckets:
                    # Mismatched grid: bucket sums would corrupt —
                    # keep the source's distribution under its own
                    # replica-labeled series (documented semantics).
                    dst = out.histogram(
                        name,
                        labels={**lbl, "replica": sid},
                        buckets=buckets,
                    )
                with dst._lock:
                    for i, c in enumerate(counts):
                        dst._counts[i] += c
                    dst._sum += hsum
                    dst._count += hcount
                    if hmin is not None:
                        dst._min = hmin if dst._min is None else min(dst._min, hmin)
                    if hmax is not None:
                        dst._max = hmax if dst._max is None else max(dst._max, hmax)
        for key in sorted(self._retired):
            for entry in self._retired[key]:
                labels = dict(entry["labels"])
                labels.setdefault("replica", f"{key}@retired")
                out.counter(entry["name"], labels=labels).add(entry["count"])
        return out


class FleetPlane:
    """The one object the router, the reconfig controller, the web
    endpoints, and the console share (class docstring above).  All
    hooks are inert one-attribute checks when disabled."""

    def __init__(
        self,
        *,
        enabled: Optional[bool] = None,
        clock: Optional[Callable[[], float]] = None,
        journal=None,
        trace_path: Optional[str] = None,
        profile_dir: Optional[str] = None,
        bundle_dir: Optional[str] = None,
        anomaly: Optional[AnomalyConfig] = None,
        slo_latency_target_s: float = 0.25,
        slo_fast_window_s: float = 300.0,
        slo_slow_window_s: float = 3600.0,
        max_history: int = 4096,
    ):
        self.enabled = resolve_fleet_plane_enabled(enabled)
        self._clock = clock if clock is not None else time.monotonic
        #: Read-only context for postmortem bundles (the cluster
        #: journal) — the plane NEVER emits to it.
        self._journal = journal
        #: The plane's own series (fleet SLO gauges, anomaly counters,
        #: obs_lines_dropped) — merged in under source id "fleet".
        self.registry = MetricsRegistry()
        self.obslog = ObservationLog(
            trace_path=trace_path if self.enabled else None,
            metrics=self.registry,
            owner="router",
        )
        self._shim = _ObsJournal(self.obslog)
        self.aggregator = FleetAggregator()
        self.detector = AnomalyDetector(anomaly) if self.enabled else None
        self.profiler = (
            ProfileCapture(
                out_dir=profile_dir,
                journal=self._shim,
                metrics=self.registry,
                clock=self._clock,
            )
            if self.enabled and profile_dir
            else None
        )
        self._bundle_dir = bundle_dir
        self._slo_latency_target_s = slo_latency_target_s
        self._slo_fast_window_s = slo_fast_window_s
        self._slo_slow_window_s = slo_slow_window_s
        self._lock = threading.Lock()
        self._sources: Dict[str, dict] = {}
        self._chain_seq = 0
        self._step = 0
        self._slo = None
        self._slo_merged: Optional[MetricsRegistry] = None
        self._totals_history: deque = deque(maxlen=max_history)
        self._anomalies: deque = deque(maxlen=256)
        self._bundles: List[str] = []
        self._profile_started_step: Optional[int] = None

    # -- source roster -------------------------------------------------------

    def register_source(
        self,
        source_id: str,
        *,
        registry: MetricsRegistry,
        trace_path: Optional[str] = None,
    ) -> None:
        """Register one telemetry source (the router itself or a
        replica).  ``trace_path`` opens a per-source observation
        sidecar for that source's side of each hop; without one the
        source's hop records land on the plane's own log."""
        if not self.enabled:
            return
        log = (
            ObservationLog(
                trace_path=trace_path, metrics=self.registry, owner=source_id
            )
            if trace_path
            else None
        )
        with self._lock:
            self._sources[source_id] = {"registry": registry, "obslog": log}

    def retire_source(
        self, key: str, source_id: str, counters: List[dict]
    ) -> Optional[dict]:
        """Drop a source from the live roster and fold its counters
        into the retired ledger as the element-wise MAX of the last
        in-process scrape and ``counters`` (the recovered durable
        authority) — class docstring's monotonicity argument.  Returns
        the source's final observation accounting (for the router's
        retired ledger and postmortem bundles), or None when the plane
        is off."""
        if not self.enabled:
            return None
        with self._lock:
            src = self._sources.pop(source_id, None)
        folded: Dict[str, dict] = {}
        for entry in counters:
            e = {
                "name": entry["name"],
                "labels": dict(entry.get("labels") or {}),
                "count": float(entry.get("count", 0.0)),
            }
            folded[_entry_key(e["name"], e["labels"])] = e
        obs_stats = None
        if src is not None:
            for entry in src["registry"].counters_snapshot():
                k = _entry_key(entry["name"], entry["labels"])
                have = folded.get(k)
                if have is None:
                    folded[k] = {
                        "name": entry["name"],
                        "labels": dict(entry["labels"]),
                        "count": float(entry["count"]),
                    }
                else:
                    have["count"] = max(have["count"], float(entry["count"]))
            log = src["obslog"]
            if log is not None:
                obs_stats = {
                    "records": len(log),
                    "last_seq": log.last_seq(),
                    "dropped": log.dropped,
                }
                log.set_trace_file(None)
        self.aggregator.retire(
            key, [folded[k] for k in sorted(folded)]
        )
        if self.detector is not None:
            self.detector.drop_source(source_id)
        return obs_stats

    def _log_for(self, source_id: Optional[str]) -> ObservationLog:
        with self._lock:
            src = self._sources.get(source_id) if source_id else None
        if src is not None and src["obslog"] is not None:
            return src["obslog"]
        return self.obslog

    # -- hop chains ----------------------------------------------------------

    def hop_begin(
        self,
        claim_id: str,
        *,
        lineage: str,
        origin: str,
        target: Optional[str],
        reason: str,
    ) -> Optional[HopContext]:
        """Mint one hop chain for a routing decision; None when off."""
        if not self.enabled:
            return None
        with self._lock:
            self._chain_seq += 1
            chain = f"h{self._chain_seq:06d}"
        return HopContext(chain, claim_id, lineage, origin, target, reason)

    def hop_send(self, ctx: Optional[HopContext], **extra) -> None:
        """Record the origin-side ``send`` for the NEXT transport
        attempt (increments the hop seq) — called immediately before
        the transport call, so a request cut down mid-call leaves this
        record as its last trace."""
        if ctx is None:
            return
        ctx.hop += 1
        self._log_for(ctx.origin).record(
            "hop",
            lineage=ctx.lineage,
            hop=ctx.hop,
            side="send",
            **ctx.as_data(),
            **extra,
        )

    def hop_recv(self, ctx: Optional[HopContext], **extra) -> None:
        """Record the destination-side ``recv`` on the TARGET's sidecar
        — the hop landed; the chain is complete."""
        if ctx is None:
            return
        self._log_for(ctx.target).record(
            "hop",
            lineage=ctx.lineage,
            hop=ctx.hop,
            side="recv",
            **ctx.as_data(),
            **extra,
        )

    def hop_end(
        self, ctx: Optional[HopContext], *, outcome: str, **extra
    ) -> None:
        """Record a terminal ``end`` on the origin: a typed refusal or
        failure closed the chain without a recv."""
        if ctx is None:
            return
        self._log_for(ctx.origin).record(
            "hop",
            lineage=ctx.lineage,
            hop=ctx.hop,
            side="end",
            outcome=outcome,
            **ctx.as_data(),
            **extra,
        )

    def hop_refused(
        self,
        claim_id: str,
        *,
        lineage: str,
        reason: str,
        outcome: str,
        target: Optional[str] = None,
        **extra,
    ) -> None:
        """One-shot chain for a router-local verdict (redirect,
        reconfig-defer, replica-down shed): no transport attempt ever
        happens, so the chain is a single terminal record."""
        ctx = self.hop_begin(
            claim_id,
            lineage=lineage,
            origin="router",
            target=target,
            reason=reason,
        )
        self.hop_end(ctx, outcome=outcome, **extra)

    # -- aggregation + SLOs --------------------------------------------------

    def merged_registry(self) -> MetricsRegistry:
        """The fleet merge over every registered source (live registry
        state), the retired ledger, and the plane's own registry."""
        with self._lock:
            sources = {
                sid: src["registry"] for sid, src in self._sources.items()
            }
        sources["fleet"] = self.registry
        return self.aggregator.merge(sources)

    def render_prometheus_fleet(self) -> str:
        """``GET /metrics/fleet``: the merged exposition."""
        return self.merged_registry().render_prometheus()

    def _slo_source(self) -> MetricsRegistry:
        merged = self._slo_merged
        return merged if merged is not None else self.merged_registry()

    def _slo_evaluator(self):
        if self._slo is None:
            from svoc_tpu.utils.slo import SLOEvaluator, fleet_slos

            self._slo = SLOEvaluator(
                fleet_slos(
                    self._slo_source,
                    latency_target_s=self._slo_latency_target_s,
                    fast_window_s=self._slo_fast_window_s,
                    slow_window_s=self._slo_slow_window_s,
                ),
                registry=self.registry,
                journal=self._shim,
                clock=self._clock,
            )
        return self._slo

    def evaluate_slos(self) -> dict:
        """One fleet SLO pass over a fresh merge (console / web)."""
        if not self.enabled:
            return {}
        return self._slo_evaluator().evaluate()

    # -- step cadence --------------------------------------------------------

    def on_step(self, live_sources: Dict[str, MetricsRegistry]) -> None:
        """The router's per-step hook: close out any anomaly-triggered
        profile from the PREVIOUS step (so ``profile.captured`` is
        witnessed deterministically in-run), evaluate the fleet SLOs
        over one shared merge, append the accounting-family totals to
        the monotonicity history, and feed the anomaly detector the
        live sources' degradation families."""
        if not self.enabled:
            return
        self._step += 1
        if (
            self.profiler is not None
            and self._profile_started_step is not None
            and self._step > self._profile_started_step
        ):
            self.profiler.stop()
            self._profile_started_step = None
        merged = self.merged_registry()
        self._slo_merged = merged
        try:
            self._slo_evaluator().evaluate()
        finally:
            self._slo_merged = None
        self._totals_history.append(
            {
                "step": self._step,
                **{f: merged.family_total(f) for f in ACCOUNTING_FAMILIES},
            }
        )
        if self.detector is None:
            return
        totals: Dict[tuple, float] = {}
        for sid in sorted(live_sources):
            reg = live_sources[sid]
            for family in self.detector.config.families:
                totals[(sid, family)] = reg.family_total(family)
        for alert in self.detector.on_step(self._step, totals):
            self._record_anomaly(alert)

    def _record_anomaly(self, alert: dict) -> None:
        self._anomalies.append(alert)
        self.obslog.record("anomaly.detected", scope="fleet", **alert)
        self.registry.counter(
            "anomaly_detected",
            labels={"replica": alert["source"], "family": alert["family"]},
        ).add(1)
        if not alert["sustained"]:
            return
        if self.profiler is not None:
            report = self.profiler.maybe_capture("anomaly")
            if report is not None and report.get("status") == "started":
                self._profile_started_step = self._step
        if self._bundle_dir is not None:
            self._build_bundle(alert)

    def _build_bundle(self, alert: dict) -> None:
        from svoc_tpu.utils.postmortem import build_bundle

        try:
            path = build_bundle(
                out_dir=self._bundle_dir,
                trigger="anomaly",
                trigger_event={"type": "anomaly.detected", "data": alert},
                registry=self.merged_registry(),
                journal=self._journal,
                slo=self._slo,
                extra={
                    "fleet_obs": self.obs_accounting(),
                    "anomaly": alert,
                },
            )
        except OSError as e:
            # Telemetry never takes serving down: a bundle that cannot
            # write is counted and typed, not raised (SVOC014).
            self.registry.counter(
                "fleet_plane_errors", labels={"op": "bundle"}
            ).add(1)
            self.obslog.record(
                "postmortem.bundle",
                scope="fleet",
                trigger="anomaly",
                error=f"{type(e).__name__}: {e}",
            )
            return
        self._bundles.append(path)
        self.registry.counter(
            "postmortem_bundles", labels={"trigger": "anomaly"}
        ).add(1)
        self.obslog.record(
            "postmortem.bundle", scope="fleet", trigger="anomaly", path=path
        )

    # -- accounting / views --------------------------------------------------

    def obs_accounting(self) -> Dict[str, dict]:
        """Per-source observation-channel accounting (records in ring,
        last seq, dropped exports) — ``fleet_accounting``'s
        ``observations`` section and the bundle's truncation witness."""
        with self._lock:
            items = sorted(self._sources.items())
        out: Dict[str, dict] = {}
        for sid, src in items:
            log = src["obslog"] if src["obslog"] is not None else self.obslog
            out[sid] = {
                "records": len(log),
                "last_seq": log.last_seq(),
                "dropped": log.dropped,
            }
        if "router" not in out:
            out["router"] = {
                "records": len(self.obslog),
                "last_seq": self.obslog.last_seq(),
                "dropped": self.obslog.dropped,
            }
        return out

    def source_observations(self, source_id: str) -> Optional[dict]:
        """One live source's observation accounting, or None."""
        return self.obs_accounting().get(source_id)

    def accounting_history(self) -> List[dict]:
        """Per-step merged accounting-family totals (on_step cadence)
        — the monotonicity regression's evidence."""
        return [dict(h) for h in self._totals_history]

    def anomalies(self) -> List[dict]:
        return [dict(a) for a in self._anomalies]

    def bundles(self) -> List[str]:
        return list(self._bundles)

    def snapshot(self) -> dict:
        """The ``/api/state`` fleet-obs section / console ``fleet``."""
        if not self.enabled:
            return {"enabled": False}
        out = {
            "enabled": True,
            "step": self._step,
            "sources": sorted(self._sources),
            "retired": self.aggregator.retired_keys(),
            "chains": self._chain_seq,
            "observations": self.obs_accounting(),
            "slo": {
                "alerting": self._slo.alerting() if self._slo else [],
            },
            "anomaly": (
                self.detector.summary() if self.detector is not None else {}
            ),
            "recent_anomalies": self.anomalies()[-8:],
            "bundles": self.bundles(),
        }
        if self.profiler is not None:
            out["profiler"] = self.profiler.status()
        return out

    def attach(self, console) -> None:
        """Expose through a CommandConsole: the ``fleet`` command,
        ``GET /metrics/fleet``, and the ``/api/state`` fleet section
        read ``console.fleetplane``."""
        console.fleetplane = self
