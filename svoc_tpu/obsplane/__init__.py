"""Cost-attribution plane (docs/OBSERVABILITY.md §cost-attribution):
per-request latency decomposition, the shape-keyed dispatch-cost
ledger, and on-demand profiling — the telemetry substrate ROADMAP
items 1 (fleet placement) and 2 (cost-model scheduling) consume."""

from svoc_tpu.obsplane.ledger import (
    CostLedger,
    CostModel,
    group_key,
    ledger_key,
)
from svoc_tpu.obsplane.plane import (
    REQUEST_STAGE_HISTOGRAM,
    CostPlane,
    resolve_cost_plane,
    resolve_cost_plane_enabled,
)
from svoc_tpu.obsplane.profiler import ProfileCapture
from svoc_tpu.obsplane.timeline import (
    MARKS,
    STAGE_OF_MARK,
    ObservationLog,
    RequestTimeline,
    read_observations,
)

__all__ = [
    "CostLedger",
    "CostModel",
    "CostPlane",
    "MARKS",
    "ObservationLog",
    "ProfileCapture",
    "REQUEST_STAGE_HISTOGRAM",
    "RequestTimeline",
    "STAGE_OF_MARK",
    "group_key",
    "ledger_key",
    "read_observations",
    "resolve_cost_plane",
    "resolve_cost_plane_enabled",
]
