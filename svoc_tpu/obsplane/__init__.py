"""Observability planes (docs/OBSERVABILITY.md): the cost-attribution
plane (§cost-attribution — per-request latency decomposition, the
shape-keyed dispatch-cost ledger, on-demand profiling) and the fleet
plane (§fleet-plane — cross-replica hop-chain tracing, merged fleet
telemetry + SLOs, seeded anomaly detection) — the telemetry substrate
ROADMAP items 1 (fleet placement) and 2 (cost-model scheduling)
consume."""

from svoc_tpu.obsplane.anomaly import (
    DEFAULT_ANOMALY_FAMILIES,
    AnomalyConfig,
    AnomalyDetector,
)
from svoc_tpu.obsplane.fleet import (
    ACCOUNTING_FAMILIES,
    FleetAggregator,
    FleetPlane,
    resolve_fleet_plane_enabled,
)
from svoc_tpu.obsplane.hopchain import (
    HOP_REASONS,
    HopContext,
    chain_stats,
    join_hop_chains,
)
from svoc_tpu.obsplane.ledger import (
    CostLedger,
    CostModel,
    group_key,
    ledger_key,
)
from svoc_tpu.obsplane.plane import (
    REQUEST_STAGE_HISTOGRAM,
    CostPlane,
    resolve_cost_plane,
    resolve_cost_plane_enabled,
)
from svoc_tpu.obsplane.profiler import ProfileCapture
from svoc_tpu.obsplane.timeline import (
    MARKS,
    STAGE_OF_MARK,
    ObservationLog,
    RequestTimeline,
    read_observations,
)

__all__ = [
    "ACCOUNTING_FAMILIES",
    "AnomalyConfig",
    "AnomalyDetector",
    "CostLedger",
    "CostModel",
    "CostPlane",
    "DEFAULT_ANOMALY_FAMILIES",
    "FleetAggregator",
    "FleetPlane",
    "HOP_REASONS",
    "HopContext",
    "MARKS",
    "ObservationLog",
    "ProfileCapture",
    "REQUEST_STAGE_HISTOGRAM",
    "RequestTimeline",
    "STAGE_OF_MARK",
    "chain_stats",
    "group_key",
    "join_hop_chains",
    "ledger_key",
    "read_observations",
    "resolve_cost_plane",
    "resolve_cost_plane_enabled",
    "resolve_fleet_plane_enabled",
]
