"""Cross-replica hop-chain trace propagation (docs/OBSERVABILITY.md
§fleet-plane).

A request that enters the fleet through :meth:`ClusterRouter.submit`
leaves half its story on the router (redirect/shed/forward decisions)
and half on the owning replica (admission, serving, completion).  The
hop chain stitches the halves back together: every routing decision
mints a :class:`HopContext` carrying a fleet-unique ``chain`` id, and
the fleet plane records one ``"hop"`` observation on EACH side of the
hop — a ``send`` record on the origin's observation sidecar before the
transport call, a ``recv`` record on the destination's sidecar after
it lands, and a terminal ``end`` record on the origin for every typed
refusal (redirect, reconfig-defer, shed, quarantine).

The records ride the ``obs`` channel ONLY (PR 16's third line shape —
:class:`~svoc_tpu.obsplane.timeline.ObservationLog`), never the
fingerprinted journal ring: hop telemetry must not shift journal seqs,
or the fleet-plane ON/OFF byte-identity `make fleet-obs-smoke`
certifies would break.  That one-sidedness is also what makes the join
diagnostic: a ``send`` with no matching ``recv`` and no terminal is a
request that **died mid-hop** (the transport call was cut down between
the two records — an injected fault, a replica death mid-call), which
is precisely the evidence a journal-only view cannot show, because the
dead side never journaled anything.

:func:`join_hop_chains` is the offline join — `tools/obs_query.py
--fleet` and the smoke both build per-chain causal timelines from the
per-source sidecar files with it.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

#: The routing decisions a hop chain can carry (docs/OBSERVABILITY.md
#: §fleet-plane hop table).
HOP_REASONS = (
    "forward",
    "redirect",
    "migrate",
    "failover",
    "reconfig-defer",
)

#: Record ordering inside one chain: each attempt's ``send`` precedes
#: its ``recv``; a terminal ``end`` sorts last at its hop.
_SIDE_ORDER = {"send": 0, "recv": 1, "end": 2}


class HopContext:
    """One routed request's hop state: the fleet-unique chain id, the
    claim lineage it joins under, the endpoints, the typed reason, and
    a monotone hop (attempt) sequence — ``hop`` increments per
    transport attempt, so a retried forward leaves attempt 1's
    unanswered ``send`` as evidence while attempt 2 completes."""

    __slots__ = ("chain", "claim", "lineage", "origin", "target", "reason", "hop")

    def __init__(
        self,
        chain: str,
        claim: str,
        lineage: str,
        origin: str,
        target: Optional[str],
        reason: str,
    ):
        if reason not in HOP_REASONS:
            raise ValueError(f"unknown hop reason {reason!r}")
        self.chain = chain
        self.claim = claim
        self.lineage = lineage
        self.origin = origin
        self.target = target
        self.reason = reason
        self.hop = 0

    def as_data(self) -> Dict[str, object]:
        """The invariant half of every record this chain emits."""
        return {
            "chain": self.chain,
            "claim": self.claim,
            "src": self.origin,
            "dst": self.target,
            "reason": self.reason,
        }


def join_hop_chains(records: Iterable[dict]) -> Dict[str, Dict[str, object]]:
    """Join ``"hop"`` observation records (from ANY number of per-source
    sidecar files) into per-chain causal timelines.

    Returns ``{chain_id: chain}`` where each chain carries its claim,
    lineage, reason, endpoints, the records sorted into causal order,
    the per-attempt fate, and a three-way classification:

    - ``complete`` — a ``recv`` landed on the destination: the request
      (or migration slice) arrived.  Earlier unanswered ``send``
      attempts are listed in ``dead_attempts`` (a retried transport
      fault).
    - ``terminal`` — no ``recv``, but a typed ``end`` record closed the
      chain (redirect, reconfig-defer, shed, quarantine); ``outcome``
      carries the type.
    - ``died_mid_hop`` — a ``send`` with neither a ``recv`` nor a
      terminal: the request was cut down between the two sides of the
      hop and no surviving process accounted for it.
    """
    chains: Dict[str, Dict[str, object]] = {}
    for rec in records:
        if rec.get("obs") != "hop":
            continue
        data = rec.get("data", {})
        chain_id = data.get("chain")
        if not chain_id:
            continue
        chain = chains.setdefault(
            chain_id,
            {
                "chain": chain_id,
                "claim": data.get("claim"),
                "lineage": rec.get("lineage"),
                "reason": data.get("reason"),
                "src": data.get("src"),
                "dst": data.get("dst"),
                "records": [],
            },
        )
        chain["records"].append(rec)
    for chain in chains.values():
        recs: List[dict] = chain["records"]
        recs.sort(
            key=lambda r: (
                r["data"].get("hop", 0),
                _SIDE_ORDER.get(r["data"].get("side"), 3),
            )
        )
        sends = {
            r["data"]["hop"] for r in recs if r["data"].get("side") == "send"
        }
        recvs = {
            r["data"]["hop"] for r in recs if r["data"].get("side") == "recv"
        }
        ends = [r for r in recs if r["data"].get("side") == "end"]
        chain["attempts"] = len(sends)
        chain["dead_attempts"] = sorted(sends - recvs)
        if recvs:
            chain["classification"] = "complete"
            chain["outcome"] = "delivered"
        elif ends:
            chain["classification"] = "terminal"
            chain["outcome"] = ends[-1]["data"].get("outcome", "unknown")
        else:
            chain["classification"] = "died_mid_hop"
            chain["outcome"] = "lost"
    return chains


def chain_stats(chains: Dict[str, Dict[str, object]]) -> Dict[str, object]:
    """Roll-up for the smoke gate and ``obs_query --fleet``'s footer:
    classification counts, per-reason counts, and the total number of
    unanswered send attempts (retried transport faults + mid-hop
    deaths — both are evidence, not noise)."""
    by_class: Dict[str, int] = {}
    by_reason: Dict[str, int] = {}
    dead_attempts = 0
    for chain in chains.values():
        by_class[chain["classification"]] = (
            by_class.get(chain["classification"], 0) + 1
        )
        reason = chain.get("reason") or "unknown"
        by_reason[reason] = by_reason.get(reason, 0) + 1
        dead_attempts += len(chain["dead_attempts"])
    return {
        "chains": len(chains),
        "by_classification": dict(sorted(by_class.items())),
        "by_reason": dict(sorted(by_reason.items())),
        "dead_attempts": dead_attempts,
    }
