"""Per-request latency decomposition (docs/OBSERVABILITY.md
§cost-attribution).

Two pieces:

- :class:`ObservationLog` — the **observation channel**: a sidecar
  stream of ``"obs"``-keyed JSONL lines sharing the flight-recorder
  file with spans (keyed ``"name"``) and journal events (keyed
  ``"event"``), plus its own bounded in-memory ring.  Observation
  records NEVER enter the :class:`~svoc_tpu.utils.events.EventJournal`:
  the replay fingerprint digests every journal record *including its
  seq*, so a timeline record in the ring would shift sibling seqs and
  break the ON-vs-OFF byte-identity `make obs-cost-smoke` certifies.
  ``read_trace_events`` keeps only ``"event"``-keyed lines, so recovery
  roll-forward is equally blind to this channel — observations are
  derived telemetry, not replayable history.
- :class:`RequestTimeline` — ordered marks on ONE clock (the serving
  tier's: virtual in seeded scenarios, monotonic live) along a
  request's path: admitted → assembled → vectorized → h2d → dispatched
  → synced → committed → completed.  Stage durations are differences of
  CONSECUTIVE marks, so their sum telescopes exactly to the end-to-end
  latency — gapless by construction, which the smoke asserts.  Under a
  virtual clock every intra-step stage is 0 and ``queue_wait`` carries
  the steps a request waited; live, each stage carries real host time.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from svoc_tpu.utils.events import release_writer, shared_writer

#: Canonical mark order.  A timeline may skip marks (a deferred claim
#: has no commit cycle that step); the stage between two PRESENT
#: neighbors is named after the later mark, so sums stay telescoping.
MARKS = (
    "admitted",
    "assembled",
    "vectorized",
    "h2d",
    "dispatched",
    "synced",
    "committed",
    "completed",
)

#: mark → the stage name that ENDS at it (docs/OBSERVABILITY.md's
#: stage table).  ``admitted`` starts the clock and ends nothing.
STAGE_OF_MARK = {
    "assembled": "queue_wait",
    "vectorized": "vectorize",
    "h2d": "h2d",
    "dispatched": "dispatch",
    "synced": "sync",
    "committed": "commit",
    "completed": "respond",
}

_MARK_ORDER = {name: i for i, name in enumerate(MARKS)}


class RequestTimeline:
    """Marks along one serving request's path, all on one clock."""

    __slots__ = ("lineage", "claim", "marks")

    def __init__(self, lineage: str, claim: str, t_submit: float):
        self.lineage = lineage
        self.claim = claim
        self.marks: List[Tuple[str, float]] = [("admitted", t_submit)]

    def mark(self, name: str, t: float) -> None:
        """Record one mark; re-marks of the same name are ignored (the
        first crossing wins — a request served from a claim that
        dispatched twice in one step keeps its first completion path)."""
        if name not in _MARK_ORDER:
            raise ValueError(f"unknown timeline mark {name!r}")
        if any(existing == name for existing, _ in self.marks):
            return
        self.marks.append((name, t))

    def extend(self, marks) -> None:
        """Merge externally-collected ``(name, t)`` marks (the router's
        per-claim dispatch marks)."""
        for name, t in marks:
            self.mark(name, t)

    def stages(self) -> Dict[str, float]:
        """Stage durations between consecutive PRESENT marks, in mark
        order.  Never negative (a claim mark taken before this
        request's own vectorize mark under a live clock clamps to 0 —
        the sum check tolerance covers the clamp)."""
        ordered = sorted(self.marks, key=lambda m: _MARK_ORDER[m[0]])
        out: Dict[str, float] = {}
        for (_prev, t_prev), (name, t) in zip(ordered, ordered[1:]):
            out[STAGE_OF_MARK[name]] = max(0.0, t - t_prev)
        return out

    def e2e_s(self) -> float:
        ordered = sorted(self.marks, key=lambda m: _MARK_ORDER[m[0]])
        return max(0.0, ordered[-1][1] - ordered[0][1])


class ObservationLog:
    """Bounded ring + ``"obs"``-keyed JSONL sidecar for derived
    telemetry (timelines, cost samples).  Same writer pool and
    rotation/error-latch discipline as the tracer; its seq counter is
    its OWN — observation seqs never interleave with journal seqs."""

    def __init__(
        self,
        *,
        max_records: int = 4096,
        trace_path: Optional[str] = None,
        metrics=None,
        owner: Optional[str] = None,
    ):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max_records)
        self._seq = 0
        self._writer = None
        self._trace_path: Optional[str] = None
        self._write_error_latched = False
        #: Attribution for the fleet plane: when ``owner`` is set, every
        #: record the latch silences counts under
        #: ``obs_lines_dropped{replica=owner}`` on ``metrics`` — a
        #: truncated sidecar must be visible in the fleet scrape and in
        #: postmortem bundles, not just as a diff of missing lines.
        self._metrics = metrics
        self._owner = owner
        self._dropped = 0
        if trace_path:
            self.set_trace_file(trace_path)

    def set_trace_file(self, path: Optional[str]) -> None:
        with self._lock:
            old = self._trace_path
            self._trace_path = path
            self._writer = shared_writer(path) if path else None
            self._write_error_latched = False
        if old and old != path:
            release_writer(old)

    def record(self, kind: str, *, lineage: Optional[str] = None, **data) -> dict:
        """Append one observation; JSONL write happens outside the
        lock (leaf-lock discipline, same as the journal's)."""
        import json

        with self._lock:
            self._seq += 1
            rec = {
                "obs": kind,
                "seq": self._seq,
                "lineage": lineage,
                "data": data,
            }
            self._ring.append(rec)
            writer = self._writer
            latched = self._write_error_latched
        if writer is not None and not latched:
            try:
                writer.write_line(json.dumps(rec, sort_keys=True))
            except OSError:
                # Loud-but-open: the plane keeps its in-memory ring, the
                # latch stops per-record error spam, and the lost export
                # is COUNTED under the tracer's write-error family so a
                # full disk shows up on the dashboard, not in a diff of
                # missing obs lines.
                from svoc_tpu.utils.metrics import registry as _metrics

                _metrics.counter("trace_write_errors").add(1)
                with self._lock:
                    self._write_error_latched = True
                self._count_dropped()
        elif writer is not None and latched:
            # Every record the latch silences is a lost export.
            self._count_dropped()
        return rec

    def _count_dropped(self) -> None:
        with self._lock:
            self._dropped += 1
        if self._owner is not None and self._metrics is not None:
            self._metrics.counter(
                "obs_lines_dropped", labels={"replica": self._owner}
            ).add(1)

    def last_seq(self) -> int:
        """The newest observation seq — the sidecar-truncation witness
        postmortem bundles and ``fleet_accounting`` carry: a sidecar
        whose tail seq lags this was cut short."""
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        """Records that never reached the sidecar (write-error latch)."""
        with self._lock:
            return self._dropped

    @property
    def write_error_latched(self) -> bool:
        with self._lock:
            return self._write_error_latched

    def recent(
        self,
        n: int = 50,
        *,
        kind: Optional[str] = None,
        lineage: Optional[str] = None,
    ) -> List[dict]:
        with self._lock:
            records = list(self._ring)
        if kind is not None:
            records = [r for r in records if r["obs"] == kind]
        if lineage is not None:
            records = [r for r in records if r["lineage"] == lineage]
        return records[-n:]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def read_observations(path: str, keep: Optional[int] = None) -> List[dict]:
    """Offline twin of ``read_trace_events`` for the observation
    channel: every ``"obs"``-keyed line across the rotated segment
    chain, oldest first, tolerating a torn final line."""
    import json
    import os

    keep = keep if keep is not None else 8
    out: List[dict] = []
    segments = [f"{path}.{i}" for i in range(keep, 0, -1)] + [path]
    for segment in segments:
        if not os.path.exists(segment):
            continue
        with open(segment, "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail of a crashed writer
                if isinstance(rec, dict) and "obs" in rec:
                    out.append(rec)
    return out
