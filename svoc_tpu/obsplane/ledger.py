"""Shape-keyed dispatch-cost ledger (docs/OBSERVABILITY.md
§cost-attribution).

The router already keys every claim-cube dispatch by a
:class:`~svoc_tpu.compile.universe.CompileKey` (its warmth accounting)
— the ledger folds the measured host cost of each dispatch into an EMA
per ``(key, warmth)`` cell, so ROADMAP item 2's scheduler can ask
"what does a warm c8n7m6 sanitized dispatch cost HERE?" and get a
number measured on this box instead of a guess.

Key schema: ``CompileKey.label()`` deliberately omits ``cfg`` and
``impl`` (metrics-label compactness), so the ledger string appends
both deterministically::

    sanitized:c4n7m6|xla|cfg#9d3a

(`cfg#xxxx` is crc32-of-``repr(cfg)`` — stable across processes for
equal configs, and two claims with different consensus configs never
share a cost cell).  Samples are ``time.perf_counter`` windows from
the router — REAL host seconds, independent of the scenario's virtual
clock — and they reach fingerprints nowhere: the ledger lives outside
the journal, and its ``cost.sample`` records ride the observation
channel (:mod:`svoc_tpu.obsplane.timeline`).

:class:`CostModel` is the read API: ``estimate(key)`` answers for
EVERY key the compile plane's universe enumerates, falling back from
the exact cell to the ``(n_oracles, dimension)`` group average to the
global average (source-labeled, so a scheduler can discount borrowed
estimates).  The EMA fold is order-deterministic: replaying the same
``cost.sample`` stream through :meth:`CostLedger.observe` reproduces
the persisted cell values exactly (``tools/obs_query.py``'s offline
reconstruction).
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, Optional

#: EMA smoothing for cost folds — matches LatencyTimer's cadence-free
#: convention; small enough to damp one slow outlier dispatch, large
#: enough that a recompile-induced regime change shows within ~10
#: dispatches.
DEFAULT_ALPHA = 0.2

WARMTHS = ("cold", "prewarmed", "warm")


def ledger_key(key) -> str:
    """Deterministic ledger cell id for a CompileKey — ``label()``
    plus the impl and a crc32 cfg signature it omits."""
    cfg_sig = zlib.crc32(repr(key.cfg).encode()) & 0xFFFF
    return f"{key.label()}|{key.impl}|cfg#{cfg_sig:04x}"


def group_key(key) -> str:
    """The fallback-pool id: keys sharing (N, M) have comparable
    per-dispatch cost regardless of bucket/kind/donate."""
    return f"n{key.n_oracles}m{key.dimension}"


class CostLedger:
    """EMA cost cells keyed ``ledger_key × warmth``; thread-safe
    (router dispatch threads fold, snapshot/console read)."""

    def __init__(self, *, alpha: float = DEFAULT_ALPHA):
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        self.alpha = alpha
        self._lock = threading.Lock()
        #: {key_str: {"group": str, "warmth": {w: {"ema_s", "samples"}}}}
        self._entries: Dict[str, dict] = {}

    def observe_key_str(
        self, key_str: str, group: str, warmth: str, seconds: float
    ) -> None:
        """Fold one measured dispatch into its cell (string-keyed twin
        of :meth:`observe` — the offline reconstruction path, which has
        JSONL records instead of CompileKeys)."""
        seconds = float(seconds)
        with self._lock:
            entry = self._entries.setdefault(
                key_str, {"group": group, "warmth": {}}
            )
            cell = entry["warmth"].get(warmth)
            if cell is None:
                entry["warmth"][warmth] = {"ema_s": seconds, "samples": 1}
            else:
                cell["ema_s"] += self.alpha * (seconds - cell["ema_s"])
                cell["samples"] += 1

    def observe(self, key, warmth: str, seconds: float) -> str:
        """Fold one dispatch measured against its CompileKey; returns
        the cell id (the router's ``cost.sample`` record carries it)."""
        key_str = ledger_key(key)
        self.observe_key_str(key_str, group_key(key), warmth, seconds)
        return key_str

    def to_dict(self) -> dict:
        """JSON-safe state (the ``cost_ledger.json`` snapshot payload
        and the ``/api/state`` costs section)."""
        with self._lock:
            return {
                "version": 1,
                "alpha": self.alpha,
                "entries": {
                    k: {
                        "group": e["group"],
                        "warmth": {
                            w: dict(c) for w, c in e["warmth"].items()
                        },
                    }
                    for k, e in self._entries.items()
                },
            }

    def restore(self, payload: dict) -> int:
        """Load persisted cells (snapshot recovery); returns the count.
        Tolerates absent/foreign payloads — a ledger is derived
        telemetry, never worth failing a recovery over."""
        entries = (payload or {}).get("entries")
        if not isinstance(entries, dict):
            return 0
        cleaned: Dict[str, dict] = {}
        for key_str, entry in entries.items():
            warmth = entry.get("warmth") if isinstance(entry, dict) else None
            if not isinstance(warmth, dict):
                continue
            cells = {
                w: {"ema_s": float(c["ema_s"]), "samples": int(c["samples"])}
                for w, c in warmth.items()
                if isinstance(c, dict) and "ema_s" in c and "samples" in c
            }
            if cells:
                cleaned[key_str] = {
                    "group": str(entry.get("group", "")),
                    "warmth": cells,
                }
        with self._lock:
            self._entries.update(cleaned)
            return len(self._entries)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def summary(self) -> dict:
        with self._lock:
            samples = sum(
                c["samples"]
                for e in self._entries.values()
                for c in e["warmth"].values()
            )
            return {
                "keys": len(self._entries),
                "samples": samples,
                "alpha": self.alpha,
            }


class CostModel:
    """The scheduler-facing read API over a :class:`CostLedger`
    (ROADMAP item 2).  Estimates are dicts, not bare floats, because
    the SOURCE matters to a placement decision: an ``exact`` warm
    number is load-bearing, a ``group`` borrow is a same-shape-family
    guess, a ``global`` borrow is barely better than nothing — and
    ``None`` means the fleet has measured nothing at all yet."""

    def __init__(self, ledger: CostLedger):
        self.ledger = ledger

    @staticmethod
    def _warm_cold(cells: Dict[str, dict]) -> Dict[str, Optional[dict]]:
        """Collapse warmth cells to the scheduler's two regimes: warm
        (steady-state; ``prewarmed`` counts — an AOT-compiled first
        dispatch pays no compile) and cold (first-touch)."""
        warm = cells.get("warm") or cells.get("prewarmed")
        cold = cells.get("cold")
        return {"warm": warm, "cold": cold}

    def estimate(self, key) -> dict:
        """Warm/cold cost estimates for one CompileKey, with fallback:
        exact cell → (N, M) group average → global average.  Each
        regime falls back independently (a key dispatched only warm
        borrows its cold estimate from the group)."""
        key_str = ledger_key(key)
        group = group_key(key)
        with self.ledger._lock:
            entries = {
                k: {
                    "group": e["group"],
                    "warmth": {w: dict(c) for w, c in e["warmth"].items()},
                }
                for k, e in self.ledger._entries.items()
            }

        exact = self._warm_cold(entries[key_str]["warmth"]) if key_str in entries else {"warm": None, "cold": None}

        def pool_average(pool) -> Dict[str, Optional[dict]]:
            sums = {"warm": [0.0, 0], "cold": [0.0, 0]}
            for entry in pool:
                regimes = self._warm_cold(entry["warmth"])
                for regime, cell in regimes.items():
                    if cell is not None:
                        sums[regime][0] += cell["ema_s"]
                        sums[regime][1] += 1
            return {
                regime: (
                    {"ema_s": total / n, "samples": n}
                    if n
                    else None
                )
                for regime, (total, n) in sums.items()
            }

        group_avg = pool_average(
            e for e in entries.values() if e["group"] == group
        )
        global_avg = pool_average(entries.values())

        out = {"key": key_str, "group": group}
        for regime in ("warm", "cold"):
            for source, cell in (
                ("exact", exact[regime]),
                ("group", group_avg[regime]),
                ("global", global_avg[regime]),
            ):
                if cell is not None:
                    out[regime] = {
                        "seconds": cell["ema_s"],
                        "source": source,
                        "samples": cell["samples"],
                    }
                    break
            else:
                out[regime] = None
        return out
