"""The cost-attribution plane: one object wiring timelines, the cost
ledger, and the observation channel through the serving tier and the
claim router (docs/OBSERVABILITY.md §cost-attribution).

The plane's ``enabled`` flag is resolved ONCE at construction
(``SVOC_COST_PLANE`` env > the committed ``PERF_DECISIONS.json``
``cost_plane`` routing > off — the same pinning discipline as
``consensus_impl``/``commit_mode``, SVOC011): a half-run flag flip
would split a request's marks across regimes.  Disabled, every hook is
a cheap attribute check and the serving hot path is byte-for-byte the
same stream of journal events — ``make obs-cost-smoke`` certifies the
fingerprints ON vs OFF.

Two clocks, deliberately:

- **timeline marks** use the TIER's clock (virtual in seeded
  scenarios) — stage sums must telescope to the same end-to-end
  latency the ``request_latency_seconds`` histogram sees;
- **ledger samples** use ``time.perf_counter`` — the scheduler needs
  the real host cost of a dispatch, which a virtual clock cannot see.

Neither reaches a fingerprint: marks aggregate into the
``request_stage_seconds{stage=,claim=}`` histogram and the observation
channel; ledger samples live in the ledger and ``cost.sample``
observation records.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from svoc_tpu.obsplane.ledger import CostLedger, CostModel, group_key
from svoc_tpu.obsplane.timeline import ObservationLog, RequestTimeline
from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry

#: The per-stage, per-claim latency decomposition histogram — the
#: request_latency_seconds twin with a stage axis.
REQUEST_STAGE_HISTOGRAM = "request_stage_seconds"


def _decisions_cost_plane() -> Optional[str]:
    """The committed ``cost_plane`` routing, or None."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "PERF_DECISIONS.json",
    )
    try:
        with open(path) as f:
            decisions = json.load(f)
        value = decisions.get("cost_plane")
        return value if isinstance(value, str) else None
    except (OSError, ValueError, AttributeError):
        return None


def resolve_cost_plane_enabled(enabled: Optional[bool] = None) -> bool:
    """Construction-time resolution: explicit arg > ``SVOC_COST_PLANE``
    env (`1/on/true` vs `0/off/false`) > PERF_DECISIONS.json
    ``cost_plane`` > off."""
    if enabled is not None:
        return bool(enabled)
    env = os.environ.get("SVOC_COST_PLANE", "").strip().lower()
    if env in ("1", "on", "true", "yes"):
        return True
    if env in ("0", "off", "false", "no"):
        return False
    return _decisions_cost_plane() == "on"


class CostPlane:
    """Timeline recorder + cost ledger + observation log behind one
    enabled flag.  Thread-safety: timeline marks for one request happen
    on the tier's step thread; the router's per-claim marks are stored
    per step and folded on the same thread; the ledger and log lock
    internally."""

    def __init__(
        self,
        *,
        enabled: Optional[bool] = None,
        clock: Optional[Callable[[], float]] = None,
        metrics: Optional[MetricsRegistry] = None,
        trace_path: Optional[str] = None,
        alpha: Optional[float] = None,
    ):
        self.enabled = resolve_cost_plane_enabled(enabled)
        self._clock = clock if clock is not None else time.monotonic
        self._metrics = metrics or _default_registry
        self.obslog = ObservationLog(trace_path=trace_path)
        self.ledger = CostLedger(**({"alpha": alpha} if alpha else {}))
        self.model = CostModel(self.ledger)
        #: Per-claim dispatch marks for the CURRENT serving step
        #: ({claim_id: [(mark, t)]}); the router writes, the tier folds
        #: into each completed request's timeline and clears per step.
        self._claim_marks: Dict[str, List[Tuple[str, float]]] = {}

    # -- timeline hooks (serving tier clock) ---------------------------------

    def timeline_for(
        self, lineage: str, claim: str, t_submit: float
    ) -> Optional[RequestTimeline]:
        if not self.enabled:
            return None
        return RequestTimeline(lineage, claim, t_submit)

    def mark_requests(self, requests: Sequence, name: str) -> None:
        """Mark every request that carries a timeline, NOW on the tier
        clock (one clock read per call, not per request)."""
        if not self.enabled:
            return
        now = self._clock()
        for request in requests:
            timeline = getattr(request, "timeline", None)
            if timeline is not None:
                timeline.mark(name, now)

    def claim_mark(self, claim_ids: Sequence[str], name: str) -> None:
        """Router-side per-claim marks (h2d/dispatched/synced/
        committed): the router knows claims, not requests — the tier
        folds these into each request's timeline at completion."""
        if not self.enabled:
            return
        now = self._clock()
        for cid in claim_ids:
            self._claim_marks.setdefault(cid, []).append((name, now))

    def complete(self, request, now: float, outcome: str = "completed") -> None:
        """Finalize one request's timeline: fold the claim marks in,
        observe per-stage histograms, append the ``timeline.request``
        observation record."""
        timeline = getattr(request, "timeline", None)
        if not self.enabled or timeline is None:
            return
        timeline.extend(self._claim_marks.get(request.claim, ()))
        timeline.mark("completed", now)
        stages = timeline.stages()
        if outcome == "completed":
            for stage, seconds in stages.items():
                self._metrics.histogram(
                    REQUEST_STAGE_HISTOGRAM,
                    labels={"stage": stage, "claim": request.claim},
                ).observe(seconds)
        self.obslog.record(
            "timeline.request",
            lineage=timeline.lineage,
            claim=request.claim,
            outcome=outcome,
            e2e_s=timeline.e2e_s(),
            stages=stages,
        )

    def shed(self, lineage: str, claim: str, reason: str) -> None:
        """Admission-only timeline for a shed request: the verdict is
        in the journal (``serving.shed``); the observation record makes
        the lineage joinable in the same timeline tooling."""
        if not self.enabled:
            return
        self.obslog.record(
            "timeline.request",
            lineage=lineage,
            claim=claim,
            outcome="shed",
            reason=reason,
            e2e_s=0.0,
            stages={},
        )

    def end_step(self) -> None:
        """Clear the per-step claim marks (tier calls once per step,
        after completions are folded)."""
        if self._claim_marks:
            self._claim_marks.clear()

    # -- ledger hooks (real host clock) --------------------------------------

    def observe_dispatch(
        self, key, warmth: str, seconds: float, breakdown: Optional[dict] = None
    ) -> None:
        """Fold one measured dispatch into the ledger and append its
        ``cost.sample`` observation record (the offline-reconstruction
        source: same samples, same order, same alpha ⇒ same EMAs)."""
        if not self.enabled:
            return
        key_str = self.ledger.observe(key, warmth, seconds)
        self._metrics.counter(
            "cost_samples", labels={"warmth": warmth}
        ).add(1)
        self.obslog.record(
            "cost.sample",
            key=key_str,
            group=group_key(key),
            warmth=warmth,
            seconds=seconds,
            **({"breakdown": breakdown} if breakdown else {}),
        )

    # -- persistence + views -------------------------------------------------

    def save_ledger(self, path: str) -> None:
        from svoc_tpu.utils.artifacts import atomic_write_json

        atomic_write_json(path, self.ledger.to_dict())

    def restore_ledger(self, path: str) -> int:
        try:
            with open(path) as f:
                payload = json.load(f)
        except FileNotFoundError:  # svoclint: disable=SVOC014 — no sidecar on a fresh boot: the routine cold-start path, not a degrade
            return 0
        except (OSError, ValueError):
            # an unreadable/corrupt sidecar degrades to a cold ledger —
            # counted under the RecoveryManager's sidecar family
            self._metrics.counter(
                "cost_ledger_errors", labels={"op": "restore"}
            ).add(1)
            return 0
        return self.ledger.restore(payload)

    def snapshot(self) -> dict:
        """The ``costs`` section for ``ServingTier.snapshot()`` /
        ``/api/state`` / the console's ``costs`` command."""
        return {
            "enabled": self.enabled,
            "ledger": self.ledger.summary(),
            "entries": self.ledger.to_dict()["entries"],
            "observations": len(self.obslog),
        }


def resolve_cost_plane(
    *,
    enabled: Optional[bool] = None,
    clock: Optional[Callable[[], float]] = None,
    metrics: Optional[MetricsRegistry] = None,
    trace_path: Optional[str] = None,
) -> CostPlane:
    """Build the tier's cost plane with the routing resolved once
    (docstring above) — the ServingTier default."""
    return CostPlane(
        enabled=enabled, clock=clock, metrics=metrics, trace_path=trace_path
    )
