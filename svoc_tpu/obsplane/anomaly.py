"""Seeded anomaly detection over fleet counter deltas
(docs/OBSERVABILITY.md §fleet-plane).

The detector watches the fleet's DEGRADATION families — per-replica
``serving_shed``/``serving_dropped`` and the router's
``cluster_unavailable``/``cluster_quarantined`` — sampled once per
router step (:meth:`ClusterRouter.step_all` cadence).  Each
``(source, family)`` series keeps a bounded ring of per-step deltas
plus an EWMA mean/variance baseline; a step's delta breaches when its
z-score against the PRE-update baseline clears the threshold (with a
minimum-delta floor so a single stray shed after a silent warmup
cannot page), or when a static per-family guardrail is exceeded
outright.  Breaching deltas are deliberately NOT absorbed into the
baseline — an incident must not teach the detector that shedding is
normal — so a sustained degradation stays visible until traffic
recovers.

Determinism is the contract (SVOC011): every threshold is pinned at
construction in :class:`AnomalyConfig`, the detector reads nothing
from the environment or the wall clock, and its output is a pure
function of the sampled counter sequence — the same seed produces the
same alerts on every run, which `tests/test_fleet_obs.py` asserts.
Alerts surface as ``anomaly.detected`` observation records (never
journal events: the fleet plane is replay-invisible) and, on the
SUSTAINED edge (``sustain_steps`` consecutive breaches), auto-trigger
a profile capture + postmortem bundle via the fleet plane.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from typing import Dict, List, Mapping, Optional, Tuple

#: The default watched families: all four only ever count DEGRADED
#: outcomes, so a healthy fleet's series are identically zero and the
#: detector is structurally silent until something actually breaks.
DEFAULT_ANOMALY_FAMILIES = (
    "serving_shed",
    "serving_dropped",
    "cluster_unavailable",
    "cluster_quarantined",
)


@dataclasses.dataclass(frozen=True)
class AnomalyConfig:
    """Every detector threshold, pinned at construction (SVOC011 — a
    mid-run threshold flip would split one incident across regimes).

    - ``alpha`` — EWMA weight for the mean/variance baseline.
    - ``z_threshold`` — breach when ``(delta - mean) / sigma`` clears
      this (sigma floored at ``sigma_floor`` so an all-zero warmup
      cannot divide by zero).
    - ``min_delta`` — z-breaches additionally need at least this many
      new degraded events in the step.
    - ``warmup_steps`` — clean baseline samples required before the
      z-detector arms (guardrails are static and always armed).
    - ``sustain_steps`` — consecutive breaches before the alert is
      ``sustained`` (profile capture + bundle fire on that edge).
    - ``guardrails`` — per-family absolute per-step delta ceilings,
      breached regardless of the learned baseline.
    """

    families: Tuple[str, ...] = DEFAULT_ANOMALY_FAMILIES
    alpha: float = 0.3
    z_threshold: float = 4.0
    min_delta: float = 3.0
    sigma_floor: float = 0.5
    warmup_steps: int = 3
    sustain_steps: int = 2
    guardrails: Mapping[str, float] = dataclasses.field(default_factory=dict)
    ring_size: int = 256

    def __post_init__(self):
        if not 0.0 < self.alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        if self.sigma_floor <= 0.0:
            raise ValueError("sigma_floor must be positive")
        if self.sustain_steps < 1:
            raise ValueError("sustain_steps must be >= 1")


class _SeriesState:
    """One ``(source, family)`` series: last cumulative total, EWMA
    baseline, breach streak, and the bounded delta ring."""

    __slots__ = ("last_total", "mean", "var", "n", "streak", "ring")

    def __init__(self, ring_size: int):
        self.last_total: Optional[float] = None
        self.mean = 0.0
        self.var = 0.0
        self.n = 0
        self.streak = 0
        self.ring: deque = deque(maxlen=ring_size)


class AnomalyDetector:
    """Deterministic per-series delta detector (module docstring).
    Not internally locked: the fleet plane drives it from the router's
    single step thread."""

    def __init__(self, config: Optional[AnomalyConfig] = None):
        self.config = config or AnomalyConfig()
        self._series: Dict[Tuple[str, str], _SeriesState] = {}
        self._alerts_total = 0

    def on_step(
        self, step: int, totals: Dict[Tuple[str, str], float]
    ) -> List[dict]:
        """Feed one step's cumulative family totals; returns this
        step's breach alerts (``sustained=True`` exactly on the
        ``sustain_steps``-th consecutive breach — the trigger edge)."""
        alerts: List[dict] = []
        cfg = self.config
        for key in sorted(totals):
            source, family = key
            state = self._series.get(key)
            if state is None:
                state = self._series[key] = _SeriesState(cfg.ring_size)
            total = float(totals[key])
            if state.last_total is None:
                state.last_total = total
                continue
            delta = total - state.last_total
            state.last_total = total
            state.ring.append((step, delta))
            sigma = max(math.sqrt(max(state.var, 0.0)), cfg.sigma_floor)
            z = (delta - state.mean) / sigma
            trigger = None
            if (
                state.n >= cfg.warmup_steps
                and delta >= cfg.min_delta
                and z >= cfg.z_threshold
            ):
                trigger = "z"
            rail = cfg.guardrails.get(family)
            if rail is not None and delta > rail:
                trigger = trigger or "guardrail"
            if trigger is None:
                # Clean sample: absorb into the baseline.  Breaches are
                # NOT absorbed (docstring) — the incident must not
                # become the new normal.
                diff = delta - state.mean
                incr = cfg.alpha * diff
                state.mean += incr
                state.var = (1.0 - cfg.alpha) * (state.var + diff * incr)
                state.n += 1
                state.streak = 0
                continue
            state.streak += 1
            self._alerts_total += 1
            alerts.append(
                {
                    "source": source,
                    "family": family,
                    "step": step,
                    "delta": round(delta, 6),
                    "mean": round(state.mean, 6),
                    "sigma": round(sigma, 6),
                    "z": round(z, 4),
                    "trigger": trigger,
                    "streak": state.streak,
                    "sustained": state.streak == cfg.sustain_steps,
                }
            )
        return alerts

    def drop_source(self, source: str) -> None:
        """Forget a retired source's series (its registry is frozen —
        zero deltas forever would only pad the state dict)."""
        for key in [k for k in self._series if k[0] == source]:
            del self._series[key]

    def summary(self) -> dict:
        """The console/``/api/state`` view: series count, total breach
        alerts, and the currently-streaking series."""
        streaking = {
            f"{src}/{fam}": st.streak
            for (src, fam), st in sorted(self._series.items())
            if st.streak > 0
        }
        return {
            "series": len(self._series),
            "alerts_total": self._alerts_total,
            "streaking": streaking,
        }
