"""On-demand ``jax.profiler`` capture sessions (docs/OBSERVABILITY.md
§cost-attribution).

The span histograms say WHICH stage is slow; when the answer is "the
device" you need the XLA view, and by the time a human starts XProf the
incident is over.  :class:`ProfileCapture` makes the capture a
first-class, bounded operation:

- **manual** — console ``profile start/stop``, ``GET /api/profile`` —
  starts a capture into ``<out_dir>/profile-<n>`` (a monotone index,
  NOT a timestamp: the capture path is journaled and wall clock never
  enters journal data — SVOC008);
- **automatic** — the :class:`~svoc_tpu.utils.postmortem.
  PostmortemMonitor` calls :meth:`maybe_capture` on SLO burn /
  breaker-open, rate-limited (default 120 s between auto captures) so
  a flapping breaker cannot fill the disk with traces;
- **bounded** — every capture arms a daemon timer that force-stops it
  after ``max_duration_s`` (default 30 s): an operator who starts a
  capture and gets paged away must not leave the profiler running for
  a week.

Completion journals one ``profile.captured`` event (trigger + path —
an incident-path event like ``postmortem.bundle``; it never fires in
seeded replays).  When ``jax.profiler`` is unavailable or a capture
fails, the plane degrades LOUDLY-BUT-OPEN: the error lands in
``profile_errors_total{stage=}`` and the returned status, and serving
is never taken down over telemetry.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry


class ProfileCapture:
    """One process-wide profiler session manager (jax.profiler allows
    a single active trace, so concurrency is a feature, not a limit)."""

    def __init__(
        self,
        out_dir: str = "profiles",
        *,
        journal=None,
        metrics: Optional[MetricsRegistry] = None,
        max_duration_s: float = 30.0,
        auto_min_interval_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.out_dir = out_dir
        self._journal = journal
        self._metrics = metrics or _default_registry
        self.max_duration_s = max_duration_s
        self.auto_min_interval_s = auto_min_interval_s
        self._clock = clock
        self._lock = threading.Lock()
        self._active: Optional[dict] = None
        self._timer: Optional[threading.Timer] = None
        self._captures = 0
        self._last_auto: Optional[float] = None

    # -- availability --------------------------------------------------------

    @staticmethod
    def available() -> bool:
        try:
            import jax.profiler  # noqa: F401

            return True
        except Exception:
            return False

    def _emit(self, event_type: str, **data) -> None:
        j = self._journal
        if j is None:
            from svoc_tpu.utils.events import journal as j
        j.emit(event_type, **data)

    def _error(self, stage: str, exc: Exception) -> dict:
        self._metrics.counter(
            "profile_errors", labels={"stage": stage}
        ).add(1)
        return {
            "status": "error",
            "stage": stage,
            "error": f"{type(exc).__name__}: {exc}",
        }

    # -- capture lifecycle ---------------------------------------------------

    def start(
        self,
        trigger: str = "manual",
        duration_s: Optional[float] = None,
    ) -> dict:
        """Start a capture.  Returns a status dict, never raises:
        ``started`` / ``already_running`` / ``unavailable`` /
        ``error``."""
        duration = min(
            self.max_duration_s,
            duration_s if duration_s is not None else self.max_duration_s,
        )
        with self._lock:
            if self._active is not None:
                return {"status": "already_running", **self._active}
            self._captures += 1
            index = self._captures
        log_dir = os.path.join(self.out_dir, f"profile-{index:04d}")
        try:
            import jax.profiler
        except Exception as e:
            return self._error("import", e)
        try:
            os.makedirs(log_dir, exist_ok=True)
            jax.profiler.start_trace(log_dir)
        except Exception as e:
            return self._error("start", e)
        info = {"path": log_dir, "trigger": trigger, "index": index}
        timer = threading.Timer(duration, self._auto_stop, args=(index,))
        timer.daemon = True
        with self._lock:
            self._active = info
            self._timer = timer
        timer.start()
        self._metrics.counter(
            "profile_captures", labels={"trigger": trigger}
        ).add(1)
        return {"status": "started", "duration_s": duration, **info}

    def stop(self) -> dict:
        """Stop the active capture and journal ``profile.captured``."""
        with self._lock:
            info = self._active
            timer = self._timer
            self._active = None
            self._timer = None
        if info is None:
            return {"status": "idle"}
        if timer is not None:
            timer.cancel()
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception as e:
            return self._error("stop", e)
        # Outside the lock (the journal lock is a leaf — SVOC010), and
        # the data carries no clock readings (SVOC008): the capture's
        # own timing lives in the profile artifact, not the journal.
        self._emit(
            "profile.captured",
            trigger=info["trigger"],
            path=info["path"],
        )
        return {"status": "captured", **info}

    def _auto_stop(self, index: int) -> None:
        """Duration-bound force stop; a no-op when the operator
        already stopped (or a newer capture started)."""
        with self._lock:
            if self._active is None or self._active["index"] != index:
                return
        self.stop()

    def maybe_capture(self, trigger: str) -> Optional[dict]:
        """The automatic path (postmortem monitor): start a capture
        unless one is running or the auto rate limit holds.  Suppressed
        calls are counted, not raised."""
        now = self._clock()
        with self._lock:
            if self._active is not None:
                return None
            if (
                self._last_auto is not None
                and now - self._last_auto < self.auto_min_interval_s
            ):
                self._metrics.counter(
                    "profile_suppressed", labels={"reason": "rate_limit"}
                ).add(1)
                return None
            self._last_auto = now
        return self.start(trigger=trigger)

    def status(self) -> dict:
        with self._lock:
            active = dict(self._active) if self._active else None
            captures = self._captures
        return {
            "available": self.available(),
            "active": active,
            "captures": captures,
            "max_duration_s": self.max_duration_s,
        }

    def attach(self, console) -> None:
        """Expose through a CommandConsole: the ``profile`` command and
        ``GET /api/profile`` read ``console.profiler``."""
        console.profiler = self
