"""The claim router: fair micro-batches through one consensus dispatch.

The dynamic half of the fabric (registry in
:mod:`svoc_tpu.fabric.registry`): each :meth:`ClaimRouter.step`

1. **selects** up to ``max_claims_per_batch`` claims by weighted
   round-robin (a claim of weight *w* holds *w* slots in the rotation;
   selection is deterministic, so seeded fabric runs replay
   byte-identically — ``make fabric-smoke``),
2. **fetches** each selected claim through its own
   :meth:`~svoc_tpu.apps.session.Session.fetch` (window → sentiment →
   fleet → counted quarantine verdict, lineage
   ``blk<scope>-<claim>-<n>``),
3. **batches** the fetched fleet blocks into claim cubes — grouped by
   ``(n_oracles, dimension, consensus config)``, padded to a
   pow2-bucketed claim count
   (:func:`svoc_tpu.consensus.batch.pad_claim_cube`) — and runs ONE
   gated consensus dispatch per group
   (:func:`svoc_tpu.consensus.batch.claims_consensus_gated`), giving
   every claim its per-claim essence, ``interval_valid`` and
   reliability mask,
4. **commits** each claim resiliently (retry + resume + breaker +
   supervisor — the claim's own instances), folds the supervisor, and
5. **accounts** the per-claim SLO counters
   (``claim_commit_cycles{claim=}`` …) that
   :func:`svoc_tpu.utils.slo.claim_slos` evaluates.

One claim's Byzantine offender, dead chain, or burning error budget
stays in that claim's fleet, breaker, and SLO — sibling claims share
only the accelerator dispatch (the isolation `make fabric-smoke`
certifies).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from svoc_tpu.apps.session import DegenerateBlockError, EmptyStoreError
from svoc_tpu.consensus.batch import (
    _PAD_VALUE,
    claims_consensus_gated,
    claims_consensus_sanitized,
    pad_claim_cube,
    pow2_bucket,
)
from svoc_tpu.compile.universe import dispatch_key
from svoc_tpu.consensus.dispatch import (
    resolve_claim_mesh,
    resolve_consensus_impl,
    resolve_warmup_mode,
)
from svoc_tpu.fabric.registry import ClaimRegistry, ClaimState
from svoc_tpu.io.chain import ChainCommitError
from svoc_tpu.resilience.breaker import CircuitOpenError
from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry
from svoc_tpu.utils.metrics import stage_span


_DONATION_WARNING_FILTERED = False
_DONATION_WARNING_LOCK = threading.Lock()


def _filter_donation_warning_once() -> None:
    """Install the donated-buffers warning filter AT MOST ONCE per
    process (an opt-in of ``device_resident=True``): donation is a
    best-effort hint and XLA warns per compiled shape on backends whose
    output layouts can't alias the cube (CPU notably) — expected here,
    and the counterfactual is log spam in every seeded smoke run.  The
    once-guard keeps repeated router constructions from growing the
    warnings filter list unboundedly; the repo's only donating call
    sites are the consensus/batch.py twins this router drives."""
    global _DONATION_WARNING_FILTERED
    with _DONATION_WARNING_LOCK:
        if _DONATION_WARNING_FILTERED:
            return
        _DONATION_WARNING_FILTERED = True
    import warnings

    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable"
    )


def resolve_journal(journal):
    """Re-export of :func:`svoc_tpu.utils.events.resolve_journal` (its
    home since PR 14 — jax-free durability consumers resolve journals
    without importing the fabric stack; fabric callers keep this name)."""
    from svoc_tpu.utils.events import resolve_journal as _resolve

    return _resolve(journal)


class _PendingGroup:
    """One in-flight claim-cube dispatch: the device outputs plus the
    per-claim context (lineage, admission source) captured at dispatch
    time, so the pipelined write-back one cycle later journals against
    the RIGHT blocks even after the sessions fetched new ones."""

    __slots__ = (
        "members",
        "cfg",
        "out",
        "oks",
        "bucket",
        "lineages",
        "warmth_key",
        "warmth",
        "h2d_s",
        "dispatch_s",
    )

    def __init__(
        self,
        members,
        cfg,
        out,
        oks,
        bucket,
        lineages,
        warmth_key=None,
        warmth=None,
        h2d_s=0.0,
        dispatch_s=0.0,
    ):
        self.members = members
        self.cfg = cfg
        self.out = out
        self.oks = oks
        self.bucket = bucket
        self.lineages = lineages
        # Cost-plane context captured at dispatch time (the write-back
        # may land a pipelined cycle later): the CompileKey + warmth
        # this dispatch was accounted under, and its measured
        # perf_counter windows (real host seconds — never the tier's
        # virtual clock, never a fingerprint).
        self.warmth_key = warmth_key
        self.warmth = warmth
        self.h2d_s = h2d_s
        self.dispatch_s = dispatch_s


class _GroupStaging:
    """Reusable host staging for one (n_oracles, dim, cfg) dispatch
    group (``ClaimRouter(device_resident=True)``, docs/PARALLELISM.md
    §host-overhead): the claim cube, admission masks, and activity mask
    live in pre-allocated arrays updated IN PLACE each cycle, so the
    steady state allocates nothing on the host — the old path rebuilt
    ``np.stack`` + ``pad_claim_cube`` concatenations every cycle.

    Padding rows are written ONCE at allocation (neutral fill,
    all-admitted, inactive) and re-established only for rows a
    shrinking micro-batch strands (``active`` tracks the high-water
    mark), exactly matching :func:`pad_claim_cube`'s per-cycle output
    bit-for-bit — the replay-fingerprint contract of the resident path.
    """

    __slots__ = ("values", "ok", "mask", "active")

    def __init__(self, bucket: int, n: int, m: int):
        self.values = np.full((bucket, n, m), _PAD_VALUE, dtype=np.float32)
        self.ok = np.ones((bucket, n), dtype=bool)
        self.mask = np.zeros(bucket, dtype=bool)
        self.active = 0

    def load(self, blocks, oks) -> None:
        """Write this cycle's blocks into rows ``[0, C)`` (the float64→
        float32 cast in ``np.copyto`` is the same rounding
        ``np.asarray(..., float32)`` applied on the unstaged path) and
        restore pad state on rows the previous, larger batch used."""
        c = len(blocks)
        for i, block in enumerate(blocks):
            np.copyto(self.values[i], block, casting="same_kind")
            np.copyto(self.ok[i], oks[i])
        if self.active > c:
            self.values[c : self.active] = _PAD_VALUE
            self.ok[c : self.active] = True
        self.mask[:c] = True
        self.mask[c:] = False
        self.active = c


class ClaimRouter:
    """Multiplexes fetch → vectorize → consensus → commit across the
    registry's claims.  ``step()`` is the single-threaded scheduling
    loop (the fabric's controller thread); registry mutation and
    snapshot reads are safe concurrently."""

    def __init__(
        self,
        registry: ClaimRegistry,
        *,
        max_claims_per_batch: int = 8,
        metrics: Optional[MetricsRegistry] = None,
        journal=None,
        sanitized_dispatch: bool = False,
        consensus_impl: Optional[str] = None,
        mesh=None,
        pipelined: bool = False,
        device_resident: bool = False,
        warmup_mode: Optional[str] = None,
    ):
        if max_claims_per_batch < 1:
            raise ValueError("max_claims_per_batch must be >= 1")
        self.registry = registry
        self.max_claims_per_batch = max_claims_per_batch
        self._metrics = metrics or _default_registry
        self._journal = journal
        #: Consensus execution strategy for every claim-cube dispatch
        #: this router issues (``"xla"`` | ``"pallas"``), resolved ONCE
        #: at construction (env > PERF_DECISIONS.json > xla) — the impl
        #: choice is part of a seeded replay's config (docs/FABRIC.md
        #: §replay), so it must not drift mid-run if the committed
        #: record changes under a live process.  Both impls are
        #: parity-tested lossless (``make pallas-parity``); an
        #: unhonorable pallas route falls back to XLA with a counted
        #: ``consensus_pallas_fallback{reason=}``.
        self.consensus_impl = (
            consensus_impl
            if consensus_impl is not None
            else resolve_consensus_impl()
        )
        #: The 2-D (claim × oracle) dispatch mesh, resolved ONCE at
        #: construction like the impl above (``SVOC_MESH`` env > the
        #: committed PERF_DECISIONS.json ``claim_mesh`` record > no
        #: mesh) — the mesh is part of a seeded replay's config
        #: (docs/FABRIC.md §mesh) and is surfaced in
        #: ``MultiSession.snapshot()`` / ``ServingTier.snapshot()`` /
        #: ``/api/state``.  Accepts a ``"<claims>x<oracles>"`` spec,
        #: a prebuilt :class:`jax.sharding.Mesh`, ``"off"`` (explicitly
        #: unsharded), or None (resolve).  The sharded path is
        #: bitwise-exact vs the single-device cube
        #: (docs/PARALLELISM.md §sharded-claims), so pinning a mesh
        #: does not change seeded-smoke fingerprints.
        self._shard = self._build_shard(mesh)
        self.mesh_spec = self._shard.spec_str if self._shard else None
        #: Double-buffered dispatch (docs/PARALLELISM.md
        #: §sharded-claims, pipelining): the claim-cube consensus for
        #: cycle k-1 executes on device while the host fetches (and
        #: commits) cycle k — its write-back (state.last_consensus,
        #: ``fabric.consensus`` events) lands one cycle later, drained
        #: by :meth:`flush`.  Pull-mode only: request-driven feeds need
        #: same-cycle accounting.  Off by default — the PR 6 cycle (and
        #: its smoke fingerprints) is byte-identical when off.
        self.pipelined = pipelined
        self._inflight: List[_PendingGroup] = []
        #: Zero-allocation steady-state dispatch (docs/PARALLELISM.md
        #: §host-overhead): each (N, M, cfg) group's staging cube lives
        #: in a reusable pinned host buffer updated in place, the H2D
        #: upload is an explicit copy (the staging buffer is mutated
        #: next cycle, so it must never alias a live device array), and
        #: the unsharded XLA dispatch DONATES the uploaded cube so the
        #: allocator recycles its device memory for the outputs
        #: (SVOC004 discipline: the upload is rebound fresh every cycle
        #: and never re-read).  Bit-identical to the unstaged path —
        #: ``make hotpath-smoke`` pins fingerprint identity — so unlike
        #: ``pipelined`` it is NOT its own fingerprint family; off by
        #: default purely so the A/B in ``bench_hotpath.py`` keeps an
        #: honest baseline.
        self.device_resident = bool(device_resident)
        if self.device_resident:
            _filter_donation_warning_once()
        self._staging: Dict[Any, _GroupStaging] = {}
        #: Donation rides only the unsharded XLA dispatch: the sharded
        #: program manages its own buffers, and the pallas route feeds
        #: the cube to two programs (see claims_consensus_sanitized).
        self._donate = self.device_resident and self._shard is None
        #: Fuse gate + consensus into ONE traced program per micro-batch
        #: (:func:`svoc_tpu.consensus.batch.claims_consensus_sanitized`)
        #: instead of reusing the host gate's per-claim verdicts.  The
        #: serving tier turns this on — admission masks come out of the
        #: same dispatch the consensus runs in, no host round-trip
        #: between them.  Off by default: the pull-mode fabric keeps its
        #: PR 6 behavior (and its seeded smoke fingerprints) unchanged.
        self.sanitized_dispatch = sanitized_dispatch
        #: Compile-plane warmup routing, resolved ONCE at construction
        #: like impl/mesh above (``SVOC_WARMUP`` env > the committed
        #: PERF_DECISIONS.json ``warmup_mode`` record > ``"none"``;
        #: docs/PARALLELISM.md §compile-plane).  NOT a fingerprint
        #: family — warmup never journals and never changes numerics
        #: (``make coldstart-smoke``) — but still pinned: cold/warm
        #: dispatch accounting must mean one thing per process.
        self.warmup_mode = (
            warmup_mode if warmup_mode is not None else resolve_warmup_mode()
        )
        #: The attached :class:`~svoc_tpu.compile.prewarm.PrewarmWorker`
        #: (None until :meth:`attach_prewarmer` /
        #: ``MultiSession.start_prewarm``) — lets the warmth accounting
        #: below distinguish a first dispatch the prewarmer already
        #: compiled (``prewarmed``) from a genuinely cold one.
        self.prewarmer = None
        #: The serving tier's cost-attribution plane
        #: (docs/OBSERVABILITY.md §cost-attribution), attached by
        #: ``ServingTier.__init__``; None (or disabled) keeps every
        #: dispatch-cost hook a no-op — the pull-mode fabric and its
        #: seeded smoke fingerprints never see it.
        self.cost_plane = None
        #: Compile keys this router has dispatched at least once — the
        #: cold/warm boundary of ``consensus_dispatch{warmth=}``.
        #: Router-thread-only (the scheduling loop is single-threaded).
        self._warmth_seen: set = set()
        #: (bucket, N, M, cfg) -> CompileKey: for a construction-pinned
        #: router the key is a pure function of the dispatched shape,
        #: so the steady state reuses one frozen dataclass per group
        #: instead of re-validating/re-hashing it every cycle (the
        #: §host-overhead discipline).
        self._warmth_keys: Dict[Any, Any] = {}
        self._lock = threading.Lock()
        #: weighted rotation: claim ids, each appearing ``weight``
        #: times.  Rebuilt lazily when the registry's membership
        #: changes; rotation POSITION survives rebuilds (fairness
        #: across adds/removes).
        self._rotation: deque = deque()
        self._rotation_members: Tuple[Tuple[str, int], ...] = ()
        self.steps = 0
        #: End-of-cycle hooks, called with the step report AFTER every
        #: claim was committed/accounted — the recovery manager's
        #: snapshot cadence rides here (docs/RESILIENCE.md
        #: §durability), and the crash harness's seeded kill points
        #: too.  Hooks run in registration order on the router thread;
        #: an exception is counted (``fabric_hook_errors``) and never
        #: kills the loop.
        self.post_step_hooks: List[Any] = []

    def _resolve_journal(self):
        return resolve_journal(self._journal)

    def _build_shard(self, mesh):
        """Resolve + pin the claim mesh (constructor-only).  Returns a
        :class:`~svoc_tpu.parallel.claim_shard.ClaimShardDispatcher`
        or None for the single-device path."""
        from jax.sharding import Mesh

        from svoc_tpu.parallel.claim_shard import ClaimShardDispatcher
        from svoc_tpu.parallel.mesh import claim_mesh

        if isinstance(mesh, ClaimShardDispatcher):
            return mesh
        if not isinstance(mesh, Mesh):
            spec = mesh if mesh is not None else resolve_claim_mesh()
            mesh = claim_mesh(spec)
            if mesh is None:
                return None
        return ClaimShardDispatcher(
            mesh,
            consensus_impl=self.consensus_impl,
            metrics=self._metrics,
        )

    # -- scheduling ---------------------------------------------------------

    def _refresh_rotation_locked(self, states: List[ClaimState]) -> None:
        members = tuple(
            (s.spec.claim_id, s.spec.weight)
            for s in sorted(states, key=lambda s: s.index)
        )
        if members == self._rotation_members:
            return
        # Preserve relative order of surviving ids; new claims join at
        # the rotation tail in registration order.
        old_order = [cid for cid in self._rotation]
        alive = {cid for cid, _w in members}
        seen = set()
        new_rotation: List[str] = []
        for cid in old_order:
            if cid in alive and cid not in seen:
                seen.add(cid)
                new_rotation.append(cid)
        for cid, _w in members:
            if cid not in seen:
                seen.add(cid)
                new_rotation.append(cid)
        weights = dict(members)
        expanded: List[str] = []
        for cid in new_rotation:
            expanded.extend([cid] * weights[cid])
        self._rotation = deque(expanded)  # svoc: volatile(derived from registry membership + weights; rebuilt on the next select() after any membership change)
        self._rotation_members = members  # svoc: volatile(cache key for the rotation rebuild; derived like _rotation)

    def select(self) -> List[ClaimState]:
        """The next micro-batch: up to ``max_claims_per_batch`` DISTINCT
        unpaused claims in weighted-rotation order.  Deterministic —
        the replay witness covers scheduling, not just math."""
        states = self.registry.states()
        by_id = {s.spec.claim_id: s for s in states}
        with self._lock:
            self._refresh_rotation_locked(states)
            if not self._rotation:
                return []
            selected: List[ClaimState] = []
            picked = set()
            # One full rotation scan at most: claims beyond the batch
            # cap (or paused) keep their slots for the next step.
            for _ in range(len(self._rotation)):
                cid = self._rotation[0]
                self._rotation.rotate(-1)
                if cid in picked:
                    continue
                state = by_id.get(cid)
                if state is None or state.paused:
                    continue
                picked.add(cid)
                selected.append(state)
                if len(selected) >= self.max_claims_per_batch:
                    break
            return selected

    # -- the multiplexed cycle ----------------------------------------------

    def step(
        self, feeds: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """One fabric cycle over the next micro-batch.  Never raises on
        a per-claim failure (an empty store or open breaker in one
        claim must not starve its siblings); per-claim errors land in
        the report and the claim's own counters.

        ``feeds`` switches the cycle to **request-driven** feeding
        (docs/SERVING.md): a ``{claim_id: [K, M] sentiment vectors}``
        map from the serving batcher.  Only fed claims are served this
        cycle (the batcher already decided who has work — an idle claim
        is not an error and costs nothing), each through
        ``Session.fetch(window=...)``, preserving lineage, gate
        verdicts, and the per-claim isolation contract: a claim whose
        feed is malformed (wrong dimension, raising tamper) lands in
        ITS ``fabric_claim_errors{claim=,stage="fetch"}`` and its
        siblings are still served.  ``feeds=None`` is the PR 6
        pull-mode cycle, byte-for-byte unchanged."""
        if self.pipelined and feeds is not None:
            raise ValueError(
                "pipelined dispatch is pull-mode only: request-driven "
                "feeds need same-cycle consensus accounting "
                "(docs/PARALLELISM.md §sharded-claims)"
            )
        report = self._step_inner(feeds=feeds)
        for hook in list(self.post_step_hooks):
            try:
                hook(report)
            except Exception:  # noqa: BLE001 — a hook must not kill serving
                self._metrics.counter("fabric_hook_errors").add(1)
        return report

    def flush(self) -> int:
        """Drain the pipelined in-flight consensus write-backs (the
        pipeline's one-cycle tail); returns how many groups were
        finished.  A no-op when unpipelined or already drained."""
        pending, self._inflight = self._inflight, []  # svoc: volatile(pipelined device buffers are process-local; a crashed cycle's groups are re-selected from the registry next step)
        if pending:
            with stage_span("fabric_consensus"):
                for group in pending:
                    self._finish_group(group)
        return len(pending)

    def _step_inner(
        self, feeds: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        self.steps += 1
        report: Dict[str, Any] = {
            "step": self.steps,
            "served": [],
            "skipped": {},
            "claims": {},
        }
        if feeds is None:
            selected = self.select()
        else:
            # Registration order (deterministic), fed + unpaused claims
            # only.  Unknown claim ids in the feed are a caller bug —
            # surfaced in the report, never fatal to the batch.
            selected = []
            known = {s.spec.claim_id: s for s in self.registry.states()}
            for cid in feeds:
                state = known.get(cid)
                if state is None:
                    report["skipped"][cid] = "unknown_claim"
                elif state.paused:
                    report["skipped"][cid] = "paused"
                else:
                    selected.append(state)
            selected.sort(key=lambda s: s.index)
        if not selected:
            return report

        # ---- fetch every selected claim (its own lineage + verdict) ----
        fetched: List[ClaimState] = []
        for state in selected:
            spec = state.spec
            tamper = None
            if spec.tamper is not None:
                cycle = state.cycles
                tamper = lambda block, _t=spec.tamper, _c=cycle: _t(_c, block)
            try:
                state.session.fetch(
                    tamper=tamper,
                    window=None if feeds is None else feeds[spec.claim_id],
                )
            except EmptyStoreError:  # svoclint: disable=SVOC014 -- deliberate: an empty store is the routine pre-data wait, surfaced per claim in the step report's `skipped` map; anomalies take the counted fabric_claim_errors lane below
                report["skipped"][spec.claim_id] = "empty_store"
                continue
            except Exception as e:  # noqa: BLE001 — isolation contract
                # ANY per-claim fetch failure (a raising tamper hook, a
                # broken vectorizer, a torn store) skips THIS claim,
                # never the batch — but unlike the routine empty-store
                # wait it is an anomaly, so it surfaces in its own
                # counter instead of blending into claim accounting.
                report["skipped"][spec.claim_id] = (
                    f"fetch_error:{type(e).__name__}"
                )
                self._metrics.counter(
                    "fabric_claim_errors",
                    labels={"claim": spec.claim_id, "stage": "fetch"},
                ).add(1)
                continue
            fetched.append(state)
        if not fetched:
            return report

        # ---- claim-cube consensus: one dispatch per (shape, config) ----
        groups: Dict[Any, List[ClaimState]] = {}
        for state in fetched:
            spec = state.spec
            key = (spec.n_oracles, spec.dimension, spec.consensus_config())
            groups.setdefault(key, []).append(state)
        if self.pipelined:
            # Double-buffered dispatch: enqueue cycle k's cubes (async,
            # no host sync), THEN resolve cycle k-1's — its collectives
            # executed on device while this cycle's blocks were being
            # fetched on the host.  The commit below still commits
            # cycle k's blocks (the chain path never consumed the cube
            # outputs); only state.last_consensus and the
            # ``fabric.consensus`` events trail one cycle, against the
            # lineages captured at dispatch.
            dispatched = [
                self._dispatch_group(members, cfg)
                for (_n, _m, cfg), members in groups.items()
            ]
            pending, self._inflight = self._inflight, dispatched
            with stage_span("fabric_consensus"):
                for group in pending:
                    self._finish_group(group)
        else:
            with stage_span("fabric_consensus"):
                for (_n, _m, cfg), members in groups.items():
                    self._finish_group(self._dispatch_group(members, cfg))

        # ---- commit + supervise + SLO, claim by claim ----
        plane = self.cost_plane
        track = plane is not None and plane.enabled
        for state in fetched:
            self._commit_claim(state)
            if track:
                plane.claim_mark([state.spec.claim_id], "committed")
            state.cycles += 1
            report["served"].append(state.spec.claim_id)
            report["claims"][state.spec.claim_id] = {
                "consensus": state.last_consensus,
                "commit": state.last_commit,
            }
        return report

    def _dispatch_group(
        self, members: List[ClaimState], cfg
    ) -> _PendingGroup:
        """Collect one shape/config group's blocks and issue ONE fused
        gated consensus dispatch — device outputs only, no host sync
        (the pipelined mode's overlap window lives between this and
        :meth:`_finish_group`).  Routes through the pinned claim mesh
        when one is configured; the sharded program is bitwise-exact
        vs the single-device one (docs/PARALLELISM.md §sharded-claims),
        so the route never changes results or fingerprints."""
        lineages = []
        blocks = []
        oks = []
        with stage_span("fabric_stage"):
            for state in members:
                session = state.session
                with session.lock:
                    predictions = session.predictions
                    quarantine = session.last_quarantine
                    lineages.append(session.last_lineage)
                blocks.append(predictions)
                oks.append(
                    np.asarray(quarantine.ok, dtype=bool)
                    if quarantine is not None
                    else np.ones(predictions.shape[0], dtype=bool)
                )
            multiple = self._shard.claim_size if self._shard else 1
            if self.device_resident:
                # In-place staging: zero fresh host allocation in the
                # steady state (the stack/pad path below rebuilds three
                # arrays per cycle).  Accounting keeps the per-claim
                # ``oks`` arrays — only the dispatch inputs are staged,
                # so nothing downstream aliases the reused buffers.
                staging = self._group_staging(blocks, cfg, multiple)
                staging.load(blocks, oks)
                values, ok, claim_mask = (
                    staging.values,
                    staging.ok,
                    staging.mask,
                )
            else:
                values, ok, claim_mask = pad_claim_cube(
                    np.stack(
                        [np.asarray(b, dtype=np.float32) for b in blocks]
                    ),
                    np.stack(oks),
                    multiple_of=multiple,
                )
        # The journaled batch_bucket is the MESH-INDEPENDENT pow2
        # bucket, not values.shape[0]: mesh padding (multiple_of above)
        # can grow the dispatched cube (e.g. 2 claims on a 4-wide or
        # 3-wide claim axis), and the fabric.consensus event data must
        # not depend on where the cube computed — the meshed==unmeshed
        # fingerprint identity (make shard-smoke) is a contract.
        journal_bucket = pow2_bucket(len(members))
        warmth_key, warmth = self._account_warmth(values, cfg)
        # Cost plane (docs/OBSERVABILITY.md §cost-attribution): real
        # perf_counter windows around the H2D + dispatch sections feed
        # the shape-keyed ledger; per-claim timeline marks ride the
        # plane's own (tier) clock.  `track` false keeps the hot path
        # byte-identical to the plane-less router.
        plane = self.cost_plane
        track = plane is not None and plane.enabled
        claim_ids = [s.spec.claim_id for s in members] if track else None
        t_start = time.perf_counter() if track else 0.0
        h2d_s = 0.0
        if self.sanitized_dispatch:
            # Gate + consensus in ONE traced program: the in-graph
            # quarantine twin recomputes the admission masks (identical
            # to the host gate's — equivalence-tested in
            # tests/test_fabric.py) and the gated kernel consumes them
            # without a host round-trip.  Bounds come from the group's
            # consensus config, exactly like the host gate's
            # SanitizeConfig.for_consensus.
            from svoc_tpu.robustness.sanitize import SanitizeConfig

            bounds = SanitizeConfig.for_consensus(cfg.constrained)
            if self._shard is not None:
                values_in, _ok_in, mask_in = self._shard_inputs(
                    values, ok, claim_mask
                )
                out, ok_traced = self._shard.dispatch_sanitized(
                    values_in, mask_in, cfg, bounds.lo, bounds.hi
                )
            else:
                with stage_span("fabric_h2d"):
                    values_dev = self._h2d(values)
                    mask_dev = self._h2d(claim_mask)
                if track:
                    h2d_s = time.perf_counter() - t_start
                    plane.claim_mark(claim_ids, "h2d")
                with stage_span("fabric_dispatch"):
                    out, ok_traced = claims_consensus_sanitized(
                        values_dev,
                        mask_dev,
                        cfg,
                        bounds.lo,
                        bounds.hi,
                        consensus_impl=self.consensus_impl,
                        metrics=self._metrics,
                        donate=self._donate,
                    )
            # The traced masks become the accounting source (fetched in
            # _finish_group along with the outputs).
            oks = ok_traced
        elif self._shard is not None:
            values_in, ok_in, mask_in = self._shard_inputs(
                values, ok, claim_mask
            )
            out = self._shard.dispatch_gated(values_in, ok_in, mask_in, cfg)
        else:
            with stage_span("fabric_h2d"):
                values_dev = self._h2d(values)
                ok_dev = self._h2d(ok)
                mask_dev = self._h2d(claim_mask)
            if track:
                h2d_s = time.perf_counter() - t_start
                plane.claim_mark(claim_ids, "h2d")
            with stage_span("fabric_dispatch"):
                out = claims_consensus_gated(
                    values_dev,
                    ok_dev,
                    mask_dev,
                    cfg,
                    consensus_impl=self.consensus_impl,
                    metrics=self._metrics,
                    donate=self._donate,
                )
        dispatch_s = 0.0
        if track:
            dispatch_s = max(0.0, time.perf_counter() - t_start - h2d_s)
            plane.claim_mark(claim_ids, "dispatched")
        # Seen only after the dispatch call returned: a raising
        # dispatch compiled nothing, and its retry must count cold.
        self._warmth_seen.add(warmth_key)
        return _PendingGroup(
            members,
            cfg,
            out,
            oks,
            journal_bucket,
            lineages,
            warmth_key=warmth_key if track else None,
            warmth=warmth,
            h2d_s=h2d_s,
            dispatch_s=dispatch_s,
        )

    def attach_prewarmer(self, worker) -> None:
        """Wire a :class:`~svoc_tpu.compile.prewarm.PrewarmWorker` into
        the warmth accounting (and the serving tier's cold-shape defer
        gate, which reads ``router.prewarmer``)."""
        self.prewarmer = worker

    def _account_warmth(self, values, cfg):
        """Count this dispatch cold / prewarmed / warm
        (``consensus_dispatch{warmth=}``, docs/PARALLELISM.md
        §compile-plane).  ``cold`` = the first time THIS process
        dispatches the compile key and no prewarmer compiled it ahead —
        the dispatch below pays trace+compile inline (or a
        persistent-cache retrieval, still the slow lane);
        ``prewarmed`` = first dispatch of a key the attached worker
        already warmed; ``warm`` = every repeat.  Metrics only — the
        journal never sees warmth, so seeded replay fingerprints are
        independent of compile state (the coldstart-smoke gate).

        Returns ``(key, warmth)``; the CALLER marks the key seen after
        the dispatch call succeeds (a raising dispatch compiled nothing
        — the retry must count cold again, not read as warm).  The
        warmth string travels with the dispatch so the cost plane's
        ledger folds the measured seconds into the regime this counter
        accounted, even when the write-back lands a pipelined cycle
        later (by which time the key reads warm)."""
        shape_key = (
            int(values.shape[0]),
            int(values.shape[1]),
            int(values.shape[2]),
            cfg,
        )
        key = self._warmth_keys.get(shape_key)
        if key is None:
            key = dispatch_key(
                sanitized=self.sanitized_dispatch,
                sharded=self._shard is not None,
                bucket=shape_key[0],
                n_oracles=shape_key[1],
                dimension=shape_key[2],
                cfg=cfg,
                donate=self._donate,
                impl=self.consensus_impl,
                mesh=self.mesh_spec,
            )
            self._warmth_keys[shape_key] = key
        if key in self._warmth_seen:
            warmth = "warm"
        elif self.prewarmer is not None and self.prewarmer.is_warm(key):
            warmth = "prewarmed"
        else:
            warmth = "cold"
        self._metrics.counter(
            "consensus_dispatch", labels={"warmth": warmth}
        ).add(1)
        return key, warmth

    def _group_staging(self, blocks, cfg, multiple: int) -> _GroupStaging:
        """The (shape, config) group's reusable staging buffers, sized
        to this cycle's pow2 bucket.  Reallocation happens only when
        the bucket crosses a power of two (or the mesh multiple) — the
        steady state reuses one allocation per group for the process
        lifetime."""
        n, m = np.shape(blocks[0])
        bucket = pow2_bucket(len(blocks), multiple_of=multiple)
        key = (n, m, cfg)
        staging = self._staging.get(key)
        if staging is None or staging.values.shape[0] != bucket:
            staging = _GroupStaging(bucket, n, m)
            self._staging[key] = staging
        return staging

    def _h2d(self, array):
        """Host→device upload for one dispatch input.  Device-resident
        staging buffers are mutated in place next cycle, and
        ``jnp.asarray`` ZERO-COPIES writeable host memory on the CPU
        backend — so the resident path copies explicitly (the copy IS
        the upload; the donated dispatch then recycles its device
        memory).  The unstaged path keeps its historical zero-copy
        ``asarray`` of per-cycle fresh arrays."""
        if self.device_resident:
            return jnp.array(array)
        return jnp.asarray(array)

    def _shard_inputs(self, values, ok, claim_mask):
        """The sharded dispatcher manages its own device placement (and
        may hold arrays across the pipelined window) — hand it private
        copies when the inputs are reused staging buffers."""
        if not self.device_resident:
            return values, ok, claim_mask
        return np.array(values), np.array(ok), np.array(claim_mask)

    def _finish_group(self, pending: _PendingGroup) -> None:
        """Host-sync one dispatched group and write each member's
        per-claim slice back (consensus state, journal, metrics)."""
        from svoc_tpu.utils.rounding import round6_list

        members = pending.members
        out = pending.out
        oks = pending.oks
        c = len(members)
        plane = self.cost_plane
        track = (
            plane is not None
            and plane.enabled
            and pending.warmth_key is not None
        )
        t_sync = time.perf_counter() if track else 0.0
        with stage_span("fabric_sync"):
            if not isinstance(oks, list):
                # Sanitized dispatch: the traced in-graph masks (still
                # on device, padded to the bucket) are the accounting
                # source.
                oks = list(np.asarray(oks)[:c])  # svoclint: disable=SVOC001
            # ONE host sync for the whole micro-batch — the claim axis
            # amortizes the dispatch/fetch overhead that a per-claim
            # loop pays C times (bench.py --claims).
            essence = np.asarray(out.essence)  # svoclint: disable=SVOC001
            essence1 = np.asarray(out.essence_first_pass)
            rel1 = np.asarray(out.reliability_first_pass)
            rel2 = np.asarray(out.reliability_second_pass)
            reliable = np.asarray(out.reliable)
            valid = np.asarray(out.interval_valid)
        if track:
            # The dispatch's full host cost lands in the shape-keyed
            # ledger here (one fold per GROUP, not per claim — the
            # amortization is the point), under the warmth the dispatch
            # was accounted at.
            sync_s = time.perf_counter() - t_sync
            plane.claim_mark(
                [s.spec.claim_id for s in members], "synced"
            )
            plane.observe_dispatch(
                pending.warmth_key,
                pending.warmth,
                pending.h2d_s + pending.dispatch_s + sync_s,
                breakdown={
                    "h2d": pending.h2d_s,
                    "dispatch": pending.dispatch_s,
                    "sync": sync_s,
                },
            )
        journal = self._resolve_journal()
        bucket = pending.bucket
        with stage_span("fabric_journal"):
            # Vectorized write-back (docs/PARALLELISM.md
            # §host-overhead): every journaled float rounds through ONE
            # numpy pass instead of a Python call per element per claim
            # — bit-identical to the old per-element loop
            # (utils/rounding.round6's exactness contract; the replay
            # fingerprints pin it).
            essence_rows = round6_list(essence[:c])
            essence1_rows = round6_list(essence1[:c])
            rel1_vals = round6_list(rel1[:c])
            rel2_vals = round6_list(rel2[:c])
            reliable_rows = reliable[:c].tolist()
            valid_flags = valid[:c].tolist()
            n_reliable = reliable[:c].sum(axis=1).tolist()
            admitted = np.stack(oks).sum(axis=1).tolist()
            inspected = [int(np.shape(ok_row)[0]) for ok_row in oks]
            for i, state in enumerate(members):
                lineage = pending.lineages[i]
                n_admitted = int(admitted[i])
                slice_ = {
                    "essence": essence_rows[i],
                    "essence_first_pass": essence1_rows[i],
                    "reliability_first_pass": rel1_vals[i],
                    "reliability_second_pass": rel2_vals[i],
                    "reliable": reliable_rows[i],
                    "interval_valid": valid_flags[i],
                    "admitted": n_admitted,
                }
                state.last_consensus = slice_
                journal.emit(
                    "fabric.consensus",
                    lineage=lineage,
                    claim=state.spec.claim_id,
                    interval_valid=slice_["interval_valid"],
                    admitted=n_admitted,
                    n_reliable=int(n_reliable[i]),
                    batch_claims=c,
                    batch_bucket=bucket,
                )
                labels = {"claim": state.spec.claim_id}
                self._metrics.counter(
                    "claim_slots_inspected", labels=labels
                ).add(inspected[i])
                self._metrics.counter(
                    "claim_slots_quarantined", labels=labels
                ).add(inspected[i] - n_admitted)
                self._metrics.gauge(
                    "claim_interval_valid", labels=labels
                ).set(1.0 if slice_["interval_valid"] else 0.0)

    def _commit_claim(self, state: ClaimState) -> None:
        """One resilient commit + supervisor fold + SLO pass for one
        claim; failures count into THAT claim's series only."""
        session = state.session
        labels = {"claim": state.spec.claim_id}
        failed = None
        outcome = None
        try:
            outcome = session.commit_resilient()
        except DegenerateBlockError:
            # Expected serving-tier cold start (a first request-fed
            # block has no oracle diversity yet): the chain write is
            # deferred, not failed — no commit budget burned, no
            # anomaly.  It is not a GOOD commit event either:
            # ``claim_commit_cycles`` counts only attempted chain
            # writes (incremented below, after this early return), so
            # a claim that defers forever reads as "no data", not as
            # commit_success=100% with zero landed txs.  The session
            # already journaled ``commit.deferred`` on the block's
            # lineage.
            self._metrics.counter(
                "claim_commit_deferred", labels=labels
            ).add(1)
            state.last_commit = {"deferred": True}
            session.supervisor_step()
            try:
                state.evaluator.evaluate()
            except Exception:
                self._metrics.counter("slo_errors").add(1)
            return
        except (ChainCommitError, CircuitOpenError) as e:
            # The commit path's EXPECTED failure classes: routine claim
            # accounting (this claim's breaker/supervisor already saw
            # them).
            failed = type(e).__name__
        except Exception as e:  # noqa: BLE001 — isolation contract
            # Anything else is a defect surfacing per claim (XLA
            # runtime error, adapter bug): still must not starve the
            # sibling claims, but it lands in the anomaly counter so it
            # reads as a bug, not as unexplained SLO burn.
            failed = f"{type(e).__name__}: {e}"
            self._metrics.counter(
                "fabric_claim_errors",
                labels={"claim": state.spec.claim_id, "stage": "commit"},
            ).add(1)
        self._metrics.counter("claim_commit_cycles", labels=labels).add(1)
        if failed is not None:
            self._metrics.counter(
                "claim_commit_failures", labels=labels
            ).add(1)
            state.last_commit = {"error": failed}
        else:
            if outcome.stranded:
                # Degraded cycles burn the claim's commit budget just
                # like the single-claim soak accounting.
                self._metrics.counter(
                    "claim_commit_failures", labels=labels
                ).add(1)
            state.last_commit = {
                "sent": outcome.sent,
                "total": outcome.total,
                "attempts": outcome.attempts,
                "stranded": len(outcome.stranded),
                "complete": outcome.complete,
            }
        session.supervisor_step()
        try:
            state.evaluator.evaluate()
        except Exception:
            self._metrics.counter("slo_errors").add(1)
