"""Claim registry: per-claim state for the multi-claim consensus fabric.

The paper's design serves ONE claim (one market/story) per session;
production means thousands of concurrent claims, each with its own
oracle fleet, lineage family, and SLO (ROADMAP item 1, following
HybridFlow's single-controller-over-multi-workload shape).  This module
is the controller's bookkeeping half:

- :class:`ClaimSpec` — the static description of one claim (fleet
  shape, consensus model, scheduling weight, SLO objectives, and an
  optional seeded ``tamper`` hook for Byzantine scenarios);
- :class:`ClaimState` — the live state the fabric owns per claim: the
  claim's :class:`~svoc_tpu.apps.session.Session` (fleet slots, chain
  adapter, supervisor health, quarantine gate — everything PRs 1–5
  built, now one-per-claim), its SLO evaluator, its scheduling
  bookkeeping, and the latest claim-batched consensus slice;
- :class:`ClaimRegistry` — the thread-safe id → state map the
  :class:`~svoc_tpu.fabric.router.ClaimRouter` schedules over.

The dynamic half (micro-batch assembly, fair scheduling, the fused
claim-cube dispatch) lives in :mod:`svoc_tpu.fabric.router`; the
operator facade in :mod:`svoc_tpu.fabric.session`.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from svoc_tpu.consensus.kernel import ConsensusConfig


@dataclasses.dataclass(frozen=True)
class ClaimSpec:
    """Static description of one claim (market/story/topic).

    ``seed=None`` derives the claim's oracle-stream seed from the
    fabric's base seed via :func:`svoc_tpu.sim.generators.claim_seed`
    (crc32-keyed — N claims get independent, replayable streams).
    ``weight`` is the fair-scheduler share: a weight-2 claim is served
    ~2× as often as a weight-1 sibling when the micro-batch cannot fit
    everyone.  ``tamper`` is the Byzantine-scenario hook threaded into
    ``Session.fetch(tamper=...)`` — called as ``tamper(cycle, block)``
    with the claim's served-cycle count, returning the (possibly
    corrupted) ``[N, M]`` block; None for honest claims.
    """

    claim_id: str
    seed: Optional[int] = None
    n_oracles: int = 7
    n_failing: int = 2
    dimension: int = 6
    constrained: bool = True
    #: unconstrained estimator spread (must be > 0 when
    #: ``constrained=False`` — the exact engine divides by it).
    max_spread: float = 10.0
    weight: int = 1
    commit_objective: float = 0.99
    admission_objective: float = 0.90
    tamper: Optional[Callable[[int, np.ndarray], np.ndarray]] = None

    def __post_init__(self):
        if not self.claim_id:
            raise ValueError("claim_id must be non-empty")
        if "-" in self.claim_id or "/" in self.claim_id:
            # Lineage ids are ``blk<scope>-<claim>-<n>`` and the audit
            # endpoint routes on path segments: a separator inside the
            # claim id would make the partition ambiguous.
            raise ValueError(
                f"claim_id {self.claim_id!r} must not contain '-' or '/'"
            )
        if self.weight < 1:
            raise ValueError("weight must be >= 1")
        if not self.constrained and self.max_spread <= 0.0:
            raise ValueError(
                "unconstrained claims need max_spread > 0 "
                "(contract.cairo:365-368 divides by it)"
            )

    def consensus_config(self) -> ConsensusConfig:
        """The claim's kernel configuration — the static half of the
        claim-cube dispatch (claims sharing it batch together)."""
        return ConsensusConfig(
            n_failing=self.n_failing,
            constrained=self.constrained,
            max_spread=self.max_spread,
        )


class ClaimState:
    """Everything the fabric owns for one live claim.

    Mutable fields are written only by the router's (single-threaded)
    scheduling loop; readers (web UI snapshots) take the registry lock
    around whole-dict reads and tolerate a torn *latest-consensus*
    view exactly like the single-claim web UI tolerates a mid-fetch
    poll.
    """

    def __init__(self, spec: ClaimSpec, session, evaluator, index: int):
        self.spec = spec
        #: the claim's Session (claim-scoped lineage, own adapter /
        #: supervisor / gate / breaker — PRs 1–5, one instance per claim).
        self.session = session
        #: per-claim SLO evaluator (``svoc_tpu.utils.slo.claim_slos``).
        self.evaluator = evaluator
        #: registration ordinal — the scheduler's deterministic tie-break.
        self.index = index
        #: served-cycle count (the ``tamper`` hook's clock).
        self.cycles = 0
        #: scheduling pause (an operator can drain a claim without
        #: removing its state).
        self.paused = False
        #: latest claim-batched consensus slice (None before the first
        #: served cycle): essence, interval_valid, reliable mask,
        #: reliabilities — the fabric's device-side view, vs the
        #: exact-engine state on the claim's own chain.
        self.last_consensus: Optional[Dict[str, Any]] = None
        #: latest commit outcome summary.
        self.last_commit: Optional[Dict[str, Any]] = None

    def snapshot(self) -> Dict[str, Any]:
        """JSON-friendly per-claim state (``/api/state``'s ``claims``
        section, docs/FABRIC.md)."""
        session = self.session
        with session.lock:
            lineage = session.last_lineage
        resilience = session.resilience_snapshot()
        return {
            "claim": self.spec.claim_id,
            "cycles": self.cycles,
            "paused": self.paused,
            "lineage": lineage,
            "consensus": self.last_consensus,
            "commit": self.last_commit,
            "health": resilience["health"],
            "replacements": resilience["replacements"],
            "quarantined": resilience["quarantined"],
            "oracle_list": [
                repr(a) for a in session.adapter.cache_snapshot().get(
                    "oracle_list"
                ) or []
            ],
        }


class ClaimRegistry:
    """Thread-safe claim id → :class:`ClaimState` map, iteration in
    registration order (the scheduler's deterministic base order)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._states: Dict[str, ClaimState] = {}
        self._next_index = 0

    def add(self, spec: ClaimSpec, session, evaluator) -> ClaimState:
        with self._lock:
            if spec.claim_id in self._states:
                raise ValueError(f"claim {spec.claim_id!r} already registered")
            state = ClaimState(spec, session, evaluator, self._next_index)
            self._next_index += 1
            self._states[spec.claim_id] = state
            return state

    def remove(self, claim_id: str) -> ClaimState:
        with self._lock:
            try:
                return self._states.pop(claim_id)
            except KeyError:
                raise KeyError(f"unknown claim {claim_id!r}") from None

    def get(self, claim_id: str) -> ClaimState:
        with self._lock:
            try:
                return self._states[claim_id]
            except KeyError:
                raise KeyError(f"unknown claim {claim_id!r}") from None

    def ids(self) -> List[str]:
        with self._lock:
            return list(self._states)

    def states(self) -> List[ClaimState]:
        """Registration-order snapshot (safe to iterate while claims
        are added concurrently — the list is a copy)."""
        with self._lock:
            return list(self._states.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._states)

    def __contains__(self, claim_id: str) -> bool:
        with self._lock:
            return claim_id in self._states
