"""MultiSession: the operator facade over the claim fabric.

One object that owns N claims end-to-end (docs/FABRIC.md): a
:class:`~svoc_tpu.fabric.registry.ClaimRegistry` of per-claim state —
each claim gets its own :class:`~svoc_tpu.apps.session.Session` (fleet,
chain adapter, supervisor, breaker, quarantine gate, claim-scoped
lineage) and its own SLO evaluator — multiplexed by a
:class:`~svoc_tpu.fabric.router.ClaimRouter` through ONE claim-batched
consensus dispatch per cycle.  The single-claim ``Session`` of PRs 1–5
is unchanged; ``MultiSession`` composes many of them the way
HybridFlow's single controller composes many workloads (PAPERS.md,
arxiv 2409.19256).

Seeding: a claim whose spec leaves ``seed=None`` derives its oracle
stream from the fabric's ``base_seed`` via
:func:`svoc_tpu.sim.generators.claim_seed` (crc32-keyed off the claim
id), so N claims are independent AND replayable from one number.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence

from svoc_tpu.apps.session import Session, SessionConfig
from svoc_tpu.fabric.registry import ClaimRegistry, ClaimSpec, ClaimState
from svoc_tpu.fabric.router import ClaimRouter
from svoc_tpu.io.comment_store import CommentStore
from svoc_tpu.sim.generators import claim_seed
from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_metrics


class MultiSession:
    """N claims, one controller (docs/FABRIC.md).

    ``journal``/``metrics``/``lineage_scope`` default to the process
    singletons — live deployments want one journal and one /metrics
    surface.  Seeded scenarios (``make fabric-smoke``) inject all three
    fresh and pinned, because replay identity needs event seqs starting
    at 1, counters starting at 0, and lineage ids that do not depend on
    how many sessions the process made before.
    """

    def __init__(
        self,
        specs: Iterable[ClaimSpec] = (),
        *,
        base_seed: int = 0,
        vectorizer: Optional[Callable[[Sequence[str]], object]] = None,
        store_factory: Optional[Callable[[str], CommentStore]] = None,
        journal=None,
        metrics: Optional[MetricsRegistry] = None,
        lineage_scope: Optional[str] = None,
        max_claims_per_batch: int = 8,
        sanitized_dispatch: bool = False,
        consensus_impl: Optional[str] = None,
        mesh=None,
        pipelined: bool = False,
        device_resident: bool = False,
        commit_mode: Optional[str] = None,
        warmup_mode: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
        adapter_factory=None,
    ):
        self.base_seed = base_seed
        self._vectorizer = vectorizer
        self._store_factory = store_factory
        #: ``adapter_factory(spec) -> ChainAdapter`` overrides each new
        #: claim session's default in-memory chain — the durability
        #: layer injects adapters over a crash-surviving tx log here
        #: (:mod:`svoc_tpu.durability.chainlog`), and a Sepolia
        #: deployment would inject real backends the same way.
        self._adapter_factory = adapter_factory
        #: Commit-intent WAL shared by every claim session once
        #: :meth:`attach_wal` is called (claim-tagged records).
        self._wal = None
        self._journal = journal
        self._metrics = metrics or _default_metrics
        self._lineage_scope = lineage_scope
        #: Clock for the per-claim SLO evaluators.  Seeded serving
        #: replays MUST pass the scenario's virtual clock here: the
        #: evaluators emit latched ``slo.alert`` events into the same
        #: journal the replay fingerprint digests, so wall-clock burn
        #: windows would make two identical runs alert differently
        #: (docs/SERVING.md §replay).
        self._clock = clock
        self.registry = ClaimRegistry()
        #: ``consensus_impl`` pins the claim-cube execution strategy
        #: (``"xla"`` | ``"pallas"``; None = env > PERF_DECISIONS.json
        #: > xla, resolved once by the router).  Seeded replays that
        #: want a non-default impl must pass it explicitly — the impl
        #: choice is part of the replay's config (docs/FABRIC.md
        #: §replay), like the fresh journal/registry/pinned scope.
        #: ``mesh`` pins the 2-D (claim × oracle) dispatch mesh the
        #: same way (``"<claims>x<oracles>"`` | jax Mesh | ``"off"``;
        #: None = ``SVOC_MESH`` env > PERF_DECISIONS.json > unsharded
        #: — docs/FABRIC.md §mesh), and ``pipelined`` turns on the
        #: double-buffered pull-mode dispatch (consensus k-1 overlaps
        #: fetch k; drain with :meth:`flush`).
        #: Commit-plane mode for every claim session this fabric builds
        #: (``"per_tx"`` | ``"batched"``; None = env > the committed
        #: PERF_DECISIONS.json ``commit_mode`` record > per_tx, resolved
        #: once per Session — docs/RESILIENCE.md §batched-commits).
        #: Pinned here like ``consensus_impl``: the WAL record family a
        #: seeded crash replay produces depends on it.
        self._commit_mode = commit_mode
        #: ``device_resident`` turns on the zero-allocation staging +
        #: donated dispatch (docs/PARALLELISM.md §host-overhead) —
        #: bit-identical outputs, so NOT a fingerprint family.
        #: ``warmup_mode`` pins the compile-plane routing the same way
        #: (``"none"`` | ``"prewarm"``; None = ``SVOC_WARMUP`` env >
        #: PERF_DECISIONS.json > none, resolved once by the router —
        #: docs/PARALLELISM.md §compile-plane).  :meth:`start_prewarm`
        #: honors it.
        self.router = ClaimRouter(
            self.registry,
            max_claims_per_batch=max_claims_per_batch,
            metrics=self._metrics,
            journal=journal,
            sanitized_dispatch=sanitized_dispatch,
            consensus_impl=consensus_impl,
            mesh=mesh,
            pipelined=pipelined,
            device_resident=device_resident,
            warmup_mode=warmup_mode,
        )
        for spec in specs:
            self.add_claim(spec)

    @property
    def metrics(self) -> MetricsRegistry:
        """The fabric's metrics registry (the serving tier and tools
        must account into the SAME registry the router does)."""
        return self._metrics

    @property
    def journal(self):
        """The injected journal, or None (= the process default — the
        serving tier resolves it the same way the router does)."""
        return self._journal

    # -- claim lifecycle ----------------------------------------------------

    def add_claim(
        self,
        spec: ClaimSpec,
        *,
        store: Optional[CommentStore] = None,
        vectorizer: Optional[Callable[[Sequence[str]], object]] = None,
    ) -> ClaimState:
        """Register one claim: build its Session (claim-scoped lineage,
        own adapter/supervisor/gate/breaker) and its SLO evaluator.
        The claim joins the router's rotation on the next ``step``."""
        from svoc_tpu.utils.slo import SLOEvaluator, claim_slos

        seed = (
            spec.seed
            if spec.seed is not None
            else claim_seed(self.base_seed, spec.claim_id)
        )
        config = SessionConfig(
            n_oracles=spec.n_oracles,
            n_failing=spec.n_failing,
            dimension=spec.dimension,
            constrained=spec.constrained,
            max_spread=spec.max_spread if not spec.constrained else 0.0,
            seed=seed,
            claim=spec.claim_id,
            lineage_scope=self._lineage_scope,
            commit_mode=self._commit_mode,
        )
        if store is None:
            store = (
                self._store_factory(spec.claim_id)
                if self._store_factory is not None
                else CommentStore()
            )
        session = Session(
            config=config,
            store=store,
            vectorizer=vectorizer or self._vectorizer,
            adapter=(
                self._adapter_factory(spec)
                if self._adapter_factory is not None
                else None
            ),
            journal=self._journal,
        )
        if self._wal is not None:
            session.attach_wal(self._wal)
        evaluator = SLOEvaluator(
            claim_slos(
                self._metrics,
                spec.claim_id,
                commit_objective=spec.commit_objective,
                admission_objective=spec.admission_objective,
            ),
            registry=self._metrics,
            journal=self._journal,
            **({"clock": self._clock} if self._clock is not None else {}),
        )
        # Pre-register the claim's SLO counter series (and the anomaly
        # counter's stages) at zero, so ``render_prometheus`` exposes a
        # complete per-claim family from registration onward — a scrape
        # can tell "claim exists, nothing happened yet" from "claim
        # unknown", and dashboards don't get born mid-incident.
        labels = {"claim": spec.claim_id}
        for name in (
            "claim_commit_cycles",
            "claim_commit_failures",
            "claim_commit_deferred",
            "claim_slots_inspected",
            "claim_slots_quarantined",
        ):
            self._metrics.counter(name, labels=labels).add(0)
        for stage in ("fetch", "commit"):
            self._metrics.counter(
                "fabric_claim_errors",
                labels={"claim": spec.claim_id, "stage": stage},
            ).add(0)
        return self.registry.add(spec, session, evaluator)

    def attach_wal(self, wal) -> None:
        """Wire one :class:`svoc_tpu.durability.wal.CommitIntentWAL`
        through every claim session (current and future): each claim's
        resilient commits journal claim-tagged, fsynced intent records
        into the shared log (docs/RESILIENCE.md §durability)."""
        self._wal = wal
        for state in self.registry.states():
            state.session.attach_wal(wal)

    def remove_claim(self, claim_id: str) -> ClaimState:
        """Drop a claim from the registry (its Session object survives
        for the caller — lineage history in the journal is untouched)."""
        return self.registry.remove(claim_id)

    def pause(self, claim_id: str, paused: bool = True) -> None:
        """Drain a claim without removing its state: a paused claim
        keeps its rotation slots but is skipped by ``select``."""
        self.registry.get(claim_id).paused = paused

    def get(self, claim_id: str) -> ClaimState:
        return self.registry.get(claim_id)

    def claim_ids(self) -> List[str]:
        return self.registry.ids()

    # -- the multiplexed loop -----------------------------------------------

    def step(self, feeds=None) -> Dict:
        """One fabric cycle: fair-select → fetch each → ONE claim-cube
        consensus dispatch per (shape, config) group → per-claim
        resilient commit + supervisor + SLO.  ``feeds`` switches to the
        request-driven cycle (``ClaimRouter.step``, docs/SERVING.md)."""
        return self.router.step(feeds=feeds)

    def run(self, cycles: int) -> List[Dict]:
        """``cycles`` steps; returns the per-step reports.  A pipelined
        router drains its one-cycle consensus tail afterwards, so the
        last cycle's write-backs are visible to the caller."""
        reports = [self.step() for _ in range(cycles)]
        self.flush()
        return reports

    def flush(self) -> int:
        """Drain pipelined in-flight consensus write-backs
        (:meth:`ClaimRouter.flush`); no-op when unpipelined."""
        return self.router.flush()

    # -- the compile plane (docs/PARALLELISM.md §compile-plane) --------------

    def start_prewarm(
        self,
        *,
        budget_s: Optional[float] = None,
        background: bool = True,
        force: bool = False,
        include_twins: bool = True,
    ):
        """Build (once) and run the AOT prewarm worker over this
        fabric's live shape universe
        (:class:`~svoc_tpu.compile.prewarm.PrewarmWorker`).

        Honors the router's pinned ``warmup_mode`` — a ``"none"``
        routing returns None unless ``force=True`` (tools/benches force
        their legs explicitly; the serving deployment follows the
        committed decision).  ``background=True`` (the serving default)
        compiles on a daemon thread while the tier serves — and defers
        cold shapes (docs/SERVING.md §cold-start); ``background=False``
        blocks until the universe is warm (recovery restarts, smokes,
        benches — with a persistent cache the walk is retrievals, not
        compiles).  Returns the worker, reused on repeat calls (a
        second call after new claims registered re-walks the refreshed
        universe).

        ``include_twins=False`` restricts THIS walk to the PRIMARY
        variants this construction-pinned process can actually dispatch
        — the synchronous recovery path uses it (a blocking restart
        should reach serving-ready in the primary walk's time, ~1/4 of
        the full universe).  It is a per-walk override, not worker
        state: a later call with the default re-enumerates the twins
        and compiles only what is still missing (warmed keys are
        skipped), which is exactly how the restart-insurance twins
        land on the background walk after a primary-only recovery."""
        if self.router.warmup_mode == "none" and not force:
            return None
        worker = self.router.prewarmer
        if worker is None:
            from svoc_tpu.compile.prewarm import PrewarmConfig, PrewarmWorker

            worker = PrewarmWorker(
                self.router,
                self.registry,
                metrics=self._metrics,
                config=PrewarmConfig(budget_s=budget_s),
            )
            self.router.attach_prewarmer(worker)
        if background:
            worker.start(budget_s=budget_s, include_twins=include_twins)
        else:
            worker.warm_all(budget_s=budget_s, include_twins=include_twins)
        return worker

    # -- views ---------------------------------------------------------------

    def claims_state(self) -> Dict[str, Dict]:
        """Per-claim snapshots (``/api/state``'s ``claims`` section)."""
        return {
            state.spec.claim_id: state.snapshot()
            for state in self.registry.states()
        }

    def snapshot(self) -> Dict:
        return {
            "steps": self.router.steps,
            "n_claims": len(self.registry),
            # The pinned dispatch routing (docs/FABRIC.md §mesh): an
            # operator can tell a mesh-sharded box from a single-device
            # one — and a pallas-routed one from XLA — straight from
            # /api/state.
            "consensus_impl": self.router.consensus_impl,
            "mesh": self.router.mesh_spec,
            "pipelined": self.router.pipelined,
            "device_resident": self.router.device_resident,
            "warmup_mode": self.router.warmup_mode,
            "prewarm": (
                self.router.prewarmer.stats()
                if self.router.prewarmer is not None
                else None
            ),
            "claims": self.claims_state(),
        }

    def _resolve_journal(self):
        from svoc_tpu.fabric.router import resolve_journal

        return resolve_journal(self._journal)

    def claim_fingerprint(self, claim_id: str) -> str:
        """Replay digest of ONE claim's slice of the journal — every
        event whose lineage this claim's session minted.  Seqs are
        global, so identity across runs also certifies identical
        scheduler interleaving (docs/FABRIC.md §replay)."""
        state = self.registry.get(claim_id)
        return self._resolve_journal().fingerprint(
            lineage_prefix=state.session.lineage_prefix + "-"
        )

    def audit(self, lineage: str) -> Dict:
        """The per-block audit record for any claim's block — lineage
        ids are claim-prefixed, so the id alone names the claim."""
        from svoc_tpu.utils.events import audit_record

        return audit_record(lineage, journal=self._journal)

    def attach(self, console) -> None:
        """Expose this fabric through an existing
        :class:`~svoc_tpu.apps.commands.CommandConsole`: the ``claims``
        command and ``/api/state``'s ``claims`` section read it."""
        console.fabric = self
