"""Multi-claim consensus fabric (docs/FABRIC.md).

Claim as a first-class batch axis from fetch to commit: a
:class:`ClaimRegistry` of per-claim state, a :class:`ClaimRouter` that
assembles pow2-bucketed claim micro-batches and runs ONE claim-cube
consensus dispatch per cycle, and the :class:`MultiSession` operator
facade over both (ROADMAP item 1; HybridFlow's
single-controller-over-multi-workload shape, arxiv 2409.19256).
"""

from svoc_tpu.fabric.registry import ClaimRegistry, ClaimSpec, ClaimState
from svoc_tpu.fabric.router import ClaimRouter
from svoc_tpu.fabric.scenario import run_fabric_scenario
from svoc_tpu.fabric.session import MultiSession

__all__ = [
    "ClaimRegistry",
    "ClaimRouter",
    "ClaimSpec",
    "ClaimState",
    "MultiSession",
    "run_fabric_scenario",
]
