"""Seeded multi-claim fabric scenario: the ``make fabric-smoke`` gate.

N claims (default 4 × 7 oracles) multiplexed through one
:class:`~svoc_tpu.fabric.session.MultiSession`; the LAST claim carries
a Byzantine offender — its final oracle slot emits NaN / Inf /
out-of-range vectors on a seeded schedule (cycle 0 always clean, like
the PR 4 Byzantine scenario, so every claim's consensus activates).
The run must show:

- every injected vector quarantined by THAT claim's gate and skipped
  from its commit (zero dirty txs), with ZERO quarantines on the
  sibling claims — one claim's poison never crosses the claim axis;
- the offender charged through its own supervisor and voted out via
  its own contract's replacement flow, while sibling fleets keep all
  their oracles;
- byte-identical PER-CLAIM journal fingerprints across two runs of the
  same seed (``EventJournal.fingerprint(lineage_prefix=...)`` — seqs
  are global, so per-claim identity also certifies the scheduler
  interleaved the claims identically).

Everything the run touches is derived from ``seed``: per-claim comment
stores and oracle streams key off :func:`claim_seed`, the injection
schedule off a crc-folded offender key, the deterministic vectorizer
off the comment text itself, and the lineage scope is pinned
(``lineage_scope="fab"``) with a FRESH journal + metrics registry per
run so event seqs and SLO counter deltas replay exactly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from svoc_tpu.fabric.registry import ClaimSpec
from svoc_tpu.fabric.session import MultiSession
from svoc_tpu.sim.generators import claim_seed

#: Claim ids for the default scenario — no ``-``/``/`` (lineage ids are
#: ``blk<scope>-<claim>-<n>``; ClaimSpec enforces this).
CLAIM_NAMES = ("alpha", "beta", "gamma", "delta", "epsilon", "zeta")


def _claim_names(n: int) -> List[str]:
    if n <= len(CLAIM_NAMES):
        return list(CLAIM_NAMES[:n])
    return list(CLAIM_NAMES) + [f"claim{i}" for i in range(len(CLAIM_NAMES), n)]


def deterministic_vectorizer(texts) -> np.ndarray:
    """Comments → ``[B, 6]`` rows in (0, 1): a pure function of the
    TEXT (crc-seeded), so two runs over the same seeded stores vectorize
    identically — no transformer build, no global RNG."""
    import zlib

    out = np.empty((len(texts), 6), dtype=np.float64)
    for i, text in enumerate(texts):
        rng = np.random.default_rng(zlib.crc32(text.encode()))
        row = rng.uniform(0.05, 0.95, size=6)
        out[i] = row / row.sum()
    return out


def _injection_schedule(
    seed: int, offender_claim: str, cycles: int
) -> List[Optional[str]]:
    """Per-cycle malformed-input kind for the offender slot (None =
    clean).  Cycle 0 is always clean so the claim's consensus activates
    before the attack starts; the kinds cover the constrained gate's
    reachable taxonomy (nan / inf / range — codec-breaking values
    report as ``range`` under the constrained precedence,
    docs/ROBUSTNESS.md)."""
    rng = np.random.default_rng(claim_seed(seed, offender_claim) ^ 0x5C0FAB)
    kinds: List[Optional[str]] = []
    for cycle in range(cycles):
        if cycle == 0 or rng.random() > 0.7:
            kinds.append(None)
        else:
            kinds.append(str(rng.choice(["nan", "inf", "range"])))
    return kinds


def run_fabric_scenario(
    seed: int = 0,
    *,
    cycles: int = 12,
    n_claims: int = 4,
    n_oracles: int = 7,
    dimension: int = 6,
    journal=None,
    metrics=None,
    mesh=None,
    pipelined: bool = False,
    device_resident: bool = False,
    commit_mode: Optional[str] = None,
    warmup: bool = False,
) -> Dict[str, Any]:
    """One seeded fabric run; returns per-claim fingerprints, isolation
    accounting, and the injection log.  Pure function of ``seed`` (plus
    the shape arguments) — ``tools/fabric_smoke.py`` runs it twice and
    asserts the fingerprints match byte-for-byte.

    ``warmup=True`` runs a SYNCHRONOUS AOT prewarm of the claim-cube
    shape universe before the first cycle (docs/PARALLELISM.md
    §compile-plane).  Warmup never journals and never changes numerics,
    so it is NOT a fingerprint family — ``make coldstart-smoke`` runs
    this scenario warmed (with a persistent compilation cache, across a
    kill/restart) and unwarmed and asserts byte-identical per-claim
    fingerprints.

    ``mesh`` pins the 2-D claim-cube dispatch mesh
    (``"<claims>x<oracles>"``, docs/FABRIC.md §mesh — the shard-smoke
    gate runs this scenario meshed and unmeshed and asserts IDENTICAL
    per-claim fingerprints, the sharded path being bitwise-exact);
    ``pipelined`` turns on the double-buffered dispatch (its own
    fingerprint family: consensus events land one cycle later).

    ``device_resident`` + ``commit_mode`` pin the host-overhead
    optimizations (docs/PARALLELISM.md §host-overhead): NEITHER is a
    fingerprint family — ``make hotpath-smoke`` runs this scenario
    optimized and unoptimized and asserts byte-identical per-claim
    fingerprints (the batched commit plane emits the per-tx plane's
    exact journal events; staging + donation are bit-identical
    numerics)."""
    from svoc_tpu.io.comment_store import CommentStore
    from svoc_tpu.io.scraper import SyntheticSource
    from svoc_tpu.utils.events import EventJournal
    from svoc_tpu.utils.metrics import MetricsRegistry

    if n_claims < 2:
        raise ValueError("isolation needs at least one sibling claim")
    journal = journal if journal is not None else EventJournal()
    metrics = metrics if metrics is not None else MetricsRegistry()
    names = _claim_names(n_claims)
    offender_claim = names[-1]
    offender_slot = n_oracles - 1
    kinds = _injection_schedule(seed, offender_claim, cycles)
    injections: List[Dict[str, Any]] = []

    def tamper(cycle: int, block: np.ndarray) -> np.ndarray:
        kind = kinds[cycle] if cycle < len(kinds) else None
        if kind is None:
            return block
        block = np.array(block, copy=True)
        if kind == "nan":
            block[offender_slot, 0] = np.nan
        elif kind == "inf":
            block[offender_slot, :] = np.inf
        else:  # out of the constrained [0, 1] domain
            block[offender_slot, :] = 7.5
        injections.append({"cycle": cycle, "kind": kind})
        return block

    def store_factory(claim_id: str) -> CommentStore:
        store = CommentStore()
        store.save(
            SyntheticSource(batch=120, seed=claim_seed(seed, claim_id))()
        )
        return store

    multi = MultiSession(
        base_seed=seed,
        vectorizer=deterministic_vectorizer,
        store_factory=store_factory,
        journal=journal,
        metrics=metrics,
        lineage_scope="fab",
        max_claims_per_batch=n_claims,
        mesh=mesh,
        pipelined=pipelined,
        device_resident=device_resident,
        commit_mode=commit_mode,
    )
    for name in names:
        multi.add_claim(
            ClaimSpec(
                claim_id=name,
                n_oracles=n_oracles,
                dimension=dimension,
                tamper=tamper if name == offender_claim else None,
            )
        )
    if warmup:
        multi.start_prewarm(background=False, force=True)
    reports = multi.run(cycles)

    claims: Dict[str, Any] = {}
    for name in names:
        state = multi.get(name)
        session = state.session
        resilience = session.resilience_snapshot()
        verdicts = [
            e
            for e in journal.recent(
                type="quarantine.verdict",
                lineage_prefix=session.lineage_prefix + "-",
            )
            if e.data.get("reasons")
        ]
        claims[name] = {
            "cycles": state.cycles,
            "fingerprint": multi.claim_fingerprint(name),
            "replacements": resilience["replacements"],
            "quarantined_slots": resilience["quarantined"],
            "quarantine_verdicts": len(verdicts),
            "oracle_list": [
                hex(a) for a in session.adapter.call_oracle_list()
            ],
            "interval_valid": (
                None
                if state.last_consensus is None
                else state.last_consensus["interval_valid"]
            ),
        }

    offender = claims[offender_claim]
    siblings = {n: c for n, c in claims.items() if n != offender_claim}
    # The offender's original address (slot layout from
    # apps.session._default_contract): replaced means it left the list.
    offender_address = hex(0x10 + offender_slot)
    return {
        "seed": seed,
        "cycles": cycles,
        "claims": claims,
        "offender_claim": offender_claim,
        "offender_address": offender_address,
        "injections": injections,
        "injection_count": len(injections),
        "offender_replaced": (
            offender["replacements"] >= 1
            and offender_address not in offender["oracle_list"]
        ),
        # Isolation: sibling fleets untouched — no quarantine verdicts
        # with reasons, no replacements, full rosters.
        "siblings_clean": all(
            c["quarantine_verdicts"] == 0 and c["replacements"] == 0
            for c in siblings.values()
        ),
        "journal_fingerprint": journal.fingerprint(),
        "journal_events": journal.last_seq(),
        "served_per_step": [len(r["served"]) for r in reports],
    }
