"""Flight recorder: the typed event journal and block-lineage layer.

Histograms (PR 1) aggregate the *distribution* and the resilience /
robustness layers (PRs 3–4) make individual incidents *survivable* —
but when an oracle gets voted out at 2 a.m. no single record explains
which block, which quarantine verdict, which breaker transition, and
which replacement vote caused it.  This module is that record, the
correlation layer G-Core / HybridFlow-scale orchestrators treat as a
first-class subsystem:

- :class:`EventRecord` — one typed, structured event with a monotone
  per-journal sequence number, a wall-clock timestamp (excluded from
  replay fingerprints), an optional **block lineage id**, and JSON-safe
  payload data.
- :class:`EventJournal` — a process-wide, lock-guarded bounded ring of
  events with JSONL export (sharing the ``SVOC_TRACE_FILE`` rotation
  with spans), subscriber callbacks (the postmortem auto-trigger), and
  a seeded-run **fingerprint** so chaos/Byzantine replays can assert
  event-stream identity, not just outcome identity.
- :class:`RotatingJsonlWriter` — size-capped JSONL segments shared by
  the span tracer and the journal (``SVOC_TRACE_MAX_BYTES`` /
  ``SVOC_TRACE_KEEP``), exported as the ``trace_file_bytes`` gauge.
- :func:`mint_lineage` / :func:`audit_record` — the lineage id minted
  at ``Session.fetch`` and the per-block audit assembly ("block
  blk-00001f: 2 quarantined (nan, range), committed 5/7, oracle 0x16
  charged, breaker stayed closed").

Event taxonomy (docs/OBSERVABILITY.md §events): ``block.fetched``,
``quarantine.verdict``, ``consensus.result``, ``commit.sent`` /
``commit.retried`` / ``commit.skipped`` / ``commit.failed``,
``breaker.transition``, ``supervisor.health`` / ``supervisor.charge`` /
``supervisor.replacement``, ``pipeline.producer_error``,
``trace.write_error``, ``slo.alert``, ``postmortem.bundle``, and the
serving tier's ``serving.admitted`` / ``serving.shed`` /
``serving.step`` (docs/SERVING.md).

Cost model: emission is host-side only (svoclint SVOC007 enforces it
stays out of jit-traced bodies, exactly like SVOC002 does for metrics)
and the per-event cost is one lock-guarded deque append plus an
optional buffered file write — the same order as a completed span.
Fingerprints digest ``(seq, type, lineage, data)`` and **never** wall
timestamps, so two seeded replays of one scenario agree byte-for-byte.

Thread-safety/deadlock contract: the journal lock is a leaf lock;
subscriber callbacks run on the emitting thread OUTSIDE the journal
lock, so emitters must not hold their own locks across ``emit`` when a
subscriber could re-enter them (the circuit breaker queues transition
events and flushes them after releasing its lock for exactly this
reason).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Tuple

from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry

#: The documented event types (docs/OBSERVABILITY.md).  Emission is not
#: restricted to this set — new subsystems may add types — but the
#: audit/summary helpers key their severity handling off it.
EVENT_TYPES: Tuple[str, ...] = (
    "block.fetched",
    "quarantine.verdict",
    "consensus.result",
    "commit.sent",
    "commit.retried",
    "commit.skipped",
    "commit.failed",
    "commit.deferred",
    "breaker.transition",
    "supervisor.health",
    "supervisor.charge",
    "supervisor.replacement",
    "pipeline.producer_error",
    "trace.write_error",
    "slo.alert",
    "postmortem.bundle",
    "postmortem.suppressed",
    "profile.captured",
    "serving.admitted",
    "serving.shed",
    "serving.step",
    "serving.deferred",
    "durability.snapshot",
    "durability.reconcile",
    "durability.drain",
)

#: Types (plus breaker.transition→open) surfaced as "alerts" in journal
#: summaries and soak/bench artifacts.
ALERT_TYPES = frozenset(
    {"slo.alert", "pipeline.producer_error", "trace.write_error",
     "commit.failed", "postmortem.bundle"}
)


def fsync_dir(path: str) -> None:
    """fsync the DIRECTORY containing ``path`` — renames and creates
    are metadata, and until the directory entry is durable a crash can
    resurrect the pre-rename layout.  Shared by the rotating trace
    writer, the commit-intent WAL, and the snapshot writer; failures
    are swallowed (platforms without directory fds)."""
    dirname = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:  # svoclint: disable=SVOC014 -- deliberate: platforms without directory fds cannot fsync a directory at all — best-effort is this helper's documented contract and there is nothing to degrade TO
        return
    try:
        with contextlib.suppress(OSError):
            os.fsync(fd)
    finally:
        os.close(fd)


def _json_safe(value: Any) -> Any:
    """Coerce to JSON-serializable, deterministically: numpy scalars →
    Python, tuples/sets → lists, mappings recursed, everything else
    repr'd (addresses may be symbolic objects in tests)."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, Mapping):
        return {str(k): _json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    if isinstance(value, (set, frozenset)):
        return sorted(_json_safe(v) for v in value)
    item = getattr(value, "item", None)
    if callable(item):
        try:  # numpy / jax scalars
            return _json_safe(item())
        except (TypeError, ValueError):  # svoclint: disable=SVOC014 -- deliberate: repr() below IS the output for non-scalar .item() objects — a codec choice inside pure data conversion, not a degraded serving path
            pass
    return repr(value)


@dataclasses.dataclass(frozen=True)
class EventRecord:
    """One journal entry.  ``ts`` is wall-clock for operators and is
    excluded from :meth:`fingerprint_payload` — replay identity must
    not depend on the clock."""

    seq: int
    ts: float
    type: str
    lineage: Optional[str]
    data: Dict[str, Any]

    def as_dict(self) -> Dict[str, Any]:
        return {
            "seq": self.seq,
            "ts": round(self.ts, 6),
            "event": self.type,
            "lineage": self.lineage,
            "data": self.data,
        }

    def to_json(self) -> str:
        return json.dumps(self.as_dict(), sort_keys=True)

    def fingerprint_payload(self) -> Dict[str, Any]:
        """The replay-stable projection: everything except ``ts``."""
        return {
            "seq": self.seq,
            "event": self.type,
            "lineage": self.lineage,
            "data": self.data,
        }


class RotatingJsonlWriter:
    """Size-capped append-only JSONL with K rotated segments.

    ``path`` is the active segment; on overflow it rotates to
    ``path.1`` … ``path.<keep>`` (oldest dropped), so a 90-minute soak
    with ``SVOC_TRACE_FILE`` set is bounded at ``(keep+1)·max_bytes``
    instead of growing without limit.  Line-buffered like the PR-1
    tracer file, so every written line is durable without an explicit
    flush.  Thread-safe; the live size is exported as the
    ``trace_file_bytes{path=<basename>}`` gauge.
    """

    MAX_BYTES_ENV = "SVOC_TRACE_MAX_BYTES"
    KEEP_ENV = "SVOC_TRACE_KEEP"
    #: Opt-in crash durability (docs/OBSERVABILITY.md §tracing): "1"
    #: fsyncs the file after EVERY written line (and the directory on
    #: rotation), so the journal tail the recovery manager replays
    #: after a SIGKILL is complete up to the last emit.  Costs one
    #: fdatasync per event (~50 µs–2 ms depending on the disk) — leave
    #: it off for pure-observability traces, turn it on when the trace
    #: is a durability artifact (docs/RESILIENCE.md §durability).
    FSYNC_ENV = "SVOC_TRACE_FSYNC"
    DEFAULT_MAX_BYTES = 64 * 1024 * 1024
    DEFAULT_KEEP = 3

    def __init__(
        self,
        path: str,
        max_bytes: Optional[int] = None,
        keep: Optional[int] = None,
        registry: Optional[MetricsRegistry] = None,
        fsync: Optional[bool] = None,
    ):
        self.path = path
        if max_bytes is None:
            max_bytes = int(
                os.environ.get(self.MAX_BYTES_ENV, self.DEFAULT_MAX_BYTES)
            )
        if keep is None:
            keep = int(os.environ.get(self.KEEP_ENV, self.DEFAULT_KEEP))
        if fsync is None:
            fsync = os.environ.get(self.FSYNC_ENV, "") == "1"
        if max_bytes < 1:
            raise ValueError("max_bytes must be >= 1")
        if keep < 0:
            raise ValueError("keep must be >= 0")
        self.max_bytes = max_bytes
        self.keep = keep
        self.fsync = bool(fsync)
        self._registry = registry or _default_registry
        self._lock = threading.Lock()
        self._file = None
        self._size = 0
        self._gauge = self._registry.gauge(
            "trace_file_bytes", labels={"path": os.path.basename(path)}
        )

    def _open_locked(self) -> None:
        if self._file is None:
            self._file = open(self.path, "a", buffering=1)
            try:
                self._size = os.path.getsize(self.path)
            except OSError:  # svoclint: disable=SVOC014 -- deliberate: 0 is the CORRECT size for a just-created file — the rotation accounting starts fresh, nothing degrades
                self._size = 0

    def _rotate_locked(self) -> None:
        if self._file is not None:
            with contextlib.suppress(OSError):
                self._file.close()
            self._file = None
        if self.keep == 0:
            # No rotated segments kept: truncate in place.
            with contextlib.suppress(OSError):
                os.remove(self.path)
        else:
            with contextlib.suppress(OSError):
                os.remove(f"{self.path}.{self.keep}")
            for i in range(self.keep - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    with contextlib.suppress(OSError):
                        os.replace(src, f"{self.path}.{i + 1}")
            with contextlib.suppress(OSError):
                os.replace(self.path, f"{self.path}.1")
        if self.fsync:
            # The renames above are metadata: a torn segment chain
            # would break the recovery manager's walk.
            fsync_dir(self.path)
        self._size = 0

    def write_line(self, line: str) -> None:
        """Append one line (newline added).  Raises ``OSError`` on
        failure — the caller owns the never-break-the-pipeline policy
        (and the error accounting: ``trace_write_errors``)."""
        text = line + "\n"
        # Size accounting in BYTES (the cap's documented unit, and what
        # _open_locked seeds from os.path.getsize) — counting str
        # length would undercount multibyte payloads ~4× and blow the
        # (keep+1)·max_bytes soak bound.
        nbytes = len(text.encode("utf-8"))
        with self._lock:
            self._open_locked()
            if self._size and self._size + nbytes > self.max_bytes:
                self._rotate_locked()
                self._open_locked()
            self._file.write(text)
            if self.fsync:
                # Line-buffered write already reached the OS; fsync
                # pushes it to the platter so a SIGKILL one instruction
                # later cannot lose it (the recovery manager's replay
                # contract, docs/RESILIENCE.md §durability).
                with contextlib.suppress(OSError):
                    os.fsync(self._file.fileno())
            self._size += nbytes
            self._gauge.set(self._size)

    def flush(self) -> None:
        with self._lock:
            if self._file is not None:
                with contextlib.suppress(OSError):
                    self._file.flush()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                with contextlib.suppress(OSError):
                    self._file.close()
                self._file = None

    def segments(self) -> List[str]:
        """Existing segment paths, newest first (active segment first)."""
        out = [self.path] if os.path.exists(self.path) else []
        for i in range(1, self.keep + 1):
            seg = f"{self.path}.{i}"
            if os.path.exists(seg):
                out.append(seg)
        return out


#: One writer per (real)path, process-wide, so the span tracer and the
#: event journal pointed at the same ``SVOC_TRACE_FILE`` share one size
#: account and one rotation schedule — two independent writers would
#: race the rename and double-rotate.
_writer_pool: Dict[str, RotatingJsonlWriter] = {}
_writer_pool_lock = threading.Lock()


def shared_writer(path: str) -> RotatingJsonlWriter:
    key = os.path.realpath(path)
    with _writer_pool_lock:
        writer = _writer_pool.get(key)
        if writer is None:
            writer = _writer_pool[key] = RotatingJsonlWriter(path)
        return writer


def release_writer(path: str) -> None:
    """Close the pooled writer's file handle for ``path`` (the writer
    stays pooled and reopens lazily on the next write).  Called when a
    tracer/journal is re-pointed away from a path — without it every
    abandoned trace destination would hold an open fd for the process
    lifetime."""
    key = os.path.realpath(path)
    with _writer_pool_lock:
        writer = _writer_pool.get(key)
    if writer is not None:
        writer.close()


def mint_lineage(n: int, prefix: str = "blk") -> str:
    """The canonical lineage-id form: ``blk-00001f`` for fetch claim 31.
    Deterministic in ``n`` so seeded replays mint identical ids."""
    return f"{prefix}-{int(n):06x}"


_lineage_scopes = itertools.count(1)


def lineage_scope() -> int:
    """A process-unique ordinal for lineage-minting scopes.  Several
    sessions share one process (and one default journal); without a
    scope each would mint ``blk-000001`` for its first fetch and their
    audit records would merge.  ``Session`` takes one at construction
    and mints ``blk<scope>-<claim>``."""
    return next(_lineage_scopes)


class EventJournal:
    """Bounded, lock-guarded ring of typed events + export/fingerprint.

    The process-wide default instance is :data:`journal`; seeded
    scenarios (``resilience/chaos.py``) construct their own so a replay
    starts from sequence 1 and two runs of one seed digest identically.
    """

    #: Same env var as the tracer: events and spans share one flight-
    #: recorder file (distinguished by their ``event`` vs ``name`` keys).
    TRACE_ENV = "SVOC_TRACE_FILE"

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        capacity: int = 4096,
    ):
        self._registry = registry or _default_registry
        self._ring: deque = deque(maxlen=capacity)
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self._subscribers: List[Callable[[EventRecord], None]] = []
        self._trace_path: Optional[str] = None
        self._trace_error = False

    # -- configuration ------------------------------------------------------

    def set_trace_file(self, path: Optional[str]) -> None:
        """Pin (or clear) the JSONL destination, overriding the env
        var; clears the write-error latch like the tracer's and
        releases the previous destination's pooled file handle."""
        with self._lock:
            old = self._resolve_path()
            self._trace_path = path
            self._trace_error = False
        if old and old != path:
            release_writer(old)

    def _resolve_path(self) -> Optional[str]:
        return self._trace_path or os.environ.get(self.TRACE_ENV) or None

    def subscribe(self, fn: Callable[[EventRecord], None]) -> None:
        """Register a callback run (on the emitting thread, outside the
        journal lock) for every subsequent event."""
        with self._lock:
            if fn not in self._subscribers:
                self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[EventRecord], None]) -> None:
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)

    # -- emission -----------------------------------------------------------

    def emit(
        self, event_type: str, lineage: Optional[str] = None, **data: Any
    ) -> EventRecord:
        """Record one event; returns the stored record."""
        safe_data = {k: _json_safe(v) for k, v in data.items()}
        with self._lock:
            # Seq allocation AND the append happen under one lock hold:
            # allocated outside, a preempted emitter could append its
            # lower seq after a racing higher one, breaking the strict
            # ring ordering since()/last_seq() consumers (the SSE
            # cursor) rely on.
            record = EventRecord(
                seq=next(self._seq),
                ts=time.time(),
                type=str(event_type),
                lineage=lineage,
                data=safe_data,
            )
            self._ring.append(record)
            subscribers = list(self._subscribers)
            path = self._resolve_path()
            write = path is not None and not self._trace_error
        self._registry.counter(
            "events_emitted", labels={"type": record.type}
        ).add(1)
        if write:
            try:
                shared_writer(path).write_line(record.to_json())
            except (OSError, ValueError):
                # A bad path must never take down the pipeline: latch
                # (until reconfigured) and count — same policy as the
                # tracer's write-error surfacing.
                with self._lock:
                    self._trace_error = True
                self._registry.counter("trace_write_errors").add(1)
        for fn in subscribers:
            try:
                fn(record)
            except Exception:
                # A broken subscriber (postmortem trigger mid-teardown)
                # must not poison emission for everyone else.
                self._registry.counter("event_subscriber_errors").add(1)
        return record

    # -- reads --------------------------------------------------------------

    def recent(
        self,
        n: Optional[int] = None,
        *,
        type: Optional[str] = None,
        lineage: Optional[str] = None,
        lineage_prefix: Optional[str] = None,
    ) -> List[EventRecord]:
        """Newest-last slice of the ring, optionally filtered by type
        and/or lineage BEFORE the tail cut (so ``recent(5,
        lineage=...)`` is the block's last 5 events, not the journal's
        last 5 that happen to match).  ``lineage_prefix`` matches a
        lineage FAMILY — the multi-claim fabric's per-claim partition:
        a claim session mints ``blk<scope>-<claim>-<n>`` ids, so the
        prefix ``blk<scope>-<claim>-`` selects every block that claim
        ever published (docs/FABRIC.md)."""
        with self._lock:
            events = list(self._ring)
        if type is not None:
            events = [e for e in events if e.type == type]
        if lineage is not None:
            events = [e for e in events if e.lineage == lineage]
        if lineage_prefix is not None:
            events = [
                e
                for e in events
                if e.lineage is not None
                and e.lineage.startswith(lineage_prefix)
            ]
        return events if n is None else events[-n:]

    def since(self, seq: int, limit: Optional[int] = None) -> List[EventRecord]:
        """Events with ``seq`` strictly greater than the given one —
        the SSE stream's cursor read."""
        with self._lock:
            events = [e for e in self._ring if e.seq > seq]
        return events if limit is None else events[:limit]

    def last_seq(self) -> int:
        with self._lock:
            return self._ring[-1].seq if self._ring else 0

    def counts_by_type(self) -> Dict[str, int]:
        with self._lock:
            events = list(self._ring)
        out: Dict[str, int] = {}
        for e in events:
            out[e.type] = out.get(e.type, 0) + 1
        return dict(sorted(out.items()))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- snapshot / recovery (docs/RESILIENCE.md §durability) ---------------

    def export_ring(self) -> List[Dict[str, Any]]:
        """The full buffered ring as JSON-safe dicts (``ts`` included —
        operators want wall time back after a restore; fingerprints
        still ignore it).  What the recovery manager's snapshot
        embeds."""
        with self._lock:
            return [e.as_dict() for e in self._ring]

    def restore(self, records: Iterable[Mapping[str, Any]]) -> int:
        """Rebuild the ring from :meth:`export_ring`-shaped dicts (a
        snapshot's journal section, optionally extended with the
        fsynced trace tail — :func:`read_trace_events`), PRESERVING the
        original seqs so fingerprints and audit records survive a
        process death.  Records are deduped by seq and sorted; the next
        ``emit`` continues numbering after the highest restored seq.
        Deliberately does NOT run subscribers — a restore replays
        history, it does not re-trigger postmortems.  Returns the
        number of restored events."""
        by_seq: Dict[int, EventRecord] = {}
        for r in records:
            rec = EventRecord(
                seq=int(r["seq"]),
                ts=float(r.get("ts", 0.0) or 0.0),
                type=str(r["event"]),
                lineage=r.get("lineage"),
                data=dict(r.get("data") or {}),
            )
            by_seq[rec.seq] = rec
        ordered = [by_seq[s] for s in sorted(by_seq)]
        with self._lock:
            self._ring.clear()
            self._ring.extend(ordered)
            last = ordered[-1].seq if ordered else 0
            self._seq = itertools.count(last + 1)
        return len(ordered)

    # -- replay identity ----------------------------------------------------

    def fingerprint(self, lineage_prefix: Optional[str] = None) -> str:
        """Canonical digest of the buffered event stream — sequence,
        types, lineage and data; never wall timestamps.  Two seeded
        replays of one scenario must agree on this byte-for-byte.

        ``lineage_prefix`` digests one claim's slice of a shared
        journal (``make fabric-smoke``'s per-claim replay witness).
        The filtered payloads still carry their GLOBAL seqs — per-claim
        identity across runs therefore also certifies that the
        scheduler interleaved the claims identically, which is exactly
        what a seeded fabric replay must reproduce."""
        with self._lock:
            events = list(self._ring)
        if lineage_prefix is not None:
            events = [
                e
                for e in events
                if e.lineage is not None
                and e.lineage.startswith(lineage_prefix)
            ]
        payloads = [e.fingerprint_payload() for e in events]
        return hashlib.sha256(
            json.dumps(payloads, sort_keys=True).encode()
        ).hexdigest()

    def summary(self, last_alerts: int = 5) -> Dict[str, Any]:
        """The artifact-embedded journal digest (soak/bench): counts by
        type, the last N alert-class events, and the fingerprint."""
        with self._lock:
            events = list(self._ring)
        alerts = [
            e.as_dict()
            for e in events
            if e.type in ALERT_TYPES
            or (e.type == "breaker.transition" and e.data.get("to") == "open")
        ]
        counts: Dict[str, int] = {}
        for e in events:
            counts[e.type] = counts.get(e.type, 0) + 1
        return {
            "events": len(events),
            "last_seq": events[-1].seq if events else 0,
            "counts_by_type": dict(sorted(counts.items())),
            "alerts": alerts[-last_alerts:],
            "fingerprint": self.fingerprint(),
        }


def read_trace_events(
    path: str, since_seq: int = 0, keep: Optional[int] = None
) -> List[Dict[str, Any]]:
    """Read journal events back out of a (possibly rotated) trace file
    — the recovery manager's roll-forward source (docs/RESILIENCE.md
    §durability).  Walks the rotated segments oldest→newest, keeps only
    EVENT lines (keyed ``event`` — the file is shared with span lines
    keyed ``name``), drops seqs ≤ ``since_seq``, and tolerates a torn
    final line (a SIGKILL mid-append leaves half a record; everything
    before it was fsynced when ``SVOC_TRACE_FSYNC=1``).  Mid-file
    garbage raises — that is corruption, not a crash artifact."""
    if keep is None:
        keep = int(
            os.environ.get(
                RotatingJsonlWriter.KEEP_ENV, RotatingJsonlWriter.DEFAULT_KEEP
            )
        )
    segments = [
        f"{path}.{i}" for i in range(keep, 0, -1) if os.path.exists(f"{path}.{i}")
    ]
    if os.path.exists(path):
        segments.append(path)
    out: List[Dict[str, Any]] = []
    for seg_idx, seg in enumerate(segments):
        with open(seg, "r") as f:
            lines = f.read().split("\n")
        # A trailing "" element means the file ends in a newline — the
        # normal case; anything else is a torn tail.
        torn = lines and lines[-1] != ""
        body, tail = (lines[:-1], lines[-1]) if lines else ([], "")
        for line in body:
            if not line:
                continue
            record = json.loads(line)
            if "event" in record and int(record.get("seq", 0)) > since_seq:
                out.append(record)
        if torn and tail:
            is_last = seg_idx == len(segments) - 1
            try:
                record = json.loads(tail)
            except ValueError:
                if not is_last:
                    raise ValueError(
                        f"corrupt trace segment {seg!r}: torn line in a "
                        "non-final segment"
                    )
                continue  # the crash artifact: ignore the torn append
            if "event" in record and int(record.get("seq", 0)) > since_seq:
                out.append(record)
    return out


#: Process-wide default journal (the apps layer, soak, and bench use
#: this), feeding the default metrics registry's ``events_emitted``
#: counters.
journal = EventJournal()


def emit_event(
    event_type: str, lineage: Optional[str] = None, **data: Any
) -> EventRecord:
    """``emit_event("block.fetched", lineage=..., n_comments=30)`` —
    the one-liner callsites use on the default journal.  Host-side
    only: svoclint SVOC007 flags any call inside a jit-traced body."""
    return journal.emit(event_type, lineage=lineage, **data)


def resolve_journal(injected) -> EventJournal:
    """An injected journal, or the process default — the one resolver
    the fabric router, the MultiSession facade, the durability plane,
    and the chaos harnesses share.  Lives here (not in fabric.router,
    its pre-PR-14 home, which re-exports it) so jax-free consumers —
    the WAL reconciler inside a chaos-fuzz child — can resolve a
    journal without importing the fabric stack."""
    if injected is not None:
        return injected
    return journal


# ---------------------------------------------------------------------------
# Per-block audit assembly
# ---------------------------------------------------------------------------


def _summarize(events: Iterable[EventRecord]) -> Dict[str, Any]:
    """The human-facing digest of one block's event stream."""
    quarantined: Dict[str, str] = {}
    charged: List[str] = []
    replaced: List[Dict[str, Any]] = []
    breaker: List[str] = []
    sent = skipped = retried = 0
    failures: List[str] = []
    interval_valid: Optional[bool] = None
    for e in events:
        if e.type == "quarantine.verdict":
            for slot, reason in (e.data.get("reasons") or {}).items():
                quarantined[str(slot)] = reason
        elif e.type == "supervisor.charge":
            charged.append(str(e.data.get("oracle")))
        elif e.type == "supervisor.replacement":
            replaced.append(dict(e.data))
        elif e.type == "breaker.transition":
            breaker.append(str(e.data.get("to")))
        elif e.type == "commit.sent":
            sent += int(e.data.get("sent", 0) or 0)
        elif e.type == "commit.skipped":
            skipped += len(e.data.get("slots") or []) or int(
                bool(e.data.get("oracle"))
            )
        elif e.type == "commit.retried":
            retried += 1
        elif e.type == "commit.failed":
            failures.append(str(e.data.get("cause", "")))
        elif e.type == "consensus.result":
            if "interval_valid" in e.data:
                interval_valid = bool(e.data["interval_valid"])
    return {
        "quarantined": quarantined,
        "charged": charged,
        "replacements": replaced,
        "breaker_transitions": breaker,
        "commit_sent": sent,
        "commit_skipped": skipped,
        "commit_retries": retried,
        "commit_failures": failures,
        "interval_valid": interval_valid,
    }


def audit_record(
    lineage: str,
    *,
    journal: Optional[EventJournal] = None,
    tracer: Optional[Any] = None,
) -> Dict[str, Any]:
    """Everything the flight recorder knows about one block: its
    events, its spans (the tracer threads lineage through nested
    stages), and a derived summary — the ``GET /api/audit/<block>`` /
    console ``audit`` payload."""
    from svoc_tpu.utils import metrics as _metrics

    j = journal if journal is not None else globals()["journal"]
    t = tracer if tracer is not None else _metrics.tracer
    events = j.recent(lineage=lineage)
    spans = [
        {
            "name": s.name,
            "duration_s": round(s.duration_s, 6),
            "span_id": s.span_id,
            "parent_id": s.parent_id,
            "thread": s.thread,
        }
        for s in t.recent()
        if getattr(s, "lineage", None) == lineage
    ]
    return {
        "lineage": lineage,
        "found": bool(events) or bool(spans),
        "events": [e.as_dict() for e in events],
        "spans": spans,
        "summary": _summarize(events),
    }
