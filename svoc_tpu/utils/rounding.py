"""Vectorized 6-decimal rounding with exact Python-`round` parity.

The journal write-back contract (docs/OBSERVABILITY.md §events) rounds
every float the fabric journals to 6 decimals with Python's
``round(float(x), 6)`` — and seeded replay fingerprints digest those
values byte-for-byte, so ANY drift in the rounding is a replay break.
The original per-element loop paid one Python call per element per
claim per cycle (``fabric/router.py`` pre-PR-13); this module is the
one-sync vectorized replacement that is *bit-identical* to the loop.

Why not plain ``np.round``: numpy rounds by scaling
(``rint(x * 10^6) / 10^6``) while CPython rounds the exact decimal
expansion of the binary float (``double_round`` via ``_Py_dg_dtoa``).
The scaled product carries up to ~0.5 ulp of error, so a value whose
true scaled fraction sits within ~1e-10 of a half-boundary can round
differently — ``0.0000005`` is the canonical divergence.  Consensus
essences are arbitrary float mantissas; across thousands of journaled
values a divergence is a *when*, not an *if*.

The fix is a two-lane design:

- the bulk lane is ``np.round`` (one vectorized pass, no Python calls);
- every element whose scaled fractional part lands within
  ``_HALF_WINDOW`` of a half-boundary — the only region where the two
  implementations can disagree — is re-rounded through Python's
  ``round``.  The window (1e-6 of scaled-unit space, i.e. ~2e-6 of the
  fraction axis) is ~4 orders of magnitude wider than the maximum
  scaling error, and statistically selects ~0.0002 % of real-valued
  inputs, so the slow lane is almost always empty.

Non-finite values pass through both lanes identically (``np.round`` and
``round(x, 6)`` both return NaN/±Inf unchanged for ``ndigits`` given).
"""

from __future__ import annotations

from typing import List

import numpy as np

#: Half-boundary proximity (in scaled units, i.e. multiples of 1e-6)
#: below which the exact Python rounding adjudicates.  Must stay far
#: above the ~1e-10 worst-case scaling error and far below 0.5.
_HALF_WINDOW = 1e-6

#: Magnitude above which the scaled product ``x * 1e6`` leaves the
#: float64 integer-exact range (2^53) and ``np.round``'s divide-back
#: DOUBLE-ROUNDS — and the half-boundary distance computed below
#: degenerates, so the fixup lane cannot flag the divergence.  Every
#: such value routes straight to Python's exact rounding instead
#: (2^52/1e6, a 2× guard under the true 2^53/1e6 edge).  Journaled
#: essences are tiny in practice, but the unconstrained codec-only gate
#: admits values up to the i128 window — the parity contract must hold
#: there too.
_BIG = float(2**52) / 1e6


def round6(values) -> np.ndarray:
    """Round a float array to 6 decimals, bit-identical to mapping
    Python's ``round(float(x), 6)`` over every element (the journal
    write-back contract).  Returns a float64 array of the input shape;
    scalars become 0-d arrays (use :func:`round6_scalar` for a Python
    float)."""
    arr = np.asarray(values, dtype=np.float64)
    out = np.round(arr, 6)
    with np.errstate(invalid="ignore", over="ignore"):
        scaled = arr * 1e6
        # Distance of the scaled value from the nearest half-boundary;
        # NaN/Inf propagate to NaN here and compare False (fast lane).
        frac = np.abs(scaled - np.floor(scaled) - 0.5)
        risky = (frac < _HALF_WINDOW) | (np.abs(arr) >= _BIG)
    if np.any(risky):
        flat_out = out.reshape(-1)
        flat_in = arr.reshape(-1)
        for i in np.flatnonzero(risky.reshape(-1)):
            flat_out[i] = round(float(flat_in[i]), 6)
    return out


def round6_scalar(x) -> float:
    """``round(float(x), 6)`` — the scalar twin, for call sites that
    journal a single reliability/ratio value."""
    return round(float(x), 6)


def round6_list(values) -> List:
    """The journal-payload form: :func:`round6` then ``tolist()`` —
    plain Python floats (1-D input) or nested lists (2-D), exactly what
    the per-element ``[round(float(x), 6) for x in row]`` loops built."""
    return round6(values).tolist()
