"""Cross-cutting utilities: checkpointing, metrics, tracing."""
