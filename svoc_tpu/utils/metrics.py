"""Observability: counters, histograms, spans, gauges, and exposition.

The reference's only instrumentation is Cairo gas budgets and print
statements (SURVEY.md §5); the framework's north-star metric is
end-to-end comments/sec and consensus-update latency, so those get
first-class telemetry here, used by ``bench.py``, ``tools/soak.py``,
the apps loop, and the web server's ``/metrics`` endpoint.

Layered like a production trainer's telemetry (HybridFlow / G-Core
style — every pipeline stage and collective phase is a first-class
series):

- :class:`Counter` — monotone event counts with windowed rates,
- :class:`Histogram` — fixed log-spaced buckets, p50/p95/p99 snapshots,
- :class:`Gauge` — last-written values (device bytes, MFU, …),
- :class:`LatencyTimer` — running mean/max (kept for artifact compat),
- :class:`Tracer` / :func:`stage_span` — nestable spans with a bounded
  ring buffer and JSONL export (``SVOC_TRACE_FILE``), each completed
  span feeding the shared per-stage histogram so traces and scraped
  percentiles can never disagree,
- :meth:`MetricsRegistry.render_prometheus` — text exposition served at
  ``GET /metrics`` (``svoc_tpu.apps.web``) and dumped by the console's
  ``metrics prom`` command,
- :func:`sample_runtime_gauges` — on-demand device/runtime gauges
  (``jax.live_arrays()`` bytes per device, compile counts via a
  ``jax.monitoring`` listener, step-time-derived MFU).

Cost model: spans record AROUND dispatch on the host — never inside
``jit``, never adding a device sync — and one completed span is two
``perf_counter`` calls plus a lock-guarded histogram increment
(sub-microsecond against multi-ms stages).  Everything is thread-safe
under the auto_fetch / auto_commit / web-handler threads.

``jax.profiler`` tracing is wrapped so a session can be profiled with
one flag and inspected in TensorBoard/XProf.

Stage-name conventions (docs/OBSERVABILITY.md): ``scrape``,
``tokenize``, ``pack``, ``forward``, ``fleet``, ``consensus``,
``consensus_certify``, ``fetch``, ``commit``, ``serving_step``.
"""

from __future__ import annotations

import bisect
import contextlib
import itertools
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple


@dataclass
class Counter:
    """A monotone event counter with windowed rate reporting.

    ``rate()`` covers only the trailing ``window_s`` seconds, so the
    ``metrics`` command reports *recent* throughput — a lifetime
    average would decay forever after any idle period.
    ``lifetime_rate()`` keeps the old semantics explicitly.
    """

    count: float = 0.0
    window_s: float = 30.0
    started_at: float = field(default_factory=time.perf_counter)
    _events: deque = field(default_factory=deque)  # (timestamp, count_after)
    # add() runs on the auto_fetch daemon thread while rate() serves the
    # web/console thread — guard the deque walk.
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def add(self, n: float = 1.0) -> None:
        now = time.perf_counter()
        with self._lock:
            self.count += n
            self._events.append((now, self.count))
            self._prune(now)

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def rate(self) -> float:
        """Events/sec over the trailing window (0 when idle)."""
        now = time.perf_counter()
        with self._lock:
            self._prune(now)
            if not self._events:
                return 0.0
            t_oldest, c_oldest = self._events[0]
            span = now - t_oldest
            if span <= 0:
                return 0.0
            # Count since the window's first sample (whose own
            # increment belongs to the time before it).
            return (self.count - c_oldest) / span

    def lifetime_rate(self) -> float:
        elapsed = time.perf_counter() - self.started_at
        return self.count / elapsed if elapsed > 0 else 0.0

    def reset(self) -> None:
        with self._lock:
            self.count = 0.0
            self.started_at = time.perf_counter()
            self._events.clear()


@dataclass
class Gauge:
    """A last-written value (device bytes, MFU, queue depth, …)."""

    value: float = 0.0
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def get(self) -> float:
        with self._lock:
            return self.value


def log_buckets(
    lo: float = 1e-4, hi: float = 120.0, per_decade: int = 4
) -> Tuple[float, ...]:
    """Fixed log-spaced bucket upper bounds, ``lo``…``hi`` inclusive.

    The default grid (100 µs → 120 s, 4/decade ⇒ ~1.78× steps) spans
    everything the pipeline produces — sub-ms consensus dispatches up
    to first-call XLA compiles — with ≤ ~78 % worst-case interpolation
    error per bucket, far inside what p95/p99 regressions look like.
    """
    edges = []
    step = 10.0 ** (1.0 / per_decade)
    v = lo
    while v < hi * (1.0 + 1e-9):
        edges.append(float(f"{v:.6g}"))  # stable, readable bounds
        v *= step
    return tuple(edges)


DEFAULT_BUCKETS = log_buckets()


class Histogram:
    """Fixed-bucket histogram with percentile snapshots.

    Buckets are cumulative-upper-bound (Prometheus ``le`` semantics)
    with a final +Inf overflow bucket.  Percentiles interpolate
    linearly inside the selected bucket — exact enough for log-spaced
    buckets, and crucially *monotone* (a p99 regression can never hide
    behind sample order).  Thread-safe: ``observe`` runs on producer /
    auto_fetch threads while snapshots serve the web thread.
    """

    def __init__(self, buckets: Optional[Tuple[float, ...]] = None):
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets or DEFAULT_BUCKETS))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._min = None
        self._max = None
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """The q-th percentile (``q`` in [0, 100]), bucket-interpolated.

        0 with no samples.  The overflow bucket reports the observed
        max (a finite, honest answer — the +Inf bound is not a value).
        """
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q={q} outside [0, 100]")
        with self._lock:
            if not self._count:
                return 0.0
            target = q / 100.0 * self._count
            cumulative = 0
            for i, c in enumerate(self._counts):
                cumulative += c
                if cumulative >= target and c:
                    lo = self.buckets[i - 1] if i > 0 else min(
                        self._min or 0.0, self.buckets[0]
                    )
                    if i >= len(self.buckets):  # overflow bucket
                        return float(self._max)
                    hi = self.buckets[i]
                    frac = (target - (cumulative - c)) / c
                    return float(lo + (hi - lo) * min(max(frac, 0.0), 1.0))
            return float(self._max or 0.0)

    def snapshot(self) -> Dict[str, float]:
        """``{count, sum, min, max, p50, p95, p99}`` — the series every
        artifact (BENCH / SOAK) and the live endpoint derive from, so
        they can never disagree."""
        p50, p95, p99 = (self.percentile(q) for q in (50, 95, 99))
        with self._lock:
            return {
                "count": self._count,
                "sum": round(self._sum, 6),
                "min": round(self._min, 6) if self._min is not None else 0.0,
                "max": round(self._max, 6) if self._max is not None else 0.0,
                "p50": round(p50, 6),
                "p95": round(p95, 6),
                "p99": round(p99, 6),
            }

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """``[(le, cumulative_count), ...]`` ending with ``(inf, n)`` —
        the Prometheus ``_bucket`` series."""
        with self._lock:
            out = []
            cumulative = 0
            for bound, c in zip(self.buckets, self._counts):
                cumulative += c
                out.append((bound, cumulative))
            out.append((float("inf"), cumulative + self._counts[-1]))
            return out


@dataclass
class LatencyTimer:
    """Running latency stats (count / mean / max, EMA of recent).

    Thread-safe like :class:`Counter`: fetch/commit timers are observed
    concurrently from the auto_fetch loop, the console, and web
    handlers — unsynchronized read-modify-writes would lose samples and
    desynchronize ``total_s`` from ``n``."""

    n: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    ema_s: Optional[float] = None
    ema_alpha: float = 0.1
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.n += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)
            self.ema_s = (
                seconds
                if self.ema_s is None
                else self.ema_alpha * seconds + (1 - self.ema_alpha) * self.ema_s
            )

    @property
    def mean_s(self) -> float:
        with self._lock:
            return self.total_s / self.n if self.n else 0.0

    @contextlib.contextmanager
    def time(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)


def _series_key(name: str, labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def _prom_name(name: str) -> str:
    """Sanitize to the Prometheus metric-name charset, ``svoc_``-prefixed."""
    safe = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    if safe and safe[0].isdigit():
        safe = "_" + safe
    return safe if safe.startswith("svoc_") else "svoc_" + safe


@dataclass(frozen=True)
class SpanRecord:
    """One completed span — what the ring buffer and JSONL trace hold.

    ``lineage`` is the block-lineage id (``svoc_tpu.utils.events``)
    this span belongs to — set explicitly or inherited from the
    enclosing span, so every stage of one fetched block is joinable
    into its audit record."""

    name: str
    start_s: float  # epoch seconds (wall clock, for cross-process merge)
    duration_s: float
    span_id: int
    parent_id: Optional[int]
    thread: str
    depth: int
    lineage: Optional[str] = None

    def to_json(self) -> str:
        payload = {
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "duration_s": round(self.duration_s, 6),
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "thread": self.thread,
            "depth": self.depth,
        }
        if self.lineage is not None:
            payload["lineage"] = self.lineage
        return json.dumps(payload)


class Tracer:
    """Nestable spans: a thread-local stack, a bounded ring buffer, and
    optional JSONL export.

    ``with tracer.span("tokenize"):`` times the enclosed host-side work
    (around dispatch — never inside traced/jitted code, never forcing a
    device sync).  On completion the span:

    - appends a :class:`SpanRecord` to a bounded ring (``capacity``
      newest spans, O(1) memory forever),
    - feeds the shared per-stage histogram
      (``stage_seconds{stage=<name>}``) in the attached registry, so
      scraped percentiles and traces are the same data,
    - when ``SVOC_TRACE_FILE`` is set (or :meth:`set_trace_file` was
      called), appends one JSON line to that file.

    Nesting is tracked per thread: a ``forward`` span opened inside a
    ``fetch`` span records ``fetch``'s id as its parent, so the JSONL
    reconstructs the stage tree.  Lineage propagates the same way: a
    child span with no explicit ``lineage=`` inherits the enclosing
    span's (set via ``span(..., lineage=)`` or
    :meth:`annotate_lineage`), so every stage of one fetched block
    carries the block's id without any per-callsite plumbing.
    Thread-safe; span bodies of different threads interleave freely
    (lineage does NOT cross threads — producer threads pass it
    explicitly, e.g. ``PrefetchPipeline(lineage=...)``).

    JSONL export shares the size-capped rotating writer of
    :mod:`svoc_tpu.utils.events` (``SVOC_TRACE_MAX_BYTES`` /
    ``SVOC_TRACE_KEEP``), so spans and events land in one bounded
    flight-recorder file.  Write failures are SURFACED — the
    ``trace_write_errors`` counter plus a one-shot
    ``trace.write_error`` journal event — never silently dropped.
    """

    #: Env var consulted (per completion, so tests can monkeypatch it
    #: after import) when no explicit trace file was configured.
    TRACE_ENV = "SVOC_TRACE_FILE"

    def __init__(self, registry: "MetricsRegistry" = None, capacity: int = 4096):
        self._registry = registry
        self._ring: deque = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._lock = threading.Lock()  # ring + error latch
        self._trace_path: Optional[str] = None
        self._trace_error = False

    # -- configuration ------------------------------------------------------

    def set_trace_file(self, path: Optional[str]) -> None:
        """Pin (or clear, with None) the JSONL destination, overriding
        the env var.  The file opens lazily on the first completed span
        and appends — a long session's traces survive restarts.
        Clears the write-error latch so a repaired path resumes export,
        and releases the previous destination's pooled file handle."""
        with self._lock:
            old = self._resolve_path()
            self._trace_path = path
            self._trace_error = False
        if old and old != path:
            from svoc_tpu.utils.events import release_writer

            release_writer(old)

    def _resolve_path(self) -> Optional[str]:
        return self._trace_path or os.environ.get(self.TRACE_ENV) or None

    # -- the span API -------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextlib.contextmanager
    def span(self, name: str, lineage: Optional[str] = None) -> Iterator[int]:
        """Time a host-side stage; yields the span id (for tests/tools).
        ``lineage=None`` inherits the enclosing span's lineage."""
        stack = self._stack()
        span_id = next(self._ids)
        parent = stack[-1][0] if stack else None
        if lineage is None and stack:
            lineage = stack[-1][1]
        entry = [span_id, lineage]
        stack.append(entry)
        start_wall = time.time()
        t0 = time.perf_counter()
        try:
            yield span_id
        finally:
            duration = time.perf_counter() - t0
            stack.pop()
            self._complete(
                SpanRecord(
                    name=name,
                    start_s=start_wall,
                    duration_s=duration,
                    span_id=span_id,
                    parent_id=parent,
                    thread=threading.current_thread().name,
                    depth=len(stack),
                    lineage=entry[1],
                )
            )

    def annotate_lineage(self, lineage: Optional[str]) -> bool:
        """Attach a lineage id to the CURRENT thread's innermost open
        span (and, through inheritance, every child opened after this
        call).  Used where the id is only minted inside the span — e.g.
        ``Session.fetch`` claims its window cursor after opening the
        ``fetch`` span.  Returns False when no span is open."""
        stack = self._stack()
        if not stack:
            return False
        stack[-1][1] = lineage
        return True

    def current_lineage(self) -> Optional[str]:
        """The innermost open span's effective lineage on this thread."""
        stack = self._stack()
        return stack[-1][1] if stack else None

    def _complete(self, record: SpanRecord) -> None:
        if self._registry is not None:
            self._registry.histogram(
                "stage_seconds", labels={"stage": record.name}
            ).observe(record.duration_s)
        path = self._resolve_path()
        with self._lock:
            self._ring.append(record)
            if path is None:
                self._trace_error = False
                return
            if self._trace_error:
                return
        try:
            # Shared size-capped writer (svoc_tpu.utils.events): spans
            # and events rotate as one flight-recorder file.  Imported
            # lazily — events.py imports this module at load time.
            from svoc_tpu.utils.events import shared_writer

            shared_writer(path).write_line(record.to_json())
        except (OSError, ValueError) as e:
            # A bad path must never take down the pipeline — but it
            # must not VANISH either (satellite fix): latch export off
            # (until reconfigured), count every latch, and emit one
            # warning event so the journal records why the trace went
            # quiet.
            with self._lock:
                self._trace_error = True
            reg = self._registry or registry
            reg.counter("trace_write_errors").add(1)
            try:
                from svoc_tpu.utils import events as _events

                _events.journal.emit(
                    "trace.write_error", path=path, error=repr(e)
                )
            except Exception:  # svoclint: disable=SVOC014 -- deliberate: recursion guard — the write-error EVENT failing to journal must not re-enter the journal; the latch + trace_write_errors counter above already made the failure visible
                pass  # the journal's own export failing must not recurse

    def recent(self, n: Optional[int] = None) -> List[SpanRecord]:
        """The newest ``n`` spans (all buffered when ``n`` is None)."""
        with self._lock:
            spans = list(self._ring)
        return spans if n is None else spans[-n:]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def flush(self) -> None:
        """Flush the shared JSONL writer so every line is durable."""
        path = self._resolve_path()
        if path is None:
            return
        from svoc_tpu.utils.events import shared_writer

        shared_writer(path).flush()


class MetricsRegistry:
    """Named counters/timers/histograms/gauges + reporting/exposition."""

    def __init__(self) -> None:
        self.counters: Dict[str, Counter] = {}
        self.timers: Dict[str, LatencyTimer] = {}
        self.histograms: Dict[str, Histogram] = {}
        self.gauges: Dict[str, Gauge] = {}
        # setdefault on a plain dict is atomic under the GIL, but the
        # constructed-then-discarded loser of a race would drop the
        # winner's concurrent observations on Histogram (its buckets
        # allocate state) — create-once under a lock instead.
        self._lock = threading.Lock()
        #: Per-series labels, keyed like the metric dicts — used by the
        #: Prometheus renderer to group families.
        self._labels: Dict[str, Tuple[str, Dict[str, str]]] = {}

    def _get(self, store: Dict, name: str, labels, factory):
        key = _series_key(name, labels)
        obj = store.get(key)
        if obj is None:
            with self._lock:
                obj = store.get(key)
                if obj is None:
                    obj = store[key] = factory()
                    self._labels[key] = (name, dict(labels or {}))
        return obj

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(self.counters, name, labels, Counter)

    def timer(self, name: str, labels: Optional[Dict[str, str]] = None) -> LatencyTimer:
        return self._get(self.timers, name, labels, LatencyTimer)

    def histogram(
        self,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        buckets: Optional[Tuple[float, ...]] = None,
    ) -> Histogram:
        return self._get(
            self.histograms, name, labels, lambda: Histogram(buckets)
        )

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(self.gauges, name, labels, Gauge)

    def stage_histogram(self, stage: str) -> Histogram:
        """The shared per-stage series every span feeds."""
        return self.histogram("stage_seconds", labels={"stage": stage})

    def family_total(self, name: str) -> float:
        """Sum of every counter series in the family ``name``, labels
        folded — e.g. ``family_total("faults_injected")`` totals the
        per-kind chaos counters for soak artifacts."""
        # Snapshot under the registry lock: other threads INSERT new
        # labeled series under it (first retry, first injected fault),
        # and iterating the live dict would race those inserts.
        with self._lock:
            items = list(self.counters.items())
        total = 0.0
        for key, c in items:
            fam, _labels = self._labels.get(key, (key, {}))
            if fam == name:
                total += c.count
        return total

    def family_series(self, name: str) -> List[Tuple[Dict[str, str], float]]:
        """Every counter series in the family ``name`` as
        ``(labels, count)`` pairs — the per-label breakdown
        ``family_total`` folds away (serving artifacts report shed
        counts per reason)."""
        with self._lock:
            items = list(self.counters.items())
        out: List[Tuple[Dict[str, str], float]] = []
        for key, c in items:
            fam, labels = self._labels.get(key, (key, {}))
            if fam == name:
                out.append((dict(labels), c.count))
        return out

    def counters_snapshot(self) -> List[Dict[str, Any]]:
        """Every counter series as ``{name, labels, count}`` — the
        recovery snapshot's SLO-continuity section (docs/RESILIENCE.md
        §durability): burn-rate evaluators difference CUMULATIVE
        counters, so a restart that zeroed them would read an error
        burst as recovery (goods jump from 0) or vice versa."""
        with self._lock:
            items = list(self.counters.items())
            labels = dict(self._labels)
        out: List[Dict[str, Any]] = []
        for key, c in items:
            name, lbl = labels.get(key, (key, {}))
            out.append({"name": name, "labels": dict(lbl), "count": c.count})
        return out

    def restore_counters(self, entries: List[Dict[str, Any]]) -> int:
        """Re-seed counter series from :meth:`counters_snapshot` (adds
        onto current values — callers restore into a FRESH registry).
        Returns the number of restored series."""
        n = 0
        for e in entries:
            value = float(e.get("count", 0.0))
            if value:
                self.counter(e["name"], labels=e.get("labels") or None).add(
                    value
                )
                n += 1
        return n

    def stage_snapshot(self) -> Dict[str, Dict[str, float]]:
        """``{stage: {count, sum, p50, p95, p99, ...}}`` for every stage
        observed so far — the block BENCH/SOAK artifacts embed."""
        out = {}
        for key, h in sorted(self.histograms.items()):
            name, labels = self._labels.get(key, (key, {}))
            if name == "stage_seconds" and "stage" in labels:
                out[labels["stage"]] = h.snapshot()
        return out

    def report(self) -> List[str]:
        lines = []
        for key, c in sorted(self.counters.items()):
            lines.append(f"{key}: {c.count:,.0f} ({c.rate():,.1f}/s recent)")
        for key, g in sorted(self.gauges.items()):
            lines.append(f"{key}: {g.get():,.6g}")
        for key, t in sorted(self.timers.items()):
            lines.append(
                f"{key}: n={t.n} mean={t.mean_s * 1e3:.2f}ms "
                f"max={t.max_s * 1e3:.2f}ms"
            )
        for key, h in sorted(self.histograms.items()):
            s = h.snapshot()
            lines.append(
                f"{key}: n={s['count']} p50={s['p50'] * 1e3:.2f}ms "
                f"p95={s['p95'] * 1e3:.2f}ms p99={s['p99'] * 1e3:.2f}ms "
                f"max={s['max'] * 1e3:.2f}ms"
            )
        return lines

    def render_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every series.

        Families emit one ``# TYPE`` line; histogram families emit the
        classic cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count``
        triple (p50/p95/p99 derivable server-side via
        ``histogram_quantile``); timers render as summary-style
        ``_count`` / ``_sum`` plus a ``_max`` gauge.
        """
        lines: List[str] = []
        typed: set = set()

        def labels_of(key: str) -> Tuple[str, Dict[str, str]]:
            return self._labels.get(key, (key, {}))

        def fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
            inner = ",".join(
                f'{k}="{v}"' for k, v in sorted(labels.items())
            )
            if extra:
                inner = f"{inner},{extra}" if inner else extra
            return "{" + inner + "}" if inner else ""

        def type_line(prom: str, kind: str) -> None:
            if prom not in typed:
                typed.add(prom)
                lines.append(f"# TYPE {prom} {kind}")

        for key, c in sorted(self.counters.items()):
            name, labels = labels_of(key)
            prom = _prom_name(name + "_total")
            type_line(prom, "counter")
            lines.append(f"{prom}{fmt_labels(labels)} {c.count:g}")
        for key, g in sorted(self.gauges.items()):
            name, labels = labels_of(key)
            prom = _prom_name(name)
            type_line(prom, "gauge")
            lines.append(f"{prom}{fmt_labels(labels)} {g.get():g}")
        for key, t in sorted(self.timers.items()):
            name, labels = labels_of(key)
            prom = _prom_name(name + "_seconds")
            type_line(prom, "summary")
            lab = fmt_labels(labels)
            lines.append(f"{prom}_count{lab} {t.n}")
            lines.append(f"{prom}_sum{lab} {t.total_s:g}")
            prom_max = _prom_name(name + "_seconds_max")
            type_line(prom_max, "gauge")
            lines.append(f"{prom_max}{lab} {t.max_s:g}")
        for key, h in sorted(self.histograms.items()):
            name, labels = labels_of(key)
            prom = _prom_name(name)
            type_line(prom, "histogram")
            for bound, cumulative in h.cumulative_buckets():
                le = "+Inf" if bound == float("inf") else f"{bound:g}"
                le_label = 'le="' + le + '"'
                lines.append(
                    f"{prom}_bucket{fmt_labels(labels, le_label)} {cumulative}"
                )
            lab = fmt_labels(labels)
            lines.append(f"{prom}_sum{lab} {h.sum:g}")
            lines.append(f"{prom}_count{lab} {h.count}")
        return "\n".join(lines) + "\n"


#: Process-wide default registry (the apps layer and bench use this).
registry = MetricsRegistry()

#: Process-wide default tracer, feeding the default registry's
#: per-stage histograms.
tracer = Tracer(registry)


def stage_span(name: str, lineage: Optional[str] = None):
    """``with stage_span("forward"):`` — the one-liner every hot-path
    callsite uses: a span on the default tracer, feeding the shared
    ``stage_seconds{stage=name}`` histogram in the default registry.
    ``lineage=None`` inherits the enclosing span's block lineage."""
    return tracer.span(name, lineage=lineage)


# --------------------------------------------------------------------------
# Device / runtime gauges (sampled on demand — never on a hot path)
# --------------------------------------------------------------------------

_monitoring_listener_state = {"installed": False}
_monitoring_lock = threading.Lock()


def install_compile_listener() -> bool:
    """Observe XLA compilation via ``jax.monitoring`` when available.

    Installs (once per process) two listeners feeding the process-wide
    default :data:`registry` — compiles are process-global events, and
    a listener bound to whichever registry happened to call first would
    silently starve every other scrape:

    - a duration listener: every ``*compile*`` monitoring event keeps
      bumping the legacy ``jit_compiles`` / ``jit_compile_seconds``
      counters (PR 1's coarse series — it counts trace and MLIR stages
      too), and the **backend** compile events additionally land in a
      real ``xla_compile_seconds`` histogram (p50/p95/p99 of actual
      XLA compile wall time) plus the ``xla_compiles_total`` counter —
      the compile plane's primary series (docs/PARALLELISM.md
      §compile-plane);
    - a plain-event listener: the persistent compilation cache's
      ``cache_hits`` / ``cache_misses`` events count into
      ``xla_cache_events{event=hit|miss}`` — a MISS is a fresh compile
      paid this process, which is exactly what ``make coldstart-smoke``
      asserts to be zero after a warm restart.

    Returns True iff the listeners are installed; any API drift in this
    private-ish surface degrades to a benign False — compile series
    simply stay absent.
    """
    with _monitoring_lock:
        if _monitoring_listener_state["installed"]:
            return True
        try:
            from jax import monitoring as _monitoring

            # Resolve BOTH registration surfaces before calling either:
            # a partial registration (duration listener in, event
            # listener AttributeError) would return False without
            # marking installed, and the next call would stack a second
            # duration listener — every compile double-counted, worse
            # each scrape.
            register_duration = (
                _monitoring.register_event_duration_secs_listener
            )
            register_event = _monitoring.register_event_listener

            def _on_duration(event: str, duration: float, **kwargs) -> None:
                if "compile" in event:
                    registry.counter("jit_compiles").add(1)
                    registry.counter("jit_compile_seconds").add(duration)
                if "backend_compile" in event:
                    registry.counter("xla_compiles_total").add(1)
                    registry.histogram("xla_compile_seconds").observe(
                        max(0.0, duration)
                    )

            def _on_event(event: str, **kwargs) -> None:
                if event.endswith("compilation_cache/cache_hits"):
                    registry.counter(
                        "xla_cache_events", labels={"event": "hit"}
                    ).add(1)
                elif event.endswith("compilation_cache/cache_misses"):
                    registry.counter(
                        "xla_cache_events", labels={"event": "miss"}
                    ).add(1)

            register_duration(_on_duration)
            # The duration listener is LIVE from here: mark installed
            # immediately so no failure below can ever stack a second
            # one, and swallow ANY register_event failure — whatever a
            # drifted jax.monitoring raises, the degradation is absent
            # cache-event series, never a crashed caller or a False
            # that contradicts the live duration listener.
            _monitoring_listener_state["installed"] = True
            try:
                register_event(_on_event)
            except Exception:  # noqa: BLE001 — see above
                pass
        except (ImportError, AttributeError, TypeError):
            return False
        return True


def compile_snapshot(reg: Optional["MetricsRegistry"] = None) -> Dict[str, float]:
    """JSON-safe digest of the compile-plane series (soak snapshots,
    bench ``detail``, the durability status panel).  Reads the DEFAULT
    registry by default — that is where :func:`install_compile_listener`
    lands process-global events regardless of which registry a seeded
    run injected."""
    reg = reg or registry
    h = reg.histogram("xla_compile_seconds")
    return {
        "xla_compiles_total": reg.counter("xla_compiles_total").count,
        "xla_compile_seconds_sum": round(h.sum, 6),
        "xla_compile_p50_ms": round(h.percentile(50) * 1e3, 3),
        "xla_compile_p99_ms": round(h.percentile(99) * 1e3, 3),
        "cache_hits": reg.counter(
            "xla_cache_events", labels={"event": "hit"}
        ).count,
        "cache_misses": reg.counter(
            "xla_cache_events", labels={"event": "miss"}
        ).count,
        "prewarm_outcomes": {
            "compiled": reg.counter(
                "compile_prewarm", labels={"outcome": "compiled"}
            ).count,
            "primed": reg.counter(
                "compile_prewarm", labels={"outcome": "primed"}
            ).count,
            "skipped": reg.counter(
                "compile_prewarm", labels={"outcome": "skipped"}
            ).count,
            "error": reg.counter(
                "compile_prewarm", labels={"outcome": "error"}
            ).count,
            "budget_exhausted": reg.counter(
                "compile_prewarm", labels={"outcome": "budget_exhausted"}
            ).count,
        },
    }


def _backend_initialized() -> bool:
    """True iff an XLA backend is already live — the same probe
    ``parallel/mesh.py`` uses, so sampling gauges from a device-free
    session (lazy-key design, ``apps/session.py``) never forces a
    backend bring-up just to serve ``/metrics``."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except (ImportError, AttributeError):
        # Probe unavailable (API drift): assume initialized only when
        # jax itself is already imported — the conservative reading.
        import sys

        return "jax" in sys.modules


def sample_runtime_gauges(reg: Optional[MetricsRegistry] = None) -> Dict[str, float]:
    """Refresh device/runtime gauges; returns ``{series_key: value}``.

    Samples ``jax.live_arrays()`` bytes per device into
    ``device_live_bytes{device=...}`` (plus array count), and installs
    the compile-count listener.  On-demand only (the ``/metrics``
    handler, the ``metrics`` command) — never called from the serving
    hot path, and a no-op before the first device touch.
    """
    reg = reg or registry
    out: Dict[str, float] = {}
    install_compile_listener()
    if not _backend_initialized():
        return out
    try:
        import jax

        per_device: Dict[str, float] = {}
        n_arrays = 0
        for arr in jax.live_arrays():
            n_arrays += 1
            shards = getattr(arr, "addressable_shards", None) or []
            for shard in shards:
                dev = str(shard.device)
                data = getattr(shard, "data", None)
                per_device[dev] = per_device.get(dev, 0.0) + float(
                    getattr(data, "nbytes", 0) or 0
                )
        for dev, nbytes in per_device.items():
            g = reg.gauge("device_live_bytes", labels={"device": dev})
            g.set(nbytes)
            out[_series_key("device_live_bytes", {"device": dev})] = nbytes
        # A device whose arrays were ALL freed produces no entry above —
        # zero its existing gauge, or the scrape reports the last-seen
        # bytes forever (phantom leak, contradicting device_live_arrays).
        sampled = {
            _series_key("device_live_bytes", {"device": dev})
            for dev in per_device
        }
        for key in list(reg.gauges):
            name, _labels = reg._labels.get(key, (key, {}))
            if name == "device_live_bytes" and key not in sampled:
                reg.gauges[key].set(0.0)
                out[key] = 0.0
        reg.gauge("device_live_arrays").set(n_arrays)
        out["device_live_arrays"] = float(n_arrays)
    except Exception:
        # Gauge sampling must never take down the caller: a backend in
        # a weird state (mid-teardown, tunneled) just yields no gauges.
        return out
    return out


def set_mfu_gauge(
    step_seconds: float,
    flops_per_step: float,
    peak_flops: Optional[float],
    reg: Optional[MetricsRegistry] = None,
) -> Optional[float]:
    """Step-time-derived MFU gauge, reusing bench.py's FLOP model: the
    caller passes ``flops_per_step`` from
    ``bench.encoder_matmul_flops_per_token × tokens`` and the assumed
    chip peak (``bench.assumed_peak_flops``).  Returns the MFU (None
    when the peak is unknown, e.g. CPU)."""
    reg = reg or registry
    if not peak_flops or step_seconds <= 0:
        return None
    mfu = flops_per_step / step_seconds / peak_flops
    reg.gauge("mfu_estimate").set(mfu)
    reg.gauge("step_seconds").set(step_seconds)
    return mfu


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """``jax.profiler`` trace around a block; view with TensorBoard."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
