"""Throughput / latency metrics and profiler hooks.

The reference's only instrumentation is Cairo gas budgets and print
statements (SURVEY.md §5); the framework's north-star metric is
end-to-end comments/sec and consensus-update latency, so those get
first-class counters here, used by ``bench.py`` and the apps loop.

``jax.profiler`` tracing is wrapped so a session can be profiled with
one flag and inspected in TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional


@dataclass
class Counter:
    """A monotone event counter with rate reporting."""

    count: float = 0.0
    started_at: float = field(default_factory=time.perf_counter)

    def add(self, n: float = 1.0) -> None:
        self.count += n

    def rate(self) -> float:
        elapsed = time.perf_counter() - self.started_at
        return self.count / elapsed if elapsed > 0 else 0.0

    def reset(self) -> None:
        self.count = 0.0
        self.started_at = time.perf_counter()


@dataclass
class LatencyTimer:
    """Running latency stats (count / mean / max, EMA of recent)."""

    n: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    ema_s: Optional[float] = None
    ema_alpha: float = 0.1

    def observe(self, seconds: float) -> None:
        self.n += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)
        self.ema_s = (
            seconds
            if self.ema_s is None
            else self.ema_alpha * seconds + (1 - self.ema_alpha) * self.ema_s
        )

    @property
    def mean_s(self) -> float:
        return self.total_s / self.n if self.n else 0.0

    @contextlib.contextmanager
    def time(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)


class MetricsRegistry:
    """Named counters/timers + one-line reporting."""

    def __init__(self) -> None:
        self.counters: Dict[str, Counter] = {}
        self.timers: Dict[str, LatencyTimer] = {}

    def counter(self, name: str) -> Counter:
        return self.counters.setdefault(name, Counter())

    def timer(self, name: str) -> LatencyTimer:
        return self.timers.setdefault(name, LatencyTimer())

    def report(self) -> List[str]:
        lines = []
        for name, c in sorted(self.counters.items()):
            lines.append(f"{name}: {c.count:,.0f} ({c.rate():,.1f}/s)")
        for name, t in sorted(self.timers.items()):
            lines.append(
                f"{name}: n={t.n} mean={t.mean_s * 1e3:.2f}ms "
                f"max={t.max_s * 1e3:.2f}ms"
            )
        return lines


#: Process-wide default registry (the apps layer and bench use this).
registry = MetricsRegistry()


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """``jax.profiler`` trace around a block; view with TensorBoard."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
