"""Throughput / latency metrics and profiler hooks.

The reference's only instrumentation is Cairo gas budgets and print
statements (SURVEY.md §5); the framework's north-star metric is
end-to-end comments/sec and consensus-update latency, so those get
first-class counters here, used by ``bench.py`` and the apps loop.

``jax.profiler`` tracing is wrapped so a session can be profiled with
one flag and inspected in TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional


@dataclass
class Counter:
    """A monotone event counter with windowed rate reporting.

    ``rate()`` covers only the trailing ``window_s`` seconds, so the
    ``metrics`` command reports *recent* throughput — a lifetime
    average would decay forever after any idle period.
    ``lifetime_rate()`` keeps the old semantics explicitly.
    """

    count: float = 0.0
    window_s: float = 30.0
    started_at: float = field(default_factory=time.perf_counter)
    _events: deque = field(default_factory=deque)  # (timestamp, count_after)
    # add() runs on the auto_fetch daemon thread while rate() serves the
    # web/console thread — guard the deque walk.
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def add(self, n: float = 1.0) -> None:
        now = time.perf_counter()
        with self._lock:
            self.count += n
            self._events.append((now, self.count))
            self._prune(now)

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def rate(self) -> float:
        """Events/sec over the trailing window (0 when idle)."""
        now = time.perf_counter()
        with self._lock:
            self._prune(now)
            if not self._events:
                return 0.0
            t_oldest, c_oldest = self._events[0]
            span = now - t_oldest
            if span <= 0:
                return 0.0
            # Count since the window's first sample (whose own
            # increment belongs to the time before it).
            return (self.count - c_oldest) / span

    def lifetime_rate(self) -> float:
        elapsed = time.perf_counter() - self.started_at
        return self.count / elapsed if elapsed > 0 else 0.0

    def reset(self) -> None:
        with self._lock:
            self.count = 0.0
            self.started_at = time.perf_counter()
            self._events.clear()


@dataclass
class LatencyTimer:
    """Running latency stats (count / mean / max, EMA of recent).

    Thread-safe like :class:`Counter`: fetch/commit timers are observed
    concurrently from the auto_fetch loop, the console, and web
    handlers — unsynchronized read-modify-writes would lose samples and
    desynchronize ``total_s`` from ``n``."""

    n: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    ema_s: Optional[float] = None
    ema_alpha: float = 0.1
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.n += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)
            self.ema_s = (
                seconds
                if self.ema_s is None
                else self.ema_alpha * seconds + (1 - self.ema_alpha) * self.ema_s
            )

    @property
    def mean_s(self) -> float:
        with self._lock:
            return self.total_s / self.n if self.n else 0.0

    @contextlib.contextmanager
    def time(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)


class MetricsRegistry:
    """Named counters/timers + one-line reporting."""

    def __init__(self) -> None:
        self.counters: Dict[str, Counter] = {}
        self.timers: Dict[str, LatencyTimer] = {}

    def counter(self, name: str) -> Counter:
        return self.counters.setdefault(name, Counter())

    def timer(self, name: str) -> LatencyTimer:
        return self.timers.setdefault(name, LatencyTimer())

    def report(self) -> List[str]:
        lines = []
        for name, c in sorted(self.counters.items()):
            lines.append(f"{name}: {c.count:,.0f} ({c.rate():,.1f}/s recent)")
        for name, t in sorted(self.timers.items()):
            lines.append(
                f"{name}: n={t.n} mean={t.mean_s * 1e3:.2f}ms "
                f"max={t.max_s * 1e3:.2f}ms"
            )
        return lines


#: Process-wide default registry (the apps layer and bench use this).
registry = MetricsRegistry()


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """``jax.profiler`` trace around a block; view with TensorBoard."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
