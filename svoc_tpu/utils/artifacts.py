"""Atomic, durable JSON artifact writes — the one tmp+replace helper.

Every smoke/bench/campaign tool publishes its artifact the same way:
``json.dump`` to ``<path>.tmp`` then ``os.replace`` so a concurrent
reader (the driver, ``hw_watch``, a human ``cat``) never sees a torn
file.  Twelve hand-rolled copies of that pattern all skipped the
durability half — no fsync of the data, no fsync of the directory —
which svoclint SVOC012 now flags: after a crash the rename can
resurrect the pre-rename layout, and a resumable journal like
``HW_CAMPAIGN.json`` (whose whole point is surviving interruption)
could roll back to a state older than work already done.

:func:`atomic_write_json` is the shared replacement: tmp write →
flush → ``os.fsync`` → ``os.replace`` → ``fsync_dir`` — the same
ordering as ``utils/checkpoint.save_snapshot``, minus the snapshot
codec.  Costs one fdatasync per artifact publication (microseconds to
low milliseconds, on paths that write at most once per smoke run or
campaign flush — never on a serving hot path).
"""

from __future__ import annotations

import json
import os
from typing import Any

from svoc_tpu.utils.events import fsync_dir


def atomic_write_json(path: str, payload: Any, indent: int = 1) -> None:
    """Write ``payload`` as JSON at ``path``: whole-or-absent (tmp +
    rename) AND durable (file fsync before the rename, directory fsync
    after it, so a crash can neither tear the file nor resurrect the
    previous one)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=indent)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(path)
