"""Debug-bundle assembly and the auto-trigger monitor.

When something breaks at 2 a.m. the evidence is spread over four
subsystems — the event journal, the span ring, the metrics registry,
and the session's resilience/robustness snapshots — and most of it
lives in bounded rings that the NEXT hour of traffic will overwrite.
A **postmortem bundle** freezes all of it into one atomically-written
JSON file at the moment of the incident:

- the journal tail (typed events, newest last), its counts-by-type and
  fingerprint,
- the span ring tail (with lineage ids, joinable against the events),
- the metrics registry (counters, gauges, per-stage percentiles),
- the SLO evaluator's burn-rate snapshot (when wired),
- the session's resilience snapshot + configuration,
- the relevant environment (``SVOC_*`` / ``JAX_*`` / ``XLA_*``).

:func:`build_bundle` assembles one on demand (the ``tools/postmortem``
CLI, tests, soak teardown); :class:`PostmortemMonitor` subscribes to a
journal and builds one automatically on incident-class events —
breaker-open transitions, quarantine spikes, ``interval_valid=False``
consensus results, producer crashes — rate-limited and bounded so an
incident storm produces a handful of bundles, not a disk full.

Writes are atomic (tmp + ``os.replace``): a bundle either exists whole
or not at all — half a postmortem is worse than none.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from svoc_tpu.utils.artifacts import atomic_write_json
from svoc_tpu.utils.events import EventJournal, EventRecord
from svoc_tpu.utils.events import journal as _default_journal
from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry
from svoc_tpu.utils.metrics import tracer as _default_tracer

BUNDLE_FORMAT = "svoc-postmortem-v1"

#: Keys a complete bundle must carry (``make obs-smoke`` asserts them).
BUNDLE_KEYS = (
    "format",
    "built_at",
    "trigger",
    "journal",
    "spans",
    "metrics",
    "slo",
    "resilience",
    "config",
    "env",
)

class SignalChain:
    """Install one callback on a set of signals, CHAINING whatever was
    there before — the one implementation of the prev-handler dance
    shared by :class:`PostmortemMonitor` and
    :class:`svoc_tpu.durability.recovery.GracefulDrain`:

    - a callable previous handler runs after the callback;
    - ``SIG_IGN`` stays ignored (the callback runs, but an ignored
      signal is never converted into process death);
    - the default disposition is restored and the signal re-delivered
      otherwise, so the process still dies with the conventional exit
      status.

    Install failures (non-main thread, unsupported platform) are
    skipped silently — hooks are best-effort by design.
    """

    def __init__(self, callback: Callable[[int, Any], None]):
        self._callback = callback
        self._prev: Dict[int, Any] = {}

    def install(self, signals) -> None:
        import signal as _signal

        for sig in signals:
            try:
                prev = _signal.signal(sig, self._on_signal)
            except (ValueError, OSError):
                continue
            self._prev[sig] = prev

    def uninstall(self) -> None:
        import signal as _signal

        for sig, prev in self._prev.items():
            try:
                _signal.signal(
                    sig, prev if prev is not None else _signal.SIG_DFL
                )
            except (ValueError, OSError):
                pass
        self._prev.clear()

    def _on_signal(self, signum, frame) -> None:
        import signal as _signal

        self._callback(signum, frame)
        prev = self._prev.get(signum)
        if prev is _signal.SIG_IGN:
            return
        if callable(prev):
            prev(signum, frame)
        else:
            _signal.signal(signum, _signal.SIG_DFL)
            os.kill(os.getpid(), signum)


_bundle_counter = iter(range(1, 10**9))
_bundle_counter_lock = threading.Lock()


def _next_bundle_id() -> int:
    with _bundle_counter_lock:
        return next(_bundle_counter)


def _config_dict(config: Any) -> Optional[Dict[str, Any]]:
    if config is None:
        return None
    if dataclasses.is_dataclass(config):
        from svoc_tpu.utils.events import _json_safe

        return _json_safe(dataclasses.asdict(config))
    return {"repr": repr(config)}


def build_bundle(
    path: Optional[str] = None,
    *,
    out_dir: str = ".",
    trigger: str = "manual",
    trigger_event: Optional[Dict[str, Any]] = None,
    session=None,
    registry: Optional[MetricsRegistry] = None,
    tracer=None,
    journal: Optional[EventJournal] = None,
    slo=None,
    events_tail: int = 512,
    spans_tail: int = 256,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Assemble and atomically write one bundle; returns its path.

    Everything defaults to the process-wide singletons; pass a
    ``session`` to include its resilience snapshot and configuration,
    and an ``slo`` evaluator to freeze the burn rates.
    """
    reg = registry or _default_registry
    t = tracer if tracer is not None else _default_tracer
    j = journal if journal is not None else _default_journal

    counters = {key: c.count for key, c in sorted(reg.counters.items())}
    gauges = {key: g.get() for key, g in sorted(reg.gauges.items())}
    bundle: Dict[str, Any] = {
        "format": BUNDLE_FORMAT,
        "built_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "trigger": trigger,
        "trigger_event": trigger_event,
        "journal": {
            "counts_by_type": j.counts_by_type(),
            "last_seq": j.last_seq(),
            "fingerprint": j.fingerprint(),
            "events": [e.as_dict() for e in j.recent(events_tail)],
        },
        "spans": [
            {
                "name": s.name,
                "start_s": round(s.start_s, 6),
                "duration_s": round(s.duration_s, 6),
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "thread": s.thread,
                "lineage": getattr(s, "lineage", None),
            }
            for s in t.recent(spans_tail)
        ],
        "metrics": {
            "stage_seconds": reg.stage_snapshot(),
            "counters": counters,
            "gauges": gauges,
        },
        "slo": None,
        "resilience": None,
        "config": None,
        "env": {
            k: v
            for k, v in sorted(os.environ.items())
            if k.startswith(("SVOC_", "JAX_", "XLA_"))
        },
    }
    if slo is not None:
        try:
            bundle["slo"] = slo.evaluate()
        except Exception as e:
            bundle["slo"] = {"error": repr(e)}
    if session is not None:
        try:
            bundle["resilience"] = session.resilience_snapshot()
        except Exception as e:
            bundle["resilience"] = {"error": repr(e)}
        bundle["config"] = _config_dict(getattr(session, "config", None))
    if extra:
        bundle["extra"] = extra

    if path is None:
        path = os.path.join(
            out_dir,
            f"postmortem-{trigger.replace('/', '_')}-{_next_bundle_id():03d}.json",
        )
    # Durable, not just atomic (svoclint SVOC012): a bundle exists to
    # outlive the incident — including a host that dies right after.
    atomic_write_json(path, bundle)
    return path


class PostmortemMonitor:
    """Auto-trigger: subscribe to a journal and bundle on incidents.

    Classification (docs/OBSERVABILITY.md §postmortem):

    - ``breaker.transition`` with ``to="open"`` — the chain was just
      declared down,
    - ``quarantine.verdict`` refusing ≥ ``quarantine_spike`` slots in
      one block — an upstream data incident,
    - ``consensus.result`` with ``interval_valid=False`` — the block
      could not produce a meaningful interval,
    - ``pipeline.producer_error`` — the prefetch producer crashed,
    - any ``crash`` event (emitters may report their own).

    Rate-limited (``min_interval_s`` between bundles) and bounded
    (``max_bundles`` lifetime) so an incident storm cannot fill the
    disk; every bundle built is itself journaled as
    ``postmortem.bundle`` (which the classifier ignores — no
    recursion).  Callbacks run on the EMITTING thread, so bundle
    assembly is bounded ring/registry reads only — no chain I/O.
    """

    def __init__(
        self,
        out_dir: str = ".",
        *,
        session=None,
        registry: Optional[MetricsRegistry] = None,
        tracer=None,
        journal: Optional[EventJournal] = None,
        slo=None,
        quarantine_spike: int = 3,
        min_interval_s: float = 60.0,
        max_bundles: int = 8,
        clock: Callable[[], float] = time.monotonic,
        profiler=None,
    ):
        self.out_dir = out_dir
        self._session = session
        self._registry = registry
        self._tracer = tracer
        self._journal = journal if journal is not None else _default_journal
        self._slo = slo
        self.quarantine_spike = quarantine_spike
        self.min_interval_s = min_interval_s
        self.max_bundles = max_bundles
        self._clock = clock
        #: An optional :class:`~svoc_tpu.obsplane.profiler.
        #: ProfileCapture`: incident-class events (breaker-open, SLO
        #: burn) trigger a bounded, rate-limited automatic capture —
        #: the device-side view a bundle's host rings cannot carry.
        self._profiler = profiler
        self._lock = threading.Lock()
        self._last_built: Optional[float] = None
        #: Suppression latch per reason: the counter bumps on EVERY
        #: suppressed incident, the ``postmortem.suppressed`` journal
        #: event fires ONCE per latch (cleared by the next bundle that
        #: does build) — visible without being an event storm of its
        #: own.
        self._suppressed_latched: set = set()
        #: Paths of every bundle this monitor built (soak artifacts).
        self.bundles: List[str] = []
        self._shutdown_done = False
        self._signal_chain = SignalChain(
            lambda signum, _frame: self.shutdown(f"signal_{signum}")
        )
        self._atexit_registered = False

    def install(self) -> "PostmortemMonitor":
        self._journal.subscribe(self._on_event)
        return self

    def uninstall(self) -> None:
        self._journal.unsubscribe(self._on_event)

    # -- orderly-shutdown bundles (docs/RESILIENCE.md §drain) ---------------

    def install_shutdown_hooks(self, signals=None) -> "PostmortemMonitor":
        """Register SIGTERM + atexit hooks so an ORDERLY shutdown (and
        the parent of an OOM-killed child, whose own atexit still runs)
        always leaves a final bundle.  The bundle is classified
        ``shutdown`` — not ``crash`` — and is EXEMPT from the 60 s rate
        limit and the lifetime cap: a dying process gets its last word
        even mid-incident-storm.  Chained via :class:`SignalChain`: a
        previously-installed handler still runs after the bundle is
        written, and an ignored signal stays ignored."""
        import atexit
        import signal as _signal

        if signals is None:
            signals = (_signal.SIGTERM,)
        self._signal_chain.install(signals)
        if not self._atexit_registered:
            atexit.register(self.shutdown, "atexit")
            self._atexit_registered = True
        return self

    def uninstall_shutdown_hooks(self) -> None:
        self._signal_chain.uninstall()

    def shutdown(self, reason: str = "shutdown") -> Optional[str]:
        """Build the final bundle, once (later calls — e.g. atexit
        after a SIGTERM already bundled — are no-ops)."""
        with self._lock:
            if self._shutdown_done:
                return None
            self._shutdown_done = True
        try:
            path = build_bundle(
                out_dir=self.out_dir,
                trigger="shutdown",
                trigger_event={"reason": reason},
                session=self._session,
                registry=self._registry,
                tracer=self._tracer,
                journal=self._journal,
                slo=self._slo,
            )
        except Exception:
            # A failing teardown bundle must never turn a clean
            # shutdown into a crash.
            (self._registry or _default_registry).counter(
                "postmortem_errors"
            ).add(1)
            return None
        with self._lock:
            self.bundles.append(path)
        (self._registry or _default_registry).counter(
            "postmortem_bundles", labels={"trigger": "shutdown"}
        ).add(1)
        self._journal.emit(
            "postmortem.bundle", trigger="shutdown", reason=reason, path=path
        )
        return path

    def classify(self, record: EventRecord) -> Optional[str]:
        """The trigger name for an incident-class event, else None."""
        if record.type == "breaker.transition" and record.data.get("to") == "open":
            return "breaker_open"
        if record.type == "quarantine.verdict":
            refused = int(record.data.get("total", 0) or 0) - int(
                record.data.get("admitted", 0) or 0
            )
            if refused >= self.quarantine_spike:
                return "quarantine_spike"
        if (
            record.type == "consensus.result"
            and record.data.get("interval_valid") is False
        ):
            return "interval_invalid"
        if record.type == "pipeline.producer_error":
            return "producer_error"
        if record.type == "crash":
            return "crash"
        return None

    def _on_event(self, record: EventRecord) -> None:
        trigger = self.classify(record)
        if self._profiler is not None and (
            trigger == "breaker_open" or record.type == "slo.alert"
        ):
            # Incident-triggered device capture (docs/OBSERVABILITY.md
            # §cost-attribution): bounded duration + its own rate limit
            # live in the profiler; a capture failure lands in
            # profile_errors and never blocks the bundle below.
            self._profiler.maybe_capture(
                "slo_burn" if record.type == "slo.alert" else trigger
            )
        if trigger is None:
            return
        now = self._clock()
        suppressed: Optional[str] = None
        first_latch = False
        with self._lock:
            if len(self.bundles) >= self.max_bundles:
                suppressed = "cap"
            elif (
                self._last_built is not None
                and now - self._last_built < self.min_interval_s
            ):
                suppressed = "rate_limit"
            else:
                self._last_built = now
            if suppressed is not None:
                first_latch = suppressed not in self._suppressed_latched
                self._suppressed_latched.add(suppressed)
        if suppressed is not None:
            # Visible suppression (the satellite contract): every
            # suppressed incident counts; the journal sees ONE latch
            # event per reason, emitted outside the monitor lock
            # (journal lock is a leaf — SVOC010).  classify() has no
            # rule for postmortem.suppressed, so no recursion.
            (self._registry or _default_registry).counter(
                "postmortem_suppressed", labels={"reason": suppressed}
            ).add(1)
            if first_latch:
                self._journal.emit(
                    "postmortem.suppressed",
                    lineage=record.lineage,
                    reason=suppressed,
                    trigger=trigger,
                )
            return
        path = build_bundle(
            out_dir=self.out_dir,
            trigger=trigger,
            trigger_event=record.as_dict(),
            session=self._session,
            registry=self._registry,
            tracer=self._tracer,
            journal=self._journal,
            slo=self._slo,
        )
        with self._lock:
            self.bundles.append(path)
            # A successful bundle re-arms the suppression latches: the
            # NEXT suppression window journals again.
            self._suppressed_latched.clear()
        (self._registry or _default_registry).counter(
            "postmortem_bundles", labels={"trigger": trigger}
        ).add(1)
        self._journal.emit(
            "postmortem.bundle", lineage=record.lineage,
            trigger=trigger, path=path,
        )
