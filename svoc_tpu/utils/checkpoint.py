"""Checkpoint / resume.

The reference has no model checkpoints; its durable state is the
on-chain contract storage (rehydrated by ``resume``), the sqlite
comment DB, and the deployment JSON files (SURVEY.md §5).  The TPU
framework adds three things worth persisting:

- **Training state** (:class:`svoc_tpu.train.trainer.TrainState`) —
  saved with orbax, which handles sharded arrays natively: each host
  writes its shards, restore re-shards onto the current mesh.
- **Simulation state** — the contract simulator + session cursor, so a
  long-running local simulation survives restarts the way the chain
  does for the real deployment.  Exact wsad ints and vote state are
  plain Python data, saved as JSON next to the orbax directory.
- **Service state** (docs/RESILIENCE.md §durability) — everything the
  multi-claim fabric/serving stack holds in memory beyond the chain:
  per-claim request windows and publish cursors, supervisor EMA health
  + hysteresis streaks, breaker states, the PRNG key, and the claim
  registry's membership.  :func:`multi_session_to_dict` /
  :func:`restore_multi_session` are the snapshot half of the PR 8
  recovery manager; a claim present in the snapshot but absent from
  the restoring fabric is QUARANTINED into the snapshot's
  ``unclaimed`` section — never silently dropped, never a crash.

All three paths are exercised in ``tests/test_checkpoint.py`` /
``tests/test_durability.py``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional

from svoc_tpu.consensus.state import OracleConsensusContract
from svoc_tpu.train.trainer import TrainState

#: The snapshot promotion boundary (docs/RESILIENCE.md §fault-surface).
#: Declared in :mod:`svoc_tpu.durability.faultspace` (importing the
#: durability package from here at module top would cycle through
#: ``durability/__init__`` → ``recovery`` → this module); the names are
#: bound here so :func:`save_snapshot` fires them by constant.
SNAPSHOT_PRE_RENAME = "snapshot.pre_rename"
SNAPSHOT_POST_RENAME = "snapshot.post_rename"


# ---------------------------------------------------------------------------
# Training state (orbax)
# ---------------------------------------------------------------------------


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_train_state(path: str, state: TrainState) -> None:
    """Write a :class:`TrainState` checkpoint (orbax PyTree format)."""
    _checkpointer().save(os.path.abspath(path), state)


def restore_train_state(path: str, template: TrainState) -> TrainState:
    """Restore a checkpoint onto ``template``'s tree structure.

    The template (e.g. a freshly built ``init_state(...)``, or an
    ``eval_shape`` + ``device_put`` abstract state for sharded restore)
    supplies the typed pytree nodes — optax opt-state NamedTuples don't
    survive an untyped restore — and, when its leaves carry shardings,
    the placement onto the current mesh."""
    restored = _checkpointer().restore(os.path.abspath(path), item=template)
    if isinstance(restored, TrainState):
        return restored
    if isinstance(restored, dict):
        return TrainState(**restored)
    return TrainState(*restored)


# ---------------------------------------------------------------------------
# Simulation / contract state (JSON)
# ---------------------------------------------------------------------------

_SCHEMA_VERSION = 1


def contract_to_dict(c: OracleConsensusContract) -> Dict[str, Any]:
    """Serialize every storage slot of the contract simulator."""
    return {
        "version": _SCHEMA_VERSION,
        "admins": list(c.admins),
        "oracles": [
            {
                "address": o.address,
                "enabled": o.enabled,
                "reliable": o.reliable,
                "value": list(o.value),
            }
            for o in c.oracles
        ],
        "enable_oracle_replacement": c.enable_oracle_replacement,
        "required_majority": c.required_majority,
        "n_failing_oracles": c.n_failing_oracles,
        "constrained": c.constrained,
        "unconstrained_max_spread": c.unconstrained_max_spread,
        "dimension": c.dimension,
        "strict_interval": c.strict_interval,
        "n_active_oracles": c.n_active_oracles,
        "consensus_active": c.consensus_active,
        "consensus_value": list(c.consensus_value),
        "reliability_first_pass": c.reliability_first_pass,
        "reliability_second_pass": c.reliability_second_pass,
        "skewness": list(c.skewness),
        "kurtosis": list(c.kurtosis),
        "vote_matrix": [
            [i, j, v] for (i, j), v in c.vote_matrix.items() if v
        ],
        "replacement_propositions": [
            list(p) if p is not None else None
            for p in c.replacement_propositions
        ],
    }


def contract_from_dict(d: Dict[str, Any]) -> OracleConsensusContract:
    if d.get("version") != _SCHEMA_VERSION:
        raise ValueError(f"unknown contract snapshot version {d.get('version')}")
    c = OracleConsensusContract(
        admins=d["admins"],
        oracles=[o["address"] for o in d["oracles"]],
        enable_oracle_replacement=d["enable_oracle_replacement"],
        required_majority=d["required_majority"],
        n_failing_oracles=d["n_failing_oracles"],
        constrained=d["constrained"],
        unconstrained_max_spread=0.0,
        dimension=d["dimension"],
        strict_interval=d["strict_interval"],
    )
    c.unconstrained_max_spread = int(d["unconstrained_max_spread"])
    for info, o in zip(c.oracles, d["oracles"]):
        info.enabled = o["enabled"]
        info.reliable = o["reliable"]
        info.value = [int(x) for x in o["value"]]
    c.n_active_oracles = d["n_active_oracles"]
    c.consensus_active = d["consensus_active"]
    c.consensus_value = [int(x) for x in d["consensus_value"]]
    c.reliability_first_pass = int(d["reliability_first_pass"])
    c.reliability_second_pass = int(d["reliability_second_pass"])
    c.skewness = [int(x) for x in d["skewness"]]
    c.kurtosis = [int(x) for x in d["kurtosis"]]
    for i, j, v in d["vote_matrix"]:
        c.vote_matrix[(i, j)] = v
    c.replacement_propositions = [
        tuple(p) if p is not None else None
        for p in d["replacement_propositions"]
    ]
    return c


def save_simulation(path: str, session) -> None:
    """Persist a :class:`svoc_tpu.apps.session.Session`'s durable state:
    the local contract + the circular-window cursor (the volatile
    ``globalState.simulation_step`` the reference loses on restart)."""
    from svoc_tpu.io.chain import LocalChainBackend

    backend = session.adapter.backend
    if not isinstance(backend, LocalChainBackend):
        raise ValueError(
            "save_simulation only applies to local-simulator sessions; "
            "Sepolia state lives on chain (use the resume command)"
        )
    payload = {
        "version": _SCHEMA_VERSION,
        "contract": contract_to_dict(backend.contract),
        "simulation_step": session.simulation_step,
        "config": dataclasses.asdict(session.config),
    }
    with open(path, "w") as f:
        json.dump(payload, f)


def restore_simulation(path: str, session) -> None:
    """Rehydrate ``session`` in place from :func:`save_simulation` —
    contract, cursor, *and* config (so fleet shape always matches the
    restored contract; a stale vectorizer sized for the old config is
    dropped when the dimension changed)."""
    from svoc_tpu.apps.session import SessionConfig
    from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend
    from svoc_tpu.resilience.retry import RetryPolicy
    from svoc_tpu.resilience.supervisor import SupervisorConfig

    with open(path) as f:
        payload = json.load(f)
    contract = contract_from_dict(payload["contract"])
    cfg_dict = dict(payload["config"])
    # dataclasses.asdict flattened the nested resilience dataclasses to
    # plain dicts in the JSON — rebuild them, or the restored session's
    # first resilient commit dies on dict.delays().
    if isinstance(cfg_dict.get("commit_retry"), dict):
        cfg_dict["commit_retry"] = RetryPolicy(**cfg_dict["commit_retry"])
    if isinstance(cfg_dict.get("supervisor"), dict):
        cfg_dict["supervisor"] = SupervisorConfig(**cfg_dict["supervisor"])
    restored_config = SessionConfig(**cfg_dict)
    if restored_config.dimension != session.config.dimension:
        session._vectorizer = None
    session.config = restored_config
    session.adapter = ChainAdapter(LocalChainBackend(contract))
    # The supervisor watches THE session's adapter — rebind it to the
    # restored one, or health folds and replacement votes would keep
    # acting on the discarded pre-restore contract.
    session.supervisor.adapter = session.adapter
    session.supervisor.config = restored_config.supervisor
    # Claim-derived state (docs/FABRIC.md) is computed at Session
    # construction; a claim session's checkpoint restored into a plain
    # Session() must keep partitioning the journal per claim (lineage
    # ``blk<scope>-<claim>-<n>``) and labeling supervisor series, or
    # its audit records and per-claim fingerprints silently stop
    # matching.  (The breaker keeps the constructing session's series
    # name — breaker state is deliberately NOT checkpointed.)
    session.supervisor.claim = restored_config.claim
    scope = (
        restored_config.lineage_scope
        if restored_config.lineage_scope is not None
        else session.lineage_prefix[len("blk"):].split("-", 1)[0]
    )
    session.lineage_prefix = (
        f"blk{scope}-{restored_config.claim}"
        if restored_config.claim
        else f"blk{scope}"
    )
    session.simulation_step = payload["simulation_step"]


# ---------------------------------------------------------------------------
# Service state (docs/RESILIENCE.md §durability)
# ---------------------------------------------------------------------------


def _addr_json(addr: Any) -> Any:
    """Sim/real addresses are ints (the felt space); symbolic test
    doubles degrade to repr — good enough for display, and a restore
    keyed on them only has to match other reprs from the same dump."""
    return addr if isinstance(addr, (int, str)) else repr(addr)


def supervisor_state_to_dict(sup) -> Dict[str, Any]:
    """Everything :class:`~svoc_tpu.resilience.supervisor.
    FleetHealthSupervisor` folds across steps: EMA scores, hysteresis
    streaks, the quarantine set, pending (un-folded) failures, the step
    count, and the replacement history/backstop state."""
    with sup._lock:
        return {
            "scores": [[_addr_json(a), s] for a, s in sup._scores.items()],
            "streaks": [[_addr_json(a), n] for a, n in sup._streaks.items()],
            "quarantined": [_addr_json(a) for a in sup._quarantined],
            "pending_failures": [
                [_addr_json(a), n] for a, n in sup._pending_failures.items()
            ],
            "steps": sup._steps,
            "replace_disabled": sup._replace_disabled,
            "replacements": [dict(r) for r in sup.replacements],
        }


def restore_supervisor_state(sup, d: Dict[str, Any]) -> None:
    with sup._lock:
        sup._scores = {a: float(s) for a, s in d.get("scores", [])}
        sup._streaks = {a: int(n) for a, n in d.get("streaks", [])}
        sup._quarantined = set(d.get("quarantined", []))
        sup._pending_failures = {
            a: int(n) for a, n in d.get("pending_failures", [])
        }
        sup._steps = int(d.get("steps", 0))
        sup._replace_disabled = bool(d.get("replace_disabled", False))
        sup.replacements = [dict(r) for r in d.get("replacements", [])]


def breaker_state_to_dict(breaker) -> Dict[str, Any]:
    with breaker._lock:
        return {
            "state": breaker._state,
            "consecutive_failures": breaker._consecutive_failures,
        }


def restore_breaker_state(breaker, d: Dict[str, Any]) -> None:
    """Rehydrate a breaker conservatively: a snapshot-OPEN breaker
    restores OPEN with a FRESH reset window (the outage may have ended
    while we were dead — half-open probes will find out in one
    ``reset_timeout_s``); half-open collapses to open (the in-flight
    probe died with the process).  Transitions go through the normal
    path so the gauge/counter/journal story stays consistent."""
    from svoc_tpu.resilience.breaker import BREAKER_CLOSED, BREAKER_OPEN

    state = d.get("state", BREAKER_CLOSED)
    with breaker._lock:
        breaker._consecutive_failures = int(d.get("consecutive_failures", 0))
        if state == BREAKER_CLOSED:
            breaker._transition(BREAKER_CLOSED)
        else:
            breaker._opened_at = breaker._clock()
            breaker._probes_in_flight = 0
            breaker._transition(BREAKER_OPEN)
    breaker._flush_events()


def session_durable_dict(session) -> Dict[str, Any]:
    """The full per-claim durable state: the :func:`save_simulation`
    payload PLUS what PRs 6–7 added in memory — the rolling request
    window, the block source, the lineage/publish cursors, the PRNG
    key, and the supervisor/breaker state.  The contract is included
    for self-contained checkpoints; crash recovery over a durable
    chain log IGNORES it (the replayed chain is strictly newer —
    :func:`restore_durable_session`)."""
    import numpy as np

    from svoc_tpu.io.chain import LocalChainBackend

    backend = session.adapter.backend
    inner = getattr(backend, "backend", None)
    contract = None
    if isinstance(backend, LocalChainBackend):
        contract = contract_to_dict(backend.contract)
    elif isinstance(inner, LocalChainBackend):
        contract = contract_to_dict(inner.contract)
    with session.lock:
        window = session._request_window
        key = session._key_value
        payload = {
            "version": _SCHEMA_VERSION,
            "contract": contract,
            "simulation_step": session.simulation_step,
            "config": dataclasses.asdict(session.config),
            "request_window": (
                None if window is None else np.asarray(window).tolist()
            ),
            "block_source": session._block_source,
            "last_lineage": session.last_lineage,
            "fetch_claim": session._fetch_claim,
            "fetch_published": session._fetch_published,
            # The published predictions themselves: fetch_published is
            # a cursor — restoring the cursor without the payload would
            # leave the commit path with "window N published" and
            # nothing to commit (the publish guard refuses to re-fetch
            # an already-published claim).
            "predictions": (
                None
                if session.predictions is None
                else np.asarray(session.predictions).tolist()
            ),
            "state_version": session.state_version,
            # Operator toggles: a crash must not silently flip the
            # fleet back to manual (or worse, re-enable auto_commit
            # the operator turned off mid-incident).
            "auto_fetch": session.auto_fetch,
            "auto_commit": session.auto_commit,
            "auto_resume": session.auto_resume,
            # The PRNG key as raw uint32 words: post-restore fleet
            # draws CONTINUE the stream instead of replaying it from
            # the seed (two restarts must not publish the same
            # bootstrap noise twice).
            "prng_key": (
                None if key is None else np.asarray(key).tolist()
            ),
        }
    payload["supervisor"] = supervisor_state_to_dict(session.supervisor)
    payload["breaker"] = breaker_state_to_dict(session.breaker)
    return payload


def restore_durable_session(
    payload: Dict[str, Any], session, adapter=None
) -> None:
    """Rehydrate ``session`` from :func:`session_durable_dict`.

    ``adapter`` — when the caller already rebuilt the chain (a replayed
    :mod:`svoc_tpu.durability.chainlog` tx log, or a real Sepolia
    adapter), the snapshot's embedded contract is IGNORED: the chain
    outlived us and is strictly newer than any snapshot.  Without it,
    falls back to the embedded contract like :func:`restore_simulation`.
    """
    import jax.numpy as jnp

    from svoc_tpu.apps.session import SessionConfig
    from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend
    from svoc_tpu.resilience.retry import RetryPolicy
    from svoc_tpu.resilience.supervisor import SupervisorConfig

    cfg_dict = dict(payload["config"])
    if isinstance(cfg_dict.get("commit_retry"), dict):
        cfg_dict["commit_retry"] = RetryPolicy(**cfg_dict["commit_retry"])
    if isinstance(cfg_dict.get("supervisor"), dict):
        cfg_dict["supervisor"] = SupervisorConfig(**cfg_dict["supervisor"])
    restored_config = SessionConfig(**cfg_dict)
    if restored_config.dimension != session.config.dimension:
        session._vectorizer = None
    session.config = restored_config
    if adapter is not None:
        session.adapter = adapter
    else:
        if payload.get("contract") is None:
            raise ValueError(
                "snapshot has no embedded contract and no adapter was "
                "provided — rebuild the chain first (replay_chain_log)"
            )
        session.adapter = ChainAdapter(
            LocalChainBackend(contract_from_dict(payload["contract"]))
        )
    session.supervisor.adapter = session.adapter
    session.supervisor.config = restored_config.supervisor
    session.supervisor.claim = restored_config.claim
    restore_supervisor_state(session.supervisor, payload.get("supervisor", {}))
    restore_breaker_state(session.breaker, payload.get("breaker", {}))
    scope = (
        restored_config.lineage_scope
        if restored_config.lineage_scope is not None
        else session.lineage_prefix[len("blk"):].split("-", 1)[0]
    )
    session.lineage_prefix = (
        f"blk{scope}-{restored_config.claim}"
        if restored_config.claim
        else f"blk{scope}"
    )
    window = payload.get("request_window")
    key = payload.get("prng_key")
    with session.lock:
        import numpy as np

        session.simulation_step = int(payload["simulation_step"])
        session._request_window = (
            None if window is None else np.asarray(window, dtype=np.float32)
        )
        session._block_source = payload.get("block_source", "store")
        session.last_lineage = payload.get("last_lineage")
        # Lineage continuity: the next fetch must mint claim N+1, or a
        # restarted session would re-mint already-published lineage ids
        # and merge two different blocks' audit records.
        session._fetch_claim = int(payload.get("fetch_claim", 0))
        session._fetch_published = int(payload.get("fetch_published", 0))
        session._key_value = (
            None
            if key is None
            else jnp.asarray(np.asarray(key, dtype=np.uint32))
        )
        preds = payload.get("predictions")
        session.predictions = (
            None if preds is None else np.asarray(preds, dtype=np.float64)
        )
        # state_version stays monotonic across the restore: a web
        # client polling with a pre-crash version must still see the
        # next redraw.
        session.state_version = max(
            session.state_version, int(payload.get("state_version", 0))
        )
        session.auto_fetch = bool(
            payload.get("auto_fetch", session.auto_fetch)
        )
        session.auto_commit = bool(
            payload.get("auto_commit", session.auto_commit)
        )
        session.auto_resume = bool(
            payload.get("auto_resume", session.auto_resume)
        )


def multi_session_to_dict(multi) -> Dict[str, Any]:
    """Snapshot a :class:`svoc_tpu.fabric.session.MultiSession`: the
    claim registry's membership (specs) + every claim's durable session
    state + the router's scheduling cursor.  ``tamper`` hooks are
    scenario-local callables and are NOT serialized (a restored claim
    is honest until its scenario re-arms it)."""
    claims: Dict[str, Any] = {}
    for state in multi.registry.states():
        claims[state.spec.claim_id] = {
            "spec": claim_spec_to_dict(state.spec),
            "cycles": state.cycles,
            "paused": state.paused,
            "session": session_durable_dict(state.session),
        }
    return {
        "version": _SCHEMA_VERSION,
        "router_steps": multi.router.steps,
        "claims": claims,
        "unclaimed": {},
    }


def claim_spec_to_dict(spec) -> Dict[str, Any]:
    d = dataclasses.asdict(spec)
    d.pop("tamper", None)  # callables don't serialize; re-arm on restore
    return d


def claim_spec_from_dict(d: Dict[str, Any]):
    from svoc_tpu.fabric.registry import ClaimSpec

    return ClaimSpec(**d)


def restore_multi_session(
    payload: Dict[str, Any], multi, adapters: Optional[Dict[str, Any]] = None
) -> Dict[str, Any]:
    """Rehydrate ``multi``'s claims in place from
    :func:`multi_session_to_dict`.

    Membership may have CHANGED between snapshot and restore (a claim
    added or removed by an operator, a different scenario roster): a
    snapshot claim with no live counterpart is moved into the
    snapshot's ``unclaimed`` section — quarantined, not dropped, so a
    later restore (or a human) can still recover it — and a live claim
    with no snapshot state is left fresh.  ``adapters`` maps claim id →
    rebuilt chain adapter (:func:`restore_durable_session` semantics).
    Returns ``{"restored": [...], "unclaimed": [...], "fresh": [...]}``.
    """
    adapters = adapters or {}
    live = {s.spec.claim_id: s for s in multi.registry.states()}
    restored: list = []
    unclaimed = payload.setdefault("unclaimed", {})
    # A previously-quarantined orphan whose claim is back in the live
    # roster is reclaimed — the quarantine is a waiting room, not a
    # grave.  When the snapshot ALSO carries fresher live state for
    # the id, that state wins and the orphan STAYS quarantined (an
    # eager pop here would silently drop it — the exact failure the
    # section exists to prevent).
    claims = payload.setdefault("claims", {})
    for cid in [c for c in list(unclaimed) if c in live]:
        if cid not in claims:
            claims[cid] = unclaimed.pop(cid)
    fresh = [cid for cid in live if cid not in payload.get("claims", {})]
    for cid, entry in list(payload.get("claims", {}).items()):
        state = live.get(cid)
        if state is None:
            # Orphan: quarantine into the snapshot itself.  Never raise
            # — the rest of the fabric must come back up.
            unclaimed[cid] = entry
            continue
        restore_durable_session(
            entry["session"], state.session, adapter=adapters.get(cid)
        )
        state.cycles = int(entry.get("cycles", 0))
        state.paused = bool(entry.get("paused", False))
        restored.append(cid)
    multi.router.steps = int(payload.get("router_steps", 0))
    return {
        "restored": sorted(restored),
        "unclaimed": sorted(unclaimed),
        "fresh": sorted(fresh),
    }


def save_snapshot(path: str, payload: Dict[str, Any]) -> None:
    """Atomic (tmp + rename + fsync file AND directory) JSON write —
    a snapshot either exists whole or not at all, and the rename is
    durable before we return (the recovery manager may rotate the WAL
    immediately after, trusting the snapshot exists)."""
    from svoc_tpu.durability.faultspace import fault_point
    from svoc_tpu.utils.events import _json_safe, fsync_dir

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(_json_safe(payload), f)
        f.flush()
        os.fsync(f.fileno())
    # A kill here leaves only the .tmp — recovery must use the PREVIOUS
    # snapshot plus the journal tail + WAL, never the half-promoted one.
    fault_point(SNAPSHOT_PRE_RENAME)
    os.replace(tmp, path)
    fsync_dir(path)
    # Snapshot durable, caller's follow-up (WAL rotation) not yet run.
    fault_point(SNAPSHOT_POST_RENAME)


def load_snapshot(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
