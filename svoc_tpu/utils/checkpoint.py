"""Checkpoint / resume.

The reference has no model checkpoints; its durable state is the
on-chain contract storage (rehydrated by ``resume``), the sqlite
comment DB, and the deployment JSON files (SURVEY.md §5).  The TPU
framework adds two things worth persisting:

- **Training state** (:class:`svoc_tpu.train.trainer.TrainState`) —
  saved with orbax, which handles sharded arrays natively: each host
  writes its shards, restore re-shards onto the current mesh.
- **Simulation state** — the contract simulator + session cursor, so a
  long-running local simulation survives restarts the way the chain
  does for the real deployment.  Exact wsad ints and vote state are
  plain Python data, saved as JSON next to the orbax directory.

Both paths are exercised in ``tests/test_checkpoint.py``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict

from svoc_tpu.consensus.state import OracleConsensusContract
from svoc_tpu.train.trainer import TrainState


# ---------------------------------------------------------------------------
# Training state (orbax)
# ---------------------------------------------------------------------------


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_train_state(path: str, state: TrainState) -> None:
    """Write a :class:`TrainState` checkpoint (orbax PyTree format)."""
    _checkpointer().save(os.path.abspath(path), state)


def restore_train_state(path: str, template: TrainState) -> TrainState:
    """Restore a checkpoint onto ``template``'s tree structure.

    The template (e.g. a freshly built ``init_state(...)``, or an
    ``eval_shape`` + ``device_put`` abstract state for sharded restore)
    supplies the typed pytree nodes — optax opt-state NamedTuples don't
    survive an untyped restore — and, when its leaves carry shardings,
    the placement onto the current mesh."""
    restored = _checkpointer().restore(os.path.abspath(path), item=template)
    if isinstance(restored, TrainState):
        return restored
    if isinstance(restored, dict):
        return TrainState(**restored)
    return TrainState(*restored)


# ---------------------------------------------------------------------------
# Simulation / contract state (JSON)
# ---------------------------------------------------------------------------

_SCHEMA_VERSION = 1


def contract_to_dict(c: OracleConsensusContract) -> Dict[str, Any]:
    """Serialize every storage slot of the contract simulator."""
    return {
        "version": _SCHEMA_VERSION,
        "admins": list(c.admins),
        "oracles": [
            {
                "address": o.address,
                "enabled": o.enabled,
                "reliable": o.reliable,
                "value": list(o.value),
            }
            for o in c.oracles
        ],
        "enable_oracle_replacement": c.enable_oracle_replacement,
        "required_majority": c.required_majority,
        "n_failing_oracles": c.n_failing_oracles,
        "constrained": c.constrained,
        "unconstrained_max_spread": c.unconstrained_max_spread,
        "dimension": c.dimension,
        "strict_interval": c.strict_interval,
        "n_active_oracles": c.n_active_oracles,
        "consensus_active": c.consensus_active,
        "consensus_value": list(c.consensus_value),
        "reliability_first_pass": c.reliability_first_pass,
        "reliability_second_pass": c.reliability_second_pass,
        "skewness": list(c.skewness),
        "kurtosis": list(c.kurtosis),
        "vote_matrix": [
            [i, j, v] for (i, j), v in c.vote_matrix.items() if v
        ],
        "replacement_propositions": [
            list(p) if p is not None else None
            for p in c.replacement_propositions
        ],
    }


def contract_from_dict(d: Dict[str, Any]) -> OracleConsensusContract:
    if d.get("version") != _SCHEMA_VERSION:
        raise ValueError(f"unknown contract snapshot version {d.get('version')}")
    c = OracleConsensusContract(
        admins=d["admins"],
        oracles=[o["address"] for o in d["oracles"]],
        enable_oracle_replacement=d["enable_oracle_replacement"],
        required_majority=d["required_majority"],
        n_failing_oracles=d["n_failing_oracles"],
        constrained=d["constrained"],
        unconstrained_max_spread=0.0,
        dimension=d["dimension"],
        strict_interval=d["strict_interval"],
    )
    c.unconstrained_max_spread = int(d["unconstrained_max_spread"])
    for info, o in zip(c.oracles, d["oracles"]):
        info.enabled = o["enabled"]
        info.reliable = o["reliable"]
        info.value = [int(x) for x in o["value"]]
    c.n_active_oracles = d["n_active_oracles"]
    c.consensus_active = d["consensus_active"]
    c.consensus_value = [int(x) for x in d["consensus_value"]]
    c.reliability_first_pass = int(d["reliability_first_pass"])
    c.reliability_second_pass = int(d["reliability_second_pass"])
    c.skewness = [int(x) for x in d["skewness"]]
    c.kurtosis = [int(x) for x in d["kurtosis"]]
    for i, j, v in d["vote_matrix"]:
        c.vote_matrix[(i, j)] = v
    c.replacement_propositions = [
        tuple(p) if p is not None else None
        for p in d["replacement_propositions"]
    ]
    return c


def save_simulation(path: str, session) -> None:
    """Persist a :class:`svoc_tpu.apps.session.Session`'s durable state:
    the local contract + the circular-window cursor (the volatile
    ``globalState.simulation_step`` the reference loses on restart)."""
    from svoc_tpu.io.chain import LocalChainBackend

    backend = session.adapter.backend
    if not isinstance(backend, LocalChainBackend):
        raise ValueError(
            "save_simulation only applies to local-simulator sessions; "
            "Sepolia state lives on chain (use the resume command)"
        )
    payload = {
        "version": _SCHEMA_VERSION,
        "contract": contract_to_dict(backend.contract),
        "simulation_step": session.simulation_step,
        "config": dataclasses.asdict(session.config),
    }
    with open(path, "w") as f:
        json.dump(payload, f)


def restore_simulation(path: str, session) -> None:
    """Rehydrate ``session`` in place from :func:`save_simulation` —
    contract, cursor, *and* config (so fleet shape always matches the
    restored contract; a stale vectorizer sized for the old config is
    dropped when the dimension changed)."""
    from svoc_tpu.apps.session import SessionConfig
    from svoc_tpu.io.chain import ChainAdapter, LocalChainBackend
    from svoc_tpu.resilience.retry import RetryPolicy
    from svoc_tpu.resilience.supervisor import SupervisorConfig

    with open(path) as f:
        payload = json.load(f)
    contract = contract_from_dict(payload["contract"])
    cfg_dict = dict(payload["config"])
    # dataclasses.asdict flattened the nested resilience dataclasses to
    # plain dicts in the JSON — rebuild them, or the restored session's
    # first resilient commit dies on dict.delays().
    if isinstance(cfg_dict.get("commit_retry"), dict):
        cfg_dict["commit_retry"] = RetryPolicy(**cfg_dict["commit_retry"])
    if isinstance(cfg_dict.get("supervisor"), dict):
        cfg_dict["supervisor"] = SupervisorConfig(**cfg_dict["supervisor"])
    restored_config = SessionConfig(**cfg_dict)
    if restored_config.dimension != session.config.dimension:
        session._vectorizer = None
    session.config = restored_config
    session.adapter = ChainAdapter(LocalChainBackend(contract))
    # The supervisor watches THE session's adapter — rebind it to the
    # restored one, or health folds and replacement votes would keep
    # acting on the discarded pre-restore contract.
    session.supervisor.adapter = session.adapter
    session.supervisor.config = restored_config.supervisor
    # Claim-derived state (docs/FABRIC.md) is computed at Session
    # construction; a claim session's checkpoint restored into a plain
    # Session() must keep partitioning the journal per claim (lineage
    # ``blk<scope>-<claim>-<n>``) and labeling supervisor series, or
    # its audit records and per-claim fingerprints silently stop
    # matching.  (The breaker keeps the constructing session's series
    # name — breaker state is deliberately NOT checkpointed.)
    session.supervisor.claim = restored_config.claim
    scope = (
        restored_config.lineage_scope
        if restored_config.lineage_scope is not None
        else session.lineage_prefix[len("blk"):].split("-", 1)[0]
    )
    session.lineage_prefix = (
        f"blk{scope}-{restored_config.claim}"
        if restored_config.claim
        else f"blk{scope}"
    )
    session.simulation_step = payload["simulation_step"]
