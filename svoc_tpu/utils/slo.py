"""Declarative SLOs evaluated as multi-window burn rates.

An SLO here is a **good/total ratio objective** (the SRE-workbook
shape): commit success ratio, the fraction of ``consensus`` stage
dispatches under the latency target (a latency SLO *is* a ratio SLO
over the histogram's cumulative buckets), and the quarantine admission
ratio.  The evaluator samples the cumulative counters from the shared
:class:`~svoc_tpu.utils.metrics.MetricsRegistry`, differences them over
a **fast** and a **slow** trailing window, and reports each window's
burn rate::

    error_rate = bad_delta / total_delta
    burn       = error_rate / (1 - objective)      # 1.0 = exactly on budget

Alerting follows the classic multi-window rule: a page-worthy condition
requires BOTH the fast burn (is it happening *now*?) and the slow burn
(is it *sustained*?) above their thresholds — a single bad commit after
an idle hour must not page.  Crossings emit one ``slo.alert`` journal
event (latched until recovery) and bump ``slo_alerts{slo=}``; the live
values are exported as ``slo_burn_rate{slo=,window=}`` /
``slo_error_rate{slo=,window=}`` gauges, so ``GET /metrics``, the
console's ``slo`` command, and soak artifacts read one data set.

The clock is injectable (tests / chaos replay), the sample history is
pruned to the slow window, and evaluation is on-demand (console, soak
snapshot cadence, the auto loop's ``Session.slo_step``) — never on the
serving hot path.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry


@dataclasses.dataclass(frozen=True)
class SLODefinition:
    """One objective plus its alerting windows.

    ``sample`` returns the CUMULATIVE ``(good, total)`` pair — the
    evaluator differences consecutive samples, so sources only need
    monotone counters.  Default thresholds are the SRE-workbook pair
    for a fast page (14.4× burn over the fast window) backed by a
    sustained signal (6× over the slow window).
    """

    name: str
    description: str
    objective: float
    sample: Callable[[], Tuple[float, float]]
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fast_burn_alert: float = 14.4
    slow_burn_alert: float = 6.0

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        if not 0.0 < self.fast_window_s <= self.slow_window_s:
            raise ValueError("need 0 < fast_window_s <= slow_window_s")


def _bucket_ratio(h, bound_s: float) -> Tuple[float, float]:
    """Cumulative ``(count ≤ bound, total)`` over a histogram's buckets
    (bucketized: the largest bucket bound ≤ the target is the effective
    threshold)."""
    buckets = h.cumulative_buckets()
    total = buckets[-1][1] if buckets else 0
    good = 0
    for le, cumulative in buckets:
        if le <= bound_s:
            good = cumulative
        else:
            break
    return float(good), float(total)


def _histogram_le(registry: MetricsRegistry, stage: str, bound_s: float):
    """:func:`_bucket_ratio` over the stage histogram — the latency
    SLO's ratio source."""
    return _bucket_ratio(registry.stage_histogram(stage), bound_s)


def default_slos(
    registry: Optional[MetricsRegistry] = None,
    *,
    consensus_p99_target_s: float = 0.25,
) -> List[SLODefinition]:
    """The framework's shipped objectives (docs/OBSERVABILITY.md §slo):

    - ``commit_success``  — ≥ 99 % of commit cycles land without a
      recorded failure (``chain_commit_failures`` over the commit
      timer's attempt count),
    - ``consensus_latency`` — ≥ 99 % of ``consensus`` stage dispatches
      complete within the p99 target (default 250 ms),
    - ``quarantine_admission`` — ≥ 90 % of inspected fleet slots pass
      the input-integrity gate (a sustained quarantine spike means an
      upstream data problem even while consensus survives it).
    """
    reg = registry or _default_registry

    def commit_sample() -> Tuple[float, float]:
        total = float(reg.timer("commit_latency").n)
        bad = float(reg.counter("chain_commit_failures").count)
        return max(0.0, total - bad), total

    def consensus_sample() -> Tuple[float, float]:
        return _histogram_le(reg, "consensus", consensus_p99_target_s)

    def quarantine_sample() -> Tuple[float, float]:
        total = float(reg.counter("quarantine_slots_inspected").count)
        bad = float(reg.family_total("oracle_quarantine"))
        return max(0.0, total - bad), total

    return [
        SLODefinition(
            name="commit_success",
            description="commit cycles without a recorded failure",
            objective=0.99,
            sample=commit_sample,
        ),
        SLODefinition(
            name="consensus_latency",
            description=(
                f"consensus stage dispatches under "
                f"{consensus_p99_target_s * 1e3:.0f} ms"
            ),
            objective=0.99,
            sample=consensus_sample,
        ),
        SLODefinition(
            name="quarantine_admission",
            description="fleet slots admitted by the input-integrity gate",
            objective=0.90,
            sample=quarantine_sample,
        ),
    ]


#: The histogram family the serving tier observes end-to-end request
#: latency (submit → completed consensus) into — shared between the
#: ``request_latency`` SLO below, the serving bench, and /metrics.
REQUEST_LATENCY_HISTOGRAM = "request_latency_seconds"


def serving_slos(
    registry: Optional[MetricsRegistry] = None,
    *,
    latency_objective: float = 0.99,
    latency_target_s: float = 0.25,
    admission_objective: float = 0.95,
    fast_window_s: float = 300.0,
    slow_window_s: float = 3600.0,
) -> List[SLODefinition]:
    """The serving tier's objectives (docs/SERVING.md):

    - ``request_latency`` — ≥ 99 % of completed requests finish within
      the latency target (cumulative-bucket ratio over
      :data:`REQUEST_LATENCY_HISTOGRAM`, the same histogram-as-ratio
      trick as ``consensus_latency``).  This is the burn rate the
      :class:`svoc_tpu.serving.frontend.AdmissionController` reads —
      overload sheds load *before* the commit objective burns.
    - ``serving_admission`` — ≥ 95 % of submitted requests are served
      (admitted or answered from cache) rather than shed.  A sustained
      admission burn means the tier is saturated even if every admitted
      request is fast.

    Windows are configurable because seeded serving scenarios run in
    virtual time (seconds, not hours) and need the burn to react within
    the run (``svoc_tpu/serving/scenario.py``).
    """
    reg = registry or _default_registry

    def latency_sample() -> Tuple[float, float]:
        return _bucket_ratio(
            reg.histogram(REQUEST_LATENCY_HISTOGRAM), latency_target_s
        )

    def admission_sample() -> Tuple[float, float]:
        served = float(reg.family_total("serving_admitted")) + float(
            reg.family_total("serving_cached")
        )
        shed = float(reg.family_total("serving_shed"))
        # Admitted-then-dropped requests (claim skipped mid-cycle,
        # vectorizer failure) were never actually served: they count
        # against the objective exactly like a shed, so a claim that
        # blackholes its traffic burns this SLO instead of reading
        # green forever.
        dropped = float(reg.family_total("serving_dropped"))
        return max(0.0, served - dropped), served + shed

    return [
        SLODefinition(
            name="request_latency",
            description=(
                f"serving requests completed within "
                f"{latency_target_s * 1e3:.0f} ms"
            ),
            objective=latency_objective,
            sample=latency_sample,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
        ),
        SLODefinition(
            name="serving_admission",
            description="submitted requests served rather than shed",
            objective=admission_objective,
            sample=admission_sample,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
        ),
    ]


def fleet_slos(
    merged: Callable[[], MetricsRegistry],
    *,
    commit_objective: float = 0.99,
    latency_objective: float = 0.99,
    latency_target_s: float = 0.25,
    admission_objective: float = 0.95,
    fast_window_s: float = 300.0,
    slow_window_s: float = 3600.0,
) -> List[SLODefinition]:
    """Fleet-wide objectives over MERGED telemetry (docs/OBSERVABILITY
    .md §fleet-plane): the user experiences the FLEET, not a replica,
    so the burn rates that matter difference counters summed across
    every replica (live + retired — the fleet plane's merge keeps them
    monotone through a failover, so the window differencing here never
    reads a replica swap as recovery).

    ``merged`` is a CALLABLE returning the current fleet merge (the
    :class:`~svoc_tpu.obsplane.fleet.FleetPlane` provides one that
    reuses a single merge per evaluation pass) — the samples are taken
    at evaluation time, like every other evaluator here.

    - ``commit_success`` — fleet commit cycles without a recorded
      failure (``commit_latency`` attempts vs ``chain_commit_failures``
      summed across replicas);
    - ``request_latency`` — completed requests within the target,
      cumulative-bucket ratio over the MERGED
      :data:`REQUEST_LATENCY_HISTOGRAM`;
    - ``serving_admission`` — the serving-tier admission ratio over
      fleet-summed counters (same formula as :func:`serving_slos`).
    """

    def commit_sample() -> Tuple[float, float]:
        reg = merged()
        total = float(reg.timer("commit_latency").n)
        bad = float(reg.family_total("chain_commit_failures"))
        return max(0.0, total - bad), total

    def latency_sample() -> Tuple[float, float]:
        return _bucket_ratio(
            merged().histogram(REQUEST_LATENCY_HISTOGRAM), latency_target_s
        )

    def admission_sample() -> Tuple[float, float]:
        reg = merged()
        served = float(reg.family_total("serving_admitted")) + float(
            reg.family_total("serving_cached")
        )
        shed = float(reg.family_total("serving_shed"))
        dropped = float(reg.family_total("serving_dropped"))
        return max(0.0, served - dropped), served + shed

    return [
        SLODefinition(
            name="commit_success",
            description="fleet commit cycles without a recorded failure",
            objective=commit_objective,
            sample=commit_sample,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
        ),
        SLODefinition(
            name="request_latency",
            description=(
                f"fleet requests completed within "
                f"{latency_target_s * 1e3:.0f} ms"
            ),
            objective=latency_objective,
            sample=latency_sample,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
        ),
        SLODefinition(
            name="serving_admission",
            description="fleet submissions served rather than shed",
            objective=admission_objective,
            sample=admission_sample,
            fast_window_s=fast_window_s,
            slow_window_s=slow_window_s,
        ),
    ]


def claim_slos(
    registry: Optional[MetricsRegistry] = None,
    claim: str = "",
    *,
    commit_objective: float = 0.99,
    admission_objective: float = 0.90,
) -> List[SLODefinition]:
    """Per-claim objectives for the multi-claim fabric (docs/FABRIC.md).

    The claim router maintains claim-labeled cumulative counters as it
    multiplexes commit cycles — ``claim_commit_cycles{claim=}`` /
    ``claim_commit_failures{claim=}`` and
    ``claim_slots_inspected{claim=}`` / ``claim_slots_quarantined``
    ``{claim=}`` — and each claim gets its own evaluator over them, so
    one claim's burning error budget pages for THAT market instead of
    diluting into a fleet-wide average (a thousand healthy claims
    would otherwise hide one dead one forever).  SLO names are
    claim-qualified (``commit_success@<claim>``): the burn-rate gauges
    key on the slo label, and two claims' series must not collide."""
    if not claim:
        raise ValueError("claim_slos needs a claim id")
    reg = registry or _default_registry
    labels = {"claim": claim}

    def commit_sample() -> Tuple[float, float]:
        total = float(reg.counter("claim_commit_cycles", labels=labels).count)
        bad = float(reg.counter("claim_commit_failures", labels=labels).count)
        return max(0.0, total - bad), total

    def admission_sample() -> Tuple[float, float]:
        total = float(
            reg.counter("claim_slots_inspected", labels=labels).count
        )
        bad = float(
            reg.counter("claim_slots_quarantined", labels=labels).count
        )
        return max(0.0, total - bad), total

    return [
        SLODefinition(
            name=f"commit_success@{claim}",
            description=f"claim {claim}: commit cycles without a failure",
            objective=commit_objective,
            sample=commit_sample,
        ),
        SLODefinition(
            name=f"quarantine_admission@{claim}",
            description=f"claim {claim}: fleet slots admitted by the gate",
            objective=admission_objective,
            sample=admission_sample,
        ),
    ]


class SLOEvaluator:
    """Samples each SLO's cumulative counters and reports fast/slow
    burn rates; thread-safe (console, soak, and the auto loop may all
    evaluate concurrently)."""

    def __init__(
        self,
        slos: Sequence[SLODefinition],
        *,
        registry: Optional[MetricsRegistry] = None,
        journal=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.slos = tuple(slos)
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self._registry = registry or _default_registry
        self._journal = journal
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: Dict[str, deque] = {s.name: deque() for s in self.slos}
        self._alerting: Dict[str, bool] = {s.name: False for s in self.slos}

    def _emit(self, event_type: str, **data: Any) -> None:
        j = self._journal
        if j is None:
            from svoc_tpu.utils.events import journal as j
        j.emit(event_type, **data)

    @staticmethod
    def _window_burn(
        samples: deque, now: float, window_s: float, objective: float
    ) -> Dict[str, float]:
        """Burn over the trailing window: difference the newest sample
        against the OLDEST one inside the window (or the last one just
        before it, so a window that started mid-interval still has a
        baseline)."""
        latest = samples[-1]
        baseline = None
        for t, good, total in samples:
            if t >= now - window_s:
                if baseline is None:
                    baseline = (t, good, total)
                break
            baseline = (t, good, total)  # newest sample BEFORE the window
        if baseline is None:
            baseline = samples[0]
        d_total = latest[2] - baseline[2]
        d_good = latest[1] - baseline[1]
        if d_total <= 0:
            return {"error_rate": 0.0, "burn": 0.0, "events": 0.0}
        error_rate = min(1.0, max(0.0, 1.0 - d_good / d_total))
        return {
            "error_rate": error_rate,
            "burn": error_rate / (1.0 - objective),
            "events": d_total,
        }

    def evaluate(self) -> Dict[str, Dict[str, Any]]:
        """One evaluation pass; returns the per-SLO snapshot and
        updates gauges / alert latches."""
        now = self._clock()
        out: Dict[str, Dict[str, Any]] = {}
        alerts: List[Dict[str, Any]] = []
        with self._lock:
            for slo in self.slos:
                good, total = slo.sample()
                dq = self._samples[slo.name]
                dq.append((now, float(good), float(total)))
                # Keep one sample older than the slow window as the
                # baseline; prune the rest.
                horizon = now - slo.slow_window_s
                while len(dq) >= 2 and dq[1][0] <= horizon:
                    dq.popleft()
                fast = self._window_burn(dq, now, slo.fast_window_s, slo.objective)
                slow = self._window_burn(dq, now, slo.slow_window_s, slo.objective)
                for window, burn in (("fast", fast), ("slow", slow)):
                    self._registry.gauge(
                        "slo_burn_rate", labels={"slo": slo.name, "window": window}
                    ).set(burn["burn"])
                    self._registry.gauge(
                        "slo_error_rate",
                        labels={"slo": slo.name, "window": window},
                    ).set(burn["error_rate"])
                alerting = (
                    fast["events"] > 0
                    and fast["burn"] >= slo.fast_burn_alert
                    and slow["burn"] >= slo.slow_burn_alert
                )
                if alerting and not self._alerting[slo.name]:
                    alerts.append(
                        {
                            "slo": slo.name,
                            "objective": slo.objective,
                            "fast_burn": round(fast["burn"], 4),
                            "slow_burn": round(slow["burn"], 4),
                        }
                    )
                self._alerting[slo.name] = alerting
                out[slo.name] = {
                    "objective": slo.objective,
                    "description": slo.description,
                    "good": good,
                    "total": total,
                    "fast": {k: round(v, 6) for k, v in fast.items()},
                    "slow": {k: round(v, 6) for k, v in slow.items()},
                    "alerting": alerting,
                }
        # Emission OUTSIDE the evaluator lock: journal subscribers (the
        # postmortem monitor) may build bundles that re-enter snapshots.
        for alert in alerts:
            self._registry.counter(
                "slo_alerts", labels={"slo": alert["slo"]}
            ).add(1)
            self._emit("slo.alert", **alert)
        return out

    def alerting(self) -> List[str]:
        """Names of SLOs currently in the alerting state."""
        with self._lock:
            return [name for name, on in self._alerting.items() if on]
