"""Data-parallel inference serving: the reference hot loop at pod scale.

The reference classifies a comment window and regenerates the oracle
fleet in a single-threaded Python loop (``client/oracle_scheduler.py:
36-40`` + ``:73-92``, ~6 comments/sec).  The honest single-chip ceiling
of the TPU rebuild is ~4.5k comments/sec at ~50% MFU (``BENCH_r03``) —
so the ≥10k comments/sec BASELINE target is a *multi-chip* target: this
module scales the serving path over a device mesh the way the trainer
scales fine-tuning.

One mesh axis (``data``) carries both parallelisms of the serving step:

- the jitted encoder forward runs **data-parallel** — the token batch is
  sharded ``P("data", None)`` over the axis, params replicated, so the
  per-step batch is ``n_devices ×`` the single-chip batch at the same
  step latency;
- the window of sentiment vectors is then replicated (one small
  ``all_gather`` of ``[window, M]`` — KBs over ICI), and the bootstrap
  fleet + two-pass consensus run **oracle-parallel** over the same axis
  via the shard_map body of :mod:`svoc_tpu.parallel.sharded` (global-
  index PRNG keys ⇒ the fleet is bitwise independent of the mesh size).

Everything is one ``jit`` — XLA inserts exactly two collective phases
(window all-gather, consensus reductions), both tiny next to the
forward, so serving throughput scales ~linearly with the mesh until the
host tokenizer saturates.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from svoc_tpu.consensus.kernel import ConsensusConfig
from svoc_tpu.models.configs import EncoderConfig
from svoc_tpu.models.forward import resolve_forward
from svoc_tpu.models.sentiment import TRACKED_INDICES, scores_to_vectors
from svoc_tpu.ops.select import first_valid_window
from svoc_tpu.parallel.sharded import fleet_consensus_shard_map
from svoc_tpu.utils.metrics import stage_span


def _traced_dispatch(fn, stage: str, lineage=None):
    """Wrap a jitted step so each call records a ``stage_seconds`` span.

    The span closes when dispatch returns — it measures host dispatch
    (plus any blocking XLA compile on first call), NEVER device
    execution: forcing completion here would serialize the serving
    loop's run-ahead.  Per-call overhead is sub-microsecond against a
    multi-ms step; end-to-end device throughput stays on the bench's
    host-fetch protocol (honest timing — ``bench.py`` module docs).

    ``lineage`` tags every span from this wrapper with a block lineage
    id (``svoc_tpu.utils.events``) — a factory-level constant, so the
    hot path pays nothing beyond the span it already recorded.
    """

    @functools.wraps(fn)  # also sets __wrapped__ = fn for unwrapping
    def dispatch(*args, **kwargs):
        with stage_span(stage, lineage=lineage):
            return fn(*args, **kwargs)

    return dispatch


def dp_serving_step_fn(
    mesh: Mesh,
    enc_cfg: EncoderConfig,
    ccfg: ConsensusConfig,
    n_oracles: int,
    *,
    window_size: int = 50,
    subset_size: int = 10,
    label_indices: tuple = TRACKED_INDICES,
    axis: str = "data",
    quant: Optional[str] = None,
):
    """Jitted ``(params, key, ids, mask) → (ConsensusOutput, honest)``.

    ``ids``/``mask`` are ``[B, T]`` with ``B`` sharded over ``axis``
    (use :func:`batch_sharding` for the device_put); params and the PRNG
    key are replicated.  ``B`` and ``n_oracles`` must divide by the mesh
    size.  Returns the same ConsensusOutput tree as
    :func:`svoc_tpu.parallel.sharded.sharded_fleet_step_fn` (per-oracle
    leaves sharded over ``axis``).

    ``quant="int8"`` serves the W8A8 dynamic-PTQ forward
    (:mod:`svoc_tpu.models.quant`): pass the QUANTIZED tree as
    ``params`` — it replicates over the mesh like the float tree (and
    is ~4× smaller in HBM).
    """
    if max(label_indices) >= enc_cfg.n_labels:
        raise ValueError(
            f"label_indices {label_indices} out of range for a "
            f"{enc_cfg.n_labels}-label head — the jitted gather would "
            "silently clamp; pass indices matching the model"
        )

    apply_fn = resolve_forward(enc_cfg, quant)
    multi_label = enc_cfg.head == "sigmoid"
    fleet = fleet_consensus_shard_map(mesh, ccfg, n_oracles, subset_size, axis)

    replicated = NamedSharding(mesh, P())
    batch_shard = NamedSharding(mesh, P(axis, None))

    def serve(params, key, ids, mask):
        if ids.shape[0] < window_size:
            raise ValueError(
                f"batch {ids.shape[0]} smaller than window_size "
                f"{window_size} — the consensus window would be "
                "silently truncated"
            )
        logits = apply_fn(params, ids, mask)  # batch stays data-sharded
        vecs = scores_to_vectors(logits, label_indices, multi_label)
        # Replicate the fleet's comment window: one [window, M] all-gather.
        window = jax.lax.with_sharding_constraint(
            vecs[:window_size], replicated
        )
        return fleet(key, window)

    return _traced_dispatch(
        jax.jit(
            serve,
            in_shardings=(replicated, replicated, batch_shard, batch_shard),
        ),
        "serving_step",
    )


def _packed_window_fn(
    mesh: Mesh,
    enc_cfg: EncoderConfig,
    window_size: int,
    label_indices: tuple,
    quant: Optional[str],
):
    """The shared forward→window computation of the packed serving
    steps: ``(params, ids, pos, seg, cls_pos, valid) → [window, M]``
    replicated window of the first ``window_size`` VALID segment
    vectors in global row order (sort-free compaction — a TPU stable
    argsort here measurably dominated the packed consensus step:
    ``ops/select.py`` module docstring).  One home so the plain and
    pipelined twins can never drift."""
    if max(label_indices) >= enc_cfg.n_labels:
        raise ValueError(
            f"label_indices {label_indices} out of range for a "
            f"{enc_cfg.n_labels}-label head"
        )
    apply_fn = resolve_forward(enc_cfg, quant, packed=True)
    multi_label = enc_cfg.head == "sigmoid"
    dim = len(label_indices)
    replicated = NamedSharding(mesh, P())

    def window_of(params, ids, pos, seg, cls_pos, valid):
        r, s = cls_pos.shape
        if r * s < window_size:
            raise ValueError(
                f"packed batch capacity {r}x{s} segments is smaller than "
                f"window_size {window_size} — the consensus window would "
                "be silently truncated"
            )
        logits = apply_fn(params, ids, pos, seg, cls_pos)  # [R, S, L]
        r, s, l = logits.shape
        vecs = scores_to_vectors(
            logits.reshape(r * s, l), label_indices, multi_label
        )
        return jax.lax.with_sharding_constraint(
            first_valid_window(vecs, valid.reshape(-1), window_size).reshape(
                window_size, dim
            ),
            replicated,
        )

    return window_of


def _packed_in_shardings(mesh: Mesh, axis: str, extra: int = 0):
    """jit in_shardings for ``(params, key, ids, pos, seg, cls_pos,
    valid, *extra-replicated)`` of the packed serving steps."""
    replicated = NamedSharding(mesh, P())
    row_shard = NamedSharding(mesh, P(axis, None))
    return (replicated, replicated) + (row_shard,) * 5 + (replicated,) * extra


def packed_serving_step_fn(
    mesh: Mesh,
    enc_cfg: EncoderConfig,
    ccfg: ConsensusConfig,
    n_oracles: int,
    *,
    window_size: int = 50,
    subset_size: int = 10,
    label_indices: tuple = TRACKED_INDICES,
    axis: str = "data",
    quant: Optional[str] = None,
):
    """Sequence-PACKED data-parallel serving: the config-7 path with the
    packed forward (:mod:`svoc_tpu.models.packing`) — rows carry several
    comments each, so per-mesh throughput compounds the packing factor
    (~3×) with the device count.  ``quant="int8"`` additionally swaps in
    the W8A8 forward (pass the quantized tree as ``params``): packing ×
    int8 × device count is the framework's highest-throughput serving
    configuration.

    Jitted ``(params, key, ids, pos, seg, cls_pos, valid) →
    (ConsensusOutput, honest)``; the four packed arrays are ``[R, T]``/
    ``[R, S]`` with rows sharded over ``axis`` (``valid`` is
    ``seg_valid > 0``).  The consensus window is the first
    ``window_size`` VALID segments in row order — the packer preserves
    input order, so this matches the unpacked path's ``vecs[:window]``
    on the same texts (equivalence-tested in ``tests/test_serving.py``).

    The segment capacity ``R×S`` must cover ``window_size`` (checked at
    trace time).  The number of VALID segments is data-dependent and
    cannot be checked inside jit: a batch with fewer than
    ``window_size`` valid segments pads the window with ZERO vectors
    (the sort-free compaction's deterministic padding — see
    ``ops/select.py``) — callers must keep rows full (the bench's
    packed stream buffers comments so every batch does).
    """
    window_of = _packed_window_fn(mesh, enc_cfg, window_size, label_indices, quant)
    fleet = fleet_consensus_shard_map(mesh, ccfg, n_oracles, subset_size, axis)

    def serve(params, key, ids, pos, seg, cls_pos, valid):
        return fleet(key, window_of(params, ids, pos, seg, cls_pos, valid))

    return _traced_dispatch(
        jax.jit(serve, in_shardings=_packed_in_shardings(mesh, axis)),
        "serving_step",
    )


def packed_serving_pipelined_step_fn(
    mesh: Mesh,
    enc_cfg: EncoderConfig,
    ccfg: ConsensusConfig,
    n_oracles: int,
    *,
    window_size: int = 50,
    subset_size: int = 10,
    label_indices: tuple = TRACKED_INDICES,
    axis: str = "data",
    quant: Optional[str] = None,
):
    """Software-pipelined twin of :func:`packed_serving_step_fn`:
    ``(params, key, ids, pos, seg, cls_pos, valid, prev_window) →
    (window, ConsensusOutput, honest)`` — the fleet+consensus runs on
    the PREVIOUS batch's (replicated, [window, M]) window inside the
    same XLA program as the current batch's forward, so the
    consensus tail overlaps the forward's MXU matmuls instead of
    serializing behind them (the round-4 packed step spent 21.4 of
    83.8 ms on that serialization).  ``key`` must be the key for the
    PREVIOUS batch.  Lossless: identical windows and consensus
    outputs, one step later; drain the last window with one
    standalone fleet call (:func:`fleet_step_fn`).
    """
    window_of = _packed_window_fn(mesh, enc_cfg, window_size, label_indices, quant)
    fleet = fleet_consensus_shard_map(mesh, ccfg, n_oracles, subset_size, axis)

    def serve(params, key, ids, pos, seg, cls_pos, valid, prev_window):
        window = window_of(params, ids, pos, seg, cls_pos, valid)
        out, honest = fleet(key, prev_window)
        return window, out, honest

    return _traced_dispatch(
        jax.jit(serve, in_shardings=_packed_in_shardings(mesh, axis, extra=1)),
        "serving_step",
    )


def fleet_step_fn(
    mesh: Mesh,
    ccfg: ConsensusConfig,
    n_oracles: int,
    *,
    subset_size: int = 10,
    axis: str = "data",
    gate=None,
):
    """Standalone jitted ``(key, window) → (ConsensusOutput, honest)``
    on the serving mesh — the drain step for the pipelined serving
    loop (and a direct window-consensus entry point).

    ``gate=(lo, hi)`` enables the in-graph input-integrity quarantine
    on the generated fleet values (docs/ROBUSTNESS.md): a corrupted
    window (NaN from a poisoned forward, out-of-domain vectors) can
    then never reach the consensus reductions — the step returns
    ``(ConsensusOutput, honest, admitted)`` and flags
    ``interval_valid=False`` when fewer than two oracles survive.
    """
    return _traced_dispatch(
        jax.jit(
            fleet_consensus_shard_map(
                mesh, ccfg, n_oracles, subset_size, axis, gate
            )
        ),
        "fleet",
    )


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding for serving token batches: batch dim over ``axis``."""
    return NamedSharding(mesh, P(axis, None))


def serving_mesh(devices: Optional[list] = None, axis: str = "data") -> Mesh:
    """A 1-D serving mesh over ``devices`` (default: all local devices)."""
    import numpy as np

    devs = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devs), (axis,))
