"""shard_map consensus + fleet step over an oracle-sharded mesh.

The reference's "distribution" is logical: one Python process multiplexes
all oracle identities and the blockchain is the reducer (SURVEY.md §2.5).
Here the oracle axis is physically sharded over the mesh and the
consensus becomes XLA collectives:

- medians need a global view of each component → one small
  ``all_gather`` over the oracle axis ([N, M] with M ≤ a few dozen —
  bytes, not megabytes; rides ICI),
- scalar risk reductions (means over N) are ``psum``,
- the rank-based reliability mask needs the global risk vector → an
  ``all_gather`` of N scalars.

Everything is fixed-shape, so the same code jit-compiles for any mesh
factorization, and results are bitwise independent of the device count
(per-oracle ``fold_in`` PRNG keys, no cross-device RNG).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 exports the function at top level
    from jax import shard_map as _shard_map  # type: ignore

    def shard_map(f, **kw):  # replicated-output check renamed check_rep→check_vma
        kw["check_vma"] = kw.pop("check_rep", False)
        return _shard_map(f, **kw)

except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from svoc_tpu.consensus.kernel import ConsensusConfig, ConsensusOutput
from svoc_tpu.ops import sort as sort_ops
from svoc_tpu.ops import stats
from svoc_tpu.robustness.sanitize import quarantine_mask_jax


def consensus_out_specs(axis: str) -> ConsensusOutput:
    """PartitionSpecs of a shard_mapped consensus: per-oracle outputs
    sharded over ``axis``, block outputs replicated."""
    return ConsensusOutput(
        essence=P(),
        essence_first_pass=P(),
        reliability_first_pass=P(),
        reliability_second_pass=P(),
        reliable=P(axis),
        quadratic_risk=P(axis),
        skewness=P(),
        kurtosis=P(),
        interval_valid=P(),
    )


def _consensus_body(cfg: ConsensusConfig, axis: str, gate=None):
    """shard_map body: ``values_local [N/d, M]`` → sharded/replicated outs.

    ``gate=(lo, hi)`` adds the in-graph input-integrity quarantine
    (docs/ROBUSTNESS.md): the admission mask is computed on the
    gathered block (no extra collective — the median gather already
    ships all values), quarantined oracles are excluded from both
    passes and carry a sentinel rank risk, and the body additionally
    returns the local admission mask.  ``gate=None`` compiles to the
    exact ungated program.
    """

    def body(values_local: jnp.ndarray):
        n_local, dim = values_local.shape
        d = jax.lax.psum(1, axis)
        n = n_local * d
        ax = jax.lax.axis_index(axis)

        # Global view for the medians: [N, M], a few KB — one ICI hop.
        values = jax.lax.all_gather(values_local, axis, tiled=True)

        all_mask = jnp.ones(n, dtype=bool)
        if gate is not None:
            ok = quarantine_mask_jax(values, gate[0], gate[1])
            # Neutral-fill BEFORE any arithmetic: masked reductions
            # multiply by 0, and 0 * NaN is NaN.
            values = jnp.where(
                jnp.logical_and(ok[:, None], jnp.isfinite(values)),
                values,
                0.0,
            )
            ok_local = jax.lax.dynamic_slice_in_dim(
                ok, ax * n_local, n_local
            )
            values_local = jax.lax.dynamic_slice_in_dim(
                values, ax * n_local, n_local
            )
            base_mask = ok
            okf_local = ok_local.astype(values.dtype)
            n_ok = jax.lax.psum(jnp.sum(okf_local), axis)
        else:
            base_mask = all_mask

        # ---- FIRST PASS (over the admitted subset when gated) ----
        essence1 = stats.masked_smooth_median(values, base_mask, cfg.smooth_mode)

        # Per-shard risks; scalar mean via psum (no second gather needed
        # for the reliability estimate).
        qr_local = stats.quadratic_risk(values_local, essence1)
        if gate is not None:
            mean_qr = jax.lax.psum(
                jnp.sum(qr_local * okf_local), axis
            ) / jnp.maximum(n_ok, 1.0)
        else:
            mean_qr = jax.lax.psum(jnp.sum(qr_local), axis) / n
        if cfg.constrained:
            rel1 = 1.0 - 2.0 * jnp.sqrt(mean_qr / dim)
        else:
            rel1 = 1.0 - jnp.minimum(cfg.max_spread, jnp.sqrt(mean_qr)) / cfg.max_spread

        # Global rank mask needs all N risks: gather N scalars.
        qr = jax.lax.all_gather(qr_local, axis, tiled=True)
        if gate is not None:
            reliable = sort_ops.gated_reliability_mask(
                qr, base_mask, n_ok.astype(jnp.int32), cfg.n_failing
            )
        else:
            reliable = sort_ops.reliability_mask(qr, cfg.n_failing)

        # ---- SECOND PASS ----
        if cfg.constrained:
            essence2 = stats.masked_smooth_median(values, reliable, cfg.smooth_mode)
        else:
            essence2 = stats.masked_mean(values, reliable)
        # Reference quirk: second-pass risk still centered on essence₁
        # (contract.cairo:414/:484) — reuse qr, re-masked, via psum.
        reliable_local = jax.lax.dynamic_slice_in_dim(
            reliable, ax * n_local, n_local
        )
        n_rel = jax.lax.psum(jnp.sum(reliable_local.astype(qr_local.dtype)), axis)
        masked_qr_sum = jax.lax.psum(jnp.sum(qr_local * reliable_local), axis)
        mean_qr2 = masked_qr_sum / jnp.maximum(n_rel, 1.0)
        if cfg.constrained:
            rel2 = 1.0 - 2.0 * jnp.sqrt(mean_qr2 / dim)
        else:
            rel2 = 1.0 - jnp.minimum(cfg.max_spread, jnp.sqrt(mean_qr2)) / cfg.max_spread

        # ---- MOMENTS over the reliable subset, psum-reduced ----
        w = reliable_local[:, None].astype(values_local.dtype)
        mean_rel = (
            jax.lax.psum(jnp.sum(values_local * w, axis=0), axis)
            / jnp.maximum(n_rel, 1.0)
        )
        centered = (values_local - mean_rel[None, :]) * w
        var = jax.lax.psum(jnp.sum(centered**2, axis=0), axis) / jnp.maximum(
            n_rel, 1.0
        )
        std = jnp.maximum(jnp.sqrt(var), 1e-30)
        z = centered / std[None, :]
        s3 = jax.lax.psum(jnp.sum(z**3, axis=0), axis)
        s4 = jax.lax.psum(jnp.sum(z**4, axis=0), axis)
        denom_s = jnp.maximum((n_rel - 1.0) * (n_rel - 2.0), 1.0)
        skew = s3 * n_rel / denom_s
        t1 = s4 * n_rel * (n_rel + 1.0) / jnp.maximum(n_rel - 1.0, 1.0)
        t2 = 3.0 * (n_rel - 1.0) ** 2
        kurt = (t1 - t2) / jnp.maximum((n_rel - 2.0) * (n_rel - 3.0), 1.0)

        valid = jnp.logical_and(stats.interval_ok(rel1), stats.interval_ok(rel2))
        # Degenerate-block guard, MIRRORING kernel.consensus_step: a
        # "consensus" of fewer than two reliable oracles is no
        # consensus (n_failing >= N-1 must surface invalid, never a
        # confident one-oracle essence).  n is static, so the ungated
        # case folds to a constant.
        if n - cfg.n_failing < 2:
            valid = jnp.logical_and(valid, False)
        if gate is not None:
            # No consensus from fewer than two admitted — or two
            # reliable — oracles (kernel.consensus_step_gated parity).
            valid = jnp.logical_and(valid, n_ok >= 2.0)
            valid = jnp.logical_and(valid, n_rel >= 2.0)
            essence1 = jnp.where(jnp.isfinite(essence1), essence1, 0.0)
            essence2 = jnp.where(jnp.isfinite(essence2), essence2, 0.0)

        out = ConsensusOutput(
            essence=essence2,
            essence_first_pass=essence1,
            reliability_first_pass=rel1,
            reliability_second_pass=rel2,
            reliable=reliable_local,
            quadratic_risk=qr_local,
            skewness=skew,
            kurtosis=kurt,
            interval_valid=valid,
        )
        if gate is not None:
            return out, ok_local
        return out

    return body


def sharded_consensus_fn(
    mesh: Mesh, cfg: ConsensusConfig, axis: str = "oracle"
) -> Callable[[jnp.ndarray], ConsensusOutput]:
    """Jitted two-pass consensus with ``values [N, M]`` sharded over ``axis``.

    Per-oracle outputs (``reliable``, ``quadratic_risk``) come back
    sharded over ``axis``; block outputs (essence, reliabilities,
    moments) replicated.  Semantics identical to
    :func:`svoc_tpu.consensus.kernel.consensus_step`
    (equivalence-tested in ``tests/test_parallel.py``).
    """
    body = _consensus_body(cfg, axis)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis, None),),
        out_specs=consensus_out_specs(axis),
        check_rep=False,
    )
    values_sharding = NamedSharding(mesh, P(axis, None))
    return jax.jit(mapped, in_shardings=(values_sharding,))


def _fleet_body(
    n_oracles: int,
    n_failing: int,
    subset_size: int,
    axis: str,
):
    """Per-device generation of the local oracle shard.

    Mirrors ``gen_oracles_predictions`` (``client/oracle_scheduler.py:
    73-92``): a global random permutation decides which oracle slots are
    the uniform-random failing ones (the post-shuffle view), and every
    oracle's stream is keyed by its *global* index — so the fleet is
    bitwise identical however it is sharded.
    """

    def body(key, window):
        n_local = n_oracles // jax.lax.psum(1, axis)
        ax = jax.lax.axis_index(axis)
        w = window.shape[0]

        # Same key on every device → same permutation (replicated compute).
        perm = jax.random.permutation(jax.random.fold_in(key, 0), n_oracles)
        failing_slot = jnp.zeros(n_oracles, bool).at[perm[:n_failing]].set(True)

        global_idx = ax * n_local + jnp.arange(n_local)

        def one_oracle(i):
            k = jax.random.fold_in(key, i + 1)
            k_fail, k_boot = jax.random.split(k)
            fail_val = jax.random.uniform(k_fail, (window.shape[1],))
            idx = jax.random.choice(k_boot, w, shape=(subset_size,), replace=False)
            boot_val = jnp.mean(window[idx], axis=0)
            return jnp.where(failing_slot[i], fail_val, boot_val)

        values_local = jax.vmap(one_oracle)(global_idx)
        honest_local = ~failing_slot[global_idx]
        return values_local, honest_local

    return body


def fleet_consensus_shard_map(
    mesh: Mesh,
    cfg: ConsensusConfig,
    n_oracles: int,
    subset_size: int = 10,
    axis: str = "oracle",
    gate=None,
):
    """UNJITTED shard_mapped ``(key, window) → (ConsensusOutput,
    honest)`` — the composable fleet+consensus building block
    (:func:`sharded_fleet_step_fn` jits it standalone;
    :mod:`svoc_tpu.parallel.serving` fuses it after the data-parallel
    forward).

    ``gate=(lo, hi)`` wires the in-graph input-integrity quarantine
    into the consensus body (the serving fleet evaluation's defense
    against a poisoned window / corrupt forward — docs/ROBUSTNESS.md);
    the step then returns ``(ConsensusOutput, honest, admitted)`` with
    ``admitted [N]`` sharded over ``axis``.
    """
    n_dev = mesh.devices.size
    if n_oracles % n_dev:
        raise ValueError(f"n_oracles={n_oracles} not divisible by mesh size {n_dev}")
    gen = _fleet_body(n_oracles, cfg.n_failing, subset_size, axis)
    consensus = _consensus_body(cfg, axis, gate)

    if gate is not None:
        def step(key, window):
            values_local, honest_local = gen(key, window)
            out, ok_local = consensus(values_local)
            return out, honest_local, ok_local

        out_specs = (consensus_out_specs(axis), P(axis), P(axis))
    else:
        def step(key, window):
            values_local, honest_local = gen(key, window)
            return consensus(values_local), honest_local

        out_specs = (consensus_out_specs(axis), P(axis))

    return shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=out_specs,
        check_rep=False,
    )


def sharded_fleet_step_fn(
    mesh: Mesh,
    cfg: ConsensusConfig,
    n_oracles: int,
    subset_size: int = 10,
    axis: str = "oracle",
    gate=None,
):
    """Jitted end-to-end simulation step: sentiment window → sharded
    bootstrap fleet → sharded consensus.

    ``(key, window [W, M]) → (ConsensusOutput, honest_mask [N])`` with
    the fleet materialized only as device-local shards — the 1024-oracle
    pod-sim configuration of BASELINE.json.  ``gate`` as in
    :func:`fleet_consensus_shard_map` (adds the ``admitted`` output).
    """
    return jax.jit(
        fleet_consensus_shard_map(mesh, cfg, n_oracles, subset_size, axis, gate)
    )
