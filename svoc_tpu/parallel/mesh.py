"""Mesh construction and sharding policy.

Axis conventions (used consistently across the framework):

- ``"oracle"`` — the fleet axis: N simulated oracles sharded across
  chips (replaces the reference's host loop over ``N_ORACLES``,
  ``client/oracle_scheduler.py:80-87``).
- ``"data"`` — batch/data-parallel axis for transformer inference and
  fine-tuning (comments per step).
- ``"model"`` — tensor-parallel axis for the transformer's feed-forward
  / attention-head dimensions.

A v5e-8 typically runs ``data×oracle = 1×8`` for the pure consensus
simulator and ``data×model = 4×2`` or ``8×1`` for inference; all
factorizations are expressible with :func:`make_mesh`.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


#: Axis names of the 2-D claim-cube mesh (docs/PARALLELISM.md
#: §sharded-claims): claims are pure data parallelism, oracles carry
#: the consensus collectives.
CLAIM_AXIS = "claim"
ORACLE_AXIS = "oracle"

#: Inference/fine-tune axes (module docstring above) and the multi-
#: slice DCN axis of :func:`hybrid_mesh`.  Every ``PartitionSpec`` and
#: collective in the tree must name one of these ``*_AXIS`` constants —
#: the shard-spec lint (SVOC017) joins spec/collective axis names
#: against exactly this set, so a literal that drifts from the mesh is
#: a build failure, not a dispatch-time surprise.
DATA_AXIS = "data"
MODEL_AXIS = "model"
REPLICA_AXIS = "replica"

#: ``SVOC_MESH=<claims>x<oracles>`` — the operator override for
#: :func:`claim_mesh` (resolution order lives in
#: :func:`svoc_tpu.consensus.dispatch.resolve_claim_mesh`).
CLAIM_MESH_ENV = "SVOC_MESH"


class MeshConfigError(ValueError):
    """A claim-mesh spec failed validation (bad ``SVOC_MESH`` form, a
    committed record naming more devices than exist).  Raised with the
    spec, the expected form, and the device inventory in the message."""


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A named mesh factorization, e.g. ``MeshSpec(("data", "oracle"), (2, 4))``."""

    axis_names: Tuple[str, ...]
    axis_sizes: Tuple[int, ...]

    @property
    def n_devices(self) -> int:
        return int(math.prod(self.axis_sizes))


def make_mesh(
    spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a :class:`jax.sharding.Mesh` for ``spec``.

    Uses the first ``spec.n_devices`` of ``devices`` (default
    ``jax.devices()``); raises if not enough are available.
    """
    devs = list(devices if devices is not None else jax.devices())
    need = spec.n_devices
    if len(devs) < need:
        raise ValueError(
            f"mesh {spec} needs {need} devices, only {len(devs)} available"
        )
    grid = np.array(devs[:need]).reshape(spec.axis_sizes)
    return Mesh(grid, spec.axis_names)


def parse_claim_mesh(spec) -> Optional[Tuple[int, int]]:
    """``"<claims>x<oracles>"`` → ``(claims, oracles)``; ``None`` /
    ``""`` / ``"none"`` / ``"off"`` → ``None`` (unsharded dispatch).
    Accepts an already-parsed 2-tuple unchanged.  Anything else raises
    :class:`MeshConfigError` naming the expected form."""
    if spec is None:
        return None
    if isinstance(spec, (tuple, list)):
        if len(spec) != 2:
            raise MeshConfigError(
                f"claim mesh tuple must be (claims, oracles), got {spec!r}"
            )
        claims, oracles = spec
    else:
        text = str(spec).strip().lower()
        if text in ("", "none", "off"):
            return None
        parts = text.split("x")
        if len(parts) != 2:
            raise MeshConfigError(
                f"claim mesh spec {spec!r} is not of the form "
                f"<claims>x<oracles> (e.g. {CLAIM_MESH_ENV}=2x4)"
            )
        try:
            claims, oracles = (int(p) for p in parts)
        except ValueError:
            raise MeshConfigError(
                f"claim mesh spec {spec!r} has non-integer axis sizes "
                f"(expected e.g. {CLAIM_MESH_ENV}=2x4)"
            ) from None
    if claims < 1 or oracles < 1:
        raise MeshConfigError(
            f"claim mesh axes must be >= 1, got {claims}x{oracles}"
        )
    return int(claims), int(oracles)


def claim_mesh(
    spec, devices: Optional[Sequence[jax.Device]] = None
) -> Optional[Mesh]:
    """The 2-D ``(claim, oracle)`` mesh factory for the sharded claim
    cube (:mod:`svoc_tpu.parallel.claim_shard`).

    ``spec`` is a ``"<claims>x<oracles>"`` string (the ``SVOC_MESH``
    form — resolution order env > PERF_DECISIONS.json > unsharded
    lives in :func:`svoc_tpu.consensus.dispatch.resolve_claim_mesh`),
    a ``(claims, oracles)`` tuple, or ``None``/``"none"``/``"off"``
    for no mesh (single-device dispatch).  Returns ``None`` for the
    unsharded case, else a :class:`Mesh` with axes
    ``(CLAIM_AXIS, ORACLE_AXIS)``.

    Multi-host launch mode (stub): a pod launch calls
    :func:`init_distributed` ONCE before any backend use, after which
    ``jax.devices()`` here is the GLOBAL device set and the same spec
    factorizes chips across hosts — no further transport code.  CPU
    tier-1 simulates devices via
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8``; the error
    below names that knob so a laptop run is self-explaining.
    """
    parsed = parse_claim_mesh(spec)
    if parsed is None:
        return None
    claims, oracles = parsed
    devs = list(devices if devices is not None else jax.devices())
    if claims * oracles > len(devs):
        raise MeshConfigError(
            f"claim mesh {claims}x{oracles} needs {claims * oracles} "
            f"devices, only {len(devs)} available — on CPU simulate "
            "devices with XLA_FLAGS=--xla_force_host_platform_device_"
            "count=<n> (set before the first jax import); on a pod "
            "call parallel.mesh.init_distributed() first"
        )
    return make_mesh(
        MeshSpec((CLAIM_AXIS, ORACLE_AXIS), (claims, oracles)), devs
    )


def best_mesh(
    axis_name: str = ORACLE_AXIS, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """A 1-D mesh over every available device — the default fleet layout."""
    devs = list(devices if devices is not None else jax.devices())
    return make_mesh(MeshSpec((axis_name,), (len(devs),)), devs)


def hybrid_mesh(
    ici_spec: MeshSpec,
    dcn_axis: str = REPLICA_AXIS,
    n_slices: Optional[int] = None,
) -> Mesh:
    """Multi-host/multi-slice mesh: ``dcn_axis`` ranges over slices
    (data-center network) and ``ici_spec`` factorizes the chips inside
    each slice (inter-chip interconnect).

    Sharding policy follows from the fabric speeds: put data/replica
    parallelism (one gradient all-reduce per step) on ``dcn_axis`` and
    everything chatty — tensor/sequence/oracle axes, whose collectives
    run per layer or per consensus step — on the ICI axes of
    ``ici_spec``.  This is the TPU-native counterpart of the scale-out
    role NCCL/MPI backends play elsewhere; XLA routes each collective
    over the right fabric from the mesh topology, no transport code.

    With a single slice (or on CPU test backends) this degrades to a
    ``make_mesh`` over ``(dcn_axis=1) × ici_spec``.
    """
    from jax.experimental import mesh_utils

    if n_slices is None:
        # A slice is a granule of devices sharing slice_index — NOT
        # total_devices / ici_size (a single big slice is one slice).
        # Backends without slice_index (CPU test meshes) are one slice.
        slice_ids = {
            getattr(d, "slice_index", 0) for d in jax.devices()
        }
        n_slices = len(slice_ids)
    axis_names = (dcn_axis,) + ici_spec.axis_names
    if n_slices == 1:
        return make_mesh(
            MeshSpec(axis_names, (1,) + ici_spec.axis_sizes)
        )
    # Multi-slice: ici_spec must cover every chip of a slice — the
    # hybrid grid is a dense (n_slices, *ici) block, there is no
    # "use the first k chips" degree of freedom as in make_mesh.
    per_slice = len(jax.devices()) // n_slices
    if ici_spec.n_devices != per_slice:
        raise ValueError(
            f"ici spec {ici_spec} covers {ici_spec.n_devices} chips but "
            f"each of the {n_slices} slices has {per_slice}"
        )
    # create_hybrid_device_mesh requires mesh_shape and dcn_mesh_shape
    # of equal length; the dcn axis is a leading 1 in the ici shape.
    grid = mesh_utils.create_hybrid_device_mesh(
        (1,) + ici_spec.axis_sizes,
        dcn_mesh_shape=(n_slices,) + (1,) * len(ici_spec.axis_sizes),
    )
    return Mesh(grid.reshape((n_slices,) + ici_spec.axis_sizes), axis_names)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> bool:
    """Multi-HOST bring-up: the one call a pod/multi-host launch makes
    before any mesh construction, after which every ``make_mesh`` /
    ``hybrid_mesh`` in this module sees the GLOBAL device set and the
    same jit code scales across hosts (XLA collectives ride ICI within
    a slice and DCN across, per :func:`hybrid_mesh`'s policy — the
    whole of the scale-out role the reference ecosystem delegates to
    NCCL/MPI backends, with no transport code in the framework).

    ``jax.distributed.initialize`` is always ATTEMPTED (it auto-detects
    TPU-pod metadata and Slurm/Open-MPI cluster envs when called with
    no args); a plain single-host run — where detection finds nothing —
    is a documented NO-OP so library code can call this
    unconditionally.  Returns True iff the distributed runtime was (or
    already is) initialized.  Ordering matters: JAX requires the call
    BEFORE anything touches an XLA backend — a late call is a no-op on
    a lone host but raises when a bring-up was explicitly configured,
    never silently degrading a pod into N independent jobs.
    """
    import os

    explicit = any(
        v is not None
        for v in (coordinator_address, process_id, local_device_ids)
    ) or (num_processes or 0) > 1
    env_signal = any(
        os.environ.get(v)
        for v in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS")
    )
    # Both probes below touch jax._src private surfaces, which drift
    # across jax versions in module path AND attribute shape — a
    # missing module (ImportError) or a renamed/removed symbol
    # (AttributeError) must stay a benign single-host no-op, never a
    # crash in every make_mesh caller.
    try:  # tolerate private-API drift across jax versions
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return True  # already initialized by the launcher
    except (ImportError, AttributeError):  # pragma: no cover
        pass
    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            if explicit or env_signal:
                raise RuntimeError(
                    "init_distributed() must run before any JAX backend "
                    "use, but an XLA backend is already live and a "
                    "multi-host bring-up was configured"
                )
            return False  # benign late call on a lone host
    except (ImportError, AttributeError):  # pragma: no cover
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
        return True
    except (RuntimeError, ValueError):
        if explicit or env_signal:
            raise  # a configured bring-up must not fail silently
        return False  # no cluster detected: single-host no-op


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def row_sharded(mesh: Mesh, axis_name: str) -> NamedSharding:
    """Shard the leading array axis over ``axis_name``, replicate the rest."""
    return NamedSharding(mesh, PartitionSpec(axis_name))
