"""2-D (claim × oracle) sharded claim-cube consensus + fleet generation.

ROADMAP item 4 made real for the fabric: the ``[C, N, M]`` gated claim
cube of docs/FABRIC.md — until now a single-device dispatch — shards
over a 2-D mesh (:func:`svoc_tpu.parallel.mesh.claim_mesh`,
``SVOC_MESH=<claims>x<oracles>``):

- **claim axis** — pure data parallelism: claims are independent
  markets, so there are ZERO cross-claim collectives; a ``claim``-axis
  shard serves ``C / mesh_claims`` claims and never sees its siblings.
- **oracle axis** — the :mod:`svoc_tpu.parallel.sharded` all_gather
  discipline generalized to carry the claim axis and the PR 4/6
  quarantine masks ``ok[C, N]``: the two-pass estimator's medians and
  rank mask need a global per-claim view, so the body all-gathers the
  ``[Cl, N, M]`` block over the oracle axis (KBs per claim — rides
  ICI) and runs the LITERAL single-device gated kernel on it, while
  the per-oracle bootstrap fleet generation and the at-rest cube
  storage stay on the device-local ``N / mesh_oracles`` shard.

**Exact-parity contract.** Sharded-vs-single parity on the DISPATCH
path is BITWISE (``parity_max_abs_diff == 0.0``, the ``bench.py
--claims C --mesh CxO`` acceptance bar).  That bar is unforgiving:
float addition is non-associative, so psum-of-partial-sums reductions
(the ``sharded.py`` body shape) differ from the single-device
reduction in the last ulp — and it is not just reduction order:
merely *adding* an ``all_gather``/``dynamic_slice`` around the
otherwise-identical kernel changes XLA's fusion rounding (a measured
one-ulp ``reliability_second_pass`` divergence on the constrained
config killed two drafts of this body, including an
``optimization_barrier``-fenced one).  Therefore:

- :func:`sharded_claims_consensus_fn` — the fabric's host-fed cube
  dispatch — partitions the CLAIM axis only: each shard runs the
  literal :func:`consensus_step_gated_batched` program on its
  ``[Cl, N, M]`` slice with zero collectives in the body, so the
  compiled per-claim math is the single-device program and parity is
  exact by construction (pinned in ``tests/test_claim_shard.py``).
  The oracle axis replicates a host-fed block — partitioning it buys
  a host-fed dispatch nothing and measurably breaks bitwise parity.
- :func:`sharded_fleet_claims_fn` — the simulation path, where the
  cube is BORN on device — shards generation over both axes and
  all-gathers each claim's ``[N, M]`` block for the consensus (the
  arxiv 2112.09017 on-chip-block regime); its parity contract is the
  ``_fleet_body`` one: results are bitwise INVARIANT across mesh
  factorizations (1x1 included), not bitwise-equal to the separately
  compiled host-path program.

This is the arxiv 2004.13336 partition split applied with the
opposite emphasis: the replicated computation (per-claim consensus
over KB-sized blocks) is cheap, so it is the per-oracle generation
work and the cube's at-rest footprint that get partitioned — and the
claim axis, with zero cross-claim collectives, that carries the
throughput scaling.

**Sharded fleet generation.** No replica ever materializes the full
``[C, N, M]`` cube: each device generates only its local
``[Cl, Nl, M]`` bootstrap-resample block, keyed by GLOBAL claim and
oracle indices (:func:`svoc_tpu.sim.generators.claim_fleet_keys`,
crc32-salted ``fold_in`` — the ``_fleet_body`` contract of
``parallel/sharded.py``) so the fleet is bitwise identical however it
is sharded.  The gathered per-claim ``[N, M]`` median block is the
largest array any replica holds: ``C/mesh_claims × N × M`` floats,
``1/mesh_claims`` of the cube.

``consensus_impl`` composition (docs/FABRIC.md §consensus_impl): the
Pallas fused kernel runs PER-SHARD inside shard_map when the oracle
axis is unsharded (``mesh_oracles == 1`` — each shard then holds whole
fleets for its claims); an oracle-sharded mesh cannot feed it partial
fleets, so a pallas route there is a counted
``consensus_pallas_fallback{reason="sharded_unsupported"}`` and the XLA
body serves, never silently.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from svoc_tpu.consensus.kernel import (
    ConsensusConfig,
    ConsensusOutput,
    _mask_padded_claims,
    consensus_step_gated_batched,
)
from svoc_tpu.parallel.mesh import CLAIM_AXIS, ORACLE_AXIS
from svoc_tpu.parallel.sharded import shard_map
from svoc_tpu.robustness.sanitize import (
    quarantine_mask_claims,
    quarantine_mask_jax,
)
from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry

_log = logging.getLogger("svoc_tpu.parallel.claim_shard")

#: Counter for cube dispatches the mesh cannot shard (a fleet size not
#: divisible by the oracle axis, a claim count the caller failed to pad
#: — see :func:`svoc_tpu.consensus.batch.pad_claim_cube`'s
#: ``multiple_of``): the dispatch falls back to the single-device cube
#: and is COUNTED, never silent — the ``shard-smoke`` gate asserts this
#: stays at zero for a mesh-pinned scenario.
SHARD_FALLBACK_COUNTER = "claim_shard_fallback"
#: Counter for dispatches the mesh actually served (the smoke's
#: "sharding really ran" witness).
SHARD_DISPATCH_COUNTER = "claim_shard_dispatches"


def claims_out_specs(oracle_sharded: bool = False) -> ConsensusOutput:
    """PartitionSpecs of the shard-mapped claim cube: per-claim fields
    sharded over the claim axis; per-oracle fields over both axes on
    the fleet path (``oracle_sharded=True``), claim-only on the
    host-fed dispatch path."""
    per_oracle = (
        P(CLAIM_AXIS, ORACLE_AXIS) if oracle_sharded else P(CLAIM_AXIS, None)
    )
    return ConsensusOutput(
        essence=P(CLAIM_AXIS),
        essence_first_pass=P(CLAIM_AXIS),
        reliability_first_pass=P(CLAIM_AXIS),
        reliability_second_pass=P(CLAIM_AXIS),
        reliable=per_oracle,
        quadratic_risk=per_oracle,
        skewness=P(CLAIM_AXIS),
        kurtosis=P(CLAIM_AXIS),
        interval_valid=P(CLAIM_AXIS),
    )


def _host_cube_body(cfg: ConsensusConfig, gate=None):
    """shard_map body of the host-fed cube dispatch: ``[Cl, N, M]``
    claim slices through the LITERAL single-device batched kernel —
    zero collectives, so the compiled per-claim math (and therefore
    every output bit) matches the single-device program (the
    exact-parity contract in the module docstring).  ``gate=(lo, hi)``
    fuses the in-graph quarantine twin (the
    ``claims_consensus_sanitized`` composition) — each shard holds its
    claims' full blocks, so the gate needs no collective either."""

    def body(values_local, ok_local, claim_mask_local):
        if gate is not None:
            ok_local = quarantine_mask_claims(
                values_local, gate[0], gate[1]
            )
        out = consensus_step_gated_batched(values_local, ok_local, cfg)
        out = _mask_padded_claims(out, claim_mask_local)
        if gate is not None:
            return out, ok_local
        return out

    return body


def sharded_claims_consensus_fn(mesh: Mesh, cfg: ConsensusConfig):
    """Jitted gated claim-cube consensus with ``values [C, N, M]`` /
    ``ok [C, N]`` / ``claim_mask [C]`` partitioned over the mesh claim
    axis (pure data parallelism — zero cross-claim collectives).

    ``C`` must divide by the mesh claim axis (pad with
    :func:`svoc_tpu.consensus.batch.pad_claim_cube` ``multiple_of=``).
    Semantics — including padded-row invalidation via the shared
    ``_mask_padded_claims`` — are BITWISE identical to the
    single-device
    :func:`svoc_tpu.consensus.kernel.consensus_step_gated_claims`
    dispatch (``tests/test_claim_shard.py`` pins 0.0 max-abs-diff,
    both configs).
    """
    body = _host_cube_body(cfg)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(CLAIM_AXIS, None, None),
            P(CLAIM_AXIS, None),
            P(CLAIM_AXIS),
        ),
        out_specs=claims_out_specs(),
        check_rep=False,
    )
    return jax.jit(
        mapped,
        in_shardings=(
            NamedSharding(mesh, P(CLAIM_AXIS, None, None)),
            NamedSharding(mesh, P(CLAIM_AXIS, None)),
            NamedSharding(mesh, P(CLAIM_AXIS)),
        ),
    )


def sharded_claims_sanitized_fn(
    mesh: Mesh,
    cfg: ConsensusConfig,
    lo: Optional[float],
    hi: Optional[float],
):
    """Claim-sharded twin of
    :func:`svoc_tpu.consensus.batch.claims_consensus_sanitized`: the
    in-graph quarantine gate and the gated kernel fused in ONE
    shard-mapped program per micro-batch, returning ``(output, ok)``
    so the router's admission accounting still reads the traced
    masks."""
    body = _host_cube_body(cfg, gate=(lo, hi))
    # The gate recomputes ok in-graph, so the mapped surface takes
    # (values, claim_mask) only — the body's ok operand is unused.
    mapped = shard_map(
        lambda v, m: body(v, None, m),
        mesh=mesh,
        in_specs=(P(CLAIM_AXIS, None, None), P(CLAIM_AXIS)),
        out_specs=(claims_out_specs(), P(CLAIM_AXIS, None)),
        check_rep=False,
    )
    return jax.jit(
        mapped,
        in_shardings=(
            NamedSharding(mesh, P(CLAIM_AXIS, None, None)),
            NamedSharding(mesh, P(CLAIM_AXIS)),
        ),
    )


def _pallas_claims_body(cfg: ConsensusConfig):
    """shard_map body for a claims-only mesh (oracle axis == 1): each
    shard holds whole fleets for its claims, so the fused Pallas kernel
    (docs/PARALLELISM.md §pallas-consensus) runs per-shard unchanged."""
    from svoc_tpu.ops import pallas_consensus as pallas_ops

    def body(values_local, ok_local, claim_mask_local):
        return pallas_ops.fused_consensus_gated_claims(
            values_local, ok_local, claim_mask_local, cfg
        )

    return body


def sharded_claims_pallas_fn(mesh: Mesh, cfg: ConsensusConfig):
    """Jitted claims-only-sharded dispatch of the fused Pallas kernel —
    the ``consensus_impl="pallas"`` × sharding composition for meshes
    whose oracle axis is 1.  Eligibility (fleet size, backend,
    interpret opt-in) is the dispatcher's job; see
    :meth:`ClaimShardDispatcher.dispatch_gated`."""
    body = _pallas_claims_body(cfg)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(CLAIM_AXIS, None, None),
            P(CLAIM_AXIS, None),
            P(CLAIM_AXIS),
        ),
        out_specs=ConsensusOutput(
            essence=P(CLAIM_AXIS),
            essence_first_pass=P(CLAIM_AXIS),
            reliability_first_pass=P(CLAIM_AXIS),
            reliability_second_pass=P(CLAIM_AXIS),
            reliable=P(CLAIM_AXIS, None),
            quadratic_risk=P(CLAIM_AXIS, None),
            skewness=P(CLAIM_AXIS),
            kurtosis=P(CLAIM_AXIS),
            interval_valid=P(CLAIM_AXIS),
        ),
        check_rep=False,
    )
    return jax.jit(
        mapped,
        in_shardings=(
            NamedSharding(mesh, P(CLAIM_AXIS, None, None)),
            NamedSharding(mesh, P(CLAIM_AXIS, None)),
            NamedSharding(mesh, P(CLAIM_AXIS)),
        ),
    )


# ---------------------------------------------------------------------------
# Sharded bootstrap-resample fleet generation over the claim cube.
# ---------------------------------------------------------------------------


def _fleet_cube_body(cfg: ConsensusConfig, gate=None):
    """Consensus half of the 2-D-sharded fleet step: the device-local
    ``[Cl, Nl, M]`` generated shard is all-gathered per claim over the
    oracle axis (``[Cl, N, M]`` — the only collective) and runs the
    batched gated kernel; per-oracle outputs slice back to the local
    rows.  ``gate=(lo, hi)`` computes admission masks on the gathered
    block (no extra collective).  Parity contract: bitwise INVARIANT
    across mesh factorizations (module docstring), certified in
    ``tests/test_claim_shard.py``."""

    def body(values_local, ok_local, claim_mask_local):
        n_local = values_local.shape[1]
        ax = jax.lax.axis_index(ORACLE_AXIS)
        values = jax.lax.all_gather(
            values_local, ORACLE_AXIS, axis=1, tiled=True
        )
        if gate is not None:
            ok = jax.vmap(
                lambda v: quarantine_mask_jax(v, gate[0], gate[1])
            )(values)
            ok_local = jax.lax.dynamic_slice_in_dim(
                ok, ax * n_local, n_local, axis=1
            )
        else:
            ok = jax.lax.all_gather(
                ok_local, ORACLE_AXIS, axis=1, tiled=True
            )
        out = consensus_step_gated_batched(values, ok, cfg)
        out = _mask_padded_claims(out, claim_mask_local)
        out = out._replace(
            reliable=jax.lax.dynamic_slice_in_dim(
                out.reliable, ax * n_local, n_local, axis=1
            ),
            quadratic_risk=jax.lax.dynamic_slice_in_dim(
                out.quadratic_risk, ax * n_local, n_local, axis=1
            ),
        )
        if gate is not None:
            return out, ok_local
        return out

    return body


def one_claim_fleet(
    key,
    window: jnp.ndarray,
    n_oracles: int,
    n_failing: int,
    subset_size: int,
    oracle_idx: jnp.ndarray,
):
    """One claim's bootstrap fleet rows for the GLOBAL oracle indices
    ``oracle_idx`` — the ``_fleet_body`` contract
    (``parallel/sharded.py``): the failing-slot permutation derives
    from the claim key replicated on every shard, and every oracle's
    stream is keyed by its global index, so the generated fleet is
    bitwise identical however (and whether) it is sharded.  Shared by
    the shard_map body and the single-device reference below — one
    implementation, no drift."""
    w = window.shape[0]
    perm = jax.random.permutation(jax.random.fold_in(key, 0), n_oracles)
    failing_slot = (
        jnp.zeros(n_oracles, bool).at[perm[:n_failing]].set(True)
    )

    def one_oracle(i):
        k = jax.random.fold_in(key, i + 1)
        k_fail, k_boot = jax.random.split(k)
        fail_val = jax.random.uniform(k_fail, (window.shape[1],))
        idx = jax.random.choice(
            k_boot, w, shape=(subset_size,), replace=False
        )
        boot_val = jnp.mean(window[idx], axis=0)
        return jnp.where(failing_slot[i], fail_val, boot_val)

    values = jax.vmap(one_oracle)(oracle_idx)
    honest = ~failing_slot[oracle_idx]
    return values, honest


def fleet_claims_reference(
    keys: jnp.ndarray,
    windows: jnp.ndarray,
    n_oracles: int,
    n_failing: int,
    subset_size: int = 10,
):
    """Single-device fleet cube ``(values [C, N, M], honest [C, N])``
    from per-claim keys (:func:`svoc_tpu.sim.generators.claim_fleet_keys`)
    — the parity oracle the sharded generation is bitwise-tested
    against."""
    idx = jnp.arange(n_oracles)
    return jax.vmap(
        lambda k, win: one_claim_fleet(
            k, win, n_oracles, n_failing, subset_size, idx
        )
    )(keys, windows)


def sharded_fleet_claims_fn(
    mesh: Mesh,
    cfg: ConsensusConfig,
    n_oracles: int,
    subset_size: int = 10,
    gate: Optional[Tuple[Optional[float], Optional[float]]] = None,
):
    """Jitted end-to-end sharded claim simulation: per-claim windows →
    per-shard bootstrap fleets → 2-D-sharded gated consensus.

    ``(keys [C, 2] uint32, windows [C, W, M]) →
    (ConsensusOutput, honest [C, N])`` (plus ``admitted [C, N]`` when
    ``gate=(lo, hi)`` wires the in-graph quarantine).  The fleet only
    ever exists as device-local ``[Cl, Nl, M]`` shards — no replica
    materializes the full cube (``tests/test_claim_shard.py`` asserts
    the live-bytes bound via the PR 1 ``jax.live_arrays`` gauge).
    """
    mesh_claims = mesh.shape[CLAIM_AXIS]
    mesh_oracles = mesh.shape[ORACLE_AXIS]
    if n_oracles % mesh_oracles:
        raise ValueError(
            f"n_oracles={n_oracles} not divisible by the mesh oracle "
            f"axis {mesh_oracles}"
        )
    del mesh_claims  # claim divisibility is checked by shard_map itself
    consensus = _fleet_cube_body(cfg, gate=gate)

    def step(keys_local, windows_local):
        n_local = n_oracles // mesh_oracles
        ax = jax.lax.axis_index(ORACLE_AXIS)
        oracle_idx = ax * n_local + jnp.arange(n_local)
        values_local, honest_local = jax.vmap(
            lambda k, win: one_claim_fleet(
                k, win, n_oracles, cfg.n_failing, subset_size, oracle_idx
            )
        )(keys_local, windows_local)
        c_local = values_local.shape[0]
        claim_mask_local = jnp.ones(c_local, dtype=bool)
        if gate is not None:
            out, ok_local = consensus(
                values_local, None, claim_mask_local
            )
            return out, honest_local, ok_local
        ones = jnp.ones((c_local, n_local), dtype=bool)
        return consensus(values_local, ones, claim_mask_local), honest_local

    per_oracle = P(CLAIM_AXIS, ORACLE_AXIS)
    if gate is not None:
        out_specs = (
            claims_out_specs(oracle_sharded=True),
            per_oracle,
            per_oracle,
        )
    else:
        out_specs = (claims_out_specs(oracle_sharded=True), per_oracle)
    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(P(CLAIM_AXIS, None), P(CLAIM_AXIS, None, None)),
        out_specs=out_specs,
        check_rep=False,
    )
    return jax.jit(
        mapped,
        in_shardings=(
            NamedSharding(mesh, P(CLAIM_AXIS, None)),
            NamedSharding(mesh, P(CLAIM_AXIS, None, None)),
        ),
    )


# ---------------------------------------------------------------------------
# The fabric-facing dispatcher: mesh resolved once, fallbacks counted.
# ---------------------------------------------------------------------------


class ClaimShardDispatcher:
    """The mesh-aware claim-cube dispatch tier the
    :class:`~svoc_tpu.fabric.router.ClaimRouter` owns.

    Built ONCE at router construction with the pinned mesh (the replay
    rule of docs/FABRIC.md §mesh — the mesh, like ``consensus_impl``,
    is part of a seeded replay's config and must not drift mid-run).
    ``dispatch_gated`` returns device arrays WITHOUT a host sync, so
    the router's double-buffered (pipelined) mode can overlap the
    collectives with the next micro-batch's host work.
    """

    def __init__(
        self,
        mesh: Mesh,
        *,
        consensus_impl: Optional[str] = None,
        metrics: Optional[MetricsRegistry] = None,
    ):
        if CLAIM_AXIS not in mesh.shape or ORACLE_AXIS not in mesh.shape:
            raise ValueError(
                f"claim-shard mesh needs axes ({CLAIM_AXIS!r}, "
                f"{ORACLE_AXIS!r}); got {tuple(mesh.shape)}"
            )
        self.mesh = mesh
        self.consensus_impl = consensus_impl
        self._metrics = metrics or _default_registry
        self._fns: Dict[tuple, object] = {}
        self._lock = threading.Lock()
        self._warned: set = set()

    @property
    def claim_size(self) -> int:
        return int(self.mesh.shape[CLAIM_AXIS])

    @property
    def oracle_size(self) -> int:
        return int(self.mesh.shape[ORACLE_AXIS])

    @property
    def spec_str(self) -> str:
        """The ``SVOC_MESH`` form of the pinned mesh, for snapshots."""
        return f"{self.claim_size}x{self.oracle_size}"

    def _fallback(self, reason: str, detail: str = "") -> None:
        self._metrics.counter(
            SHARD_FALLBACK_COUNTER, labels={"reason": reason}
        ).add(1)
        with self._lock:
            if reason in self._warned:
                return
            self._warned.add(reason)
        _log.warning(
            "claim-cube dispatch fell back to the single-device path "
            "(mesh=%s, reason=%s%s); further fallbacks are counted in "
            "%s{reason=%s} without logging",
            self.spec_str,
            reason,
            f": {detail}" if detail else "",
            SHARD_FALLBACK_COUNTER,
            reason,
        )

    def _sharded_fn(self, key, builder):
        with self._lock:
            fn = self._fns.get(key)
        if fn is None:
            fn = builder()
            with self._lock:
                self._fns.setdefault(key, fn)
                fn = self._fns[key]
        return fn

    def _gated_fn(self, cfg: ConsensusConfig, pallas: bool):
        return self._sharded_fn(
            ("gated", cfg, pallas),
            lambda: (
                sharded_claims_pallas_fn(self.mesh, cfg)
                if pallas
                else sharded_claims_consensus_fn(self.mesh, cfg)
            ),
        )

    def _sanitized_fn(self, cfg: ConsensusConfig, lo, hi):
        return self._sharded_fn(
            ("sanitized", cfg, lo, hi),
            lambda: sharded_claims_sanitized_fn(self.mesh, cfg, lo, hi),
        )

    def shardable(self, n_claims: int, n_oracles: int) -> Optional[str]:
        """None when the cube fits the mesh, else the fallback reason."""
        if n_claims % self.claim_size:
            return "claim_indivisible"
        if n_oracles % self.oracle_size:
            return "oracle_indivisible"
        return None

    def dispatch_gated(
        self, values, ok, claim_mask, cfg: ConsensusConfig
    ) -> ConsensusOutput:
        """One mesh-sharded gated cube dispatch (device outputs, no
        sync).  A cube the mesh cannot shard falls back — counted — to
        the single-device :func:`claims_consensus_gated` path, which
        itself honors ``consensus_impl``."""
        from svoc_tpu.consensus import batch as _batch

        values = jnp.asarray(values)
        ok = jnp.asarray(ok)
        claim_mask = jnp.asarray(claim_mask)
        c, n, _m = values.shape
        reason = self.shardable(c, n)
        if reason is not None:
            self._fallback(reason, detail=f"cube {c}x{n}")
            return _batch.claims_consensus_gated(
                values,
                ok,
                claim_mask,
                cfg,
                consensus_impl=self.consensus_impl,
                metrics=self._metrics,
            )
        pallas = _batch._pallas_route(
            values,
            cfg,
            self.consensus_impl,
            self._metrics,
            "sharded_claims_consensus",
        )
        if pallas and self.oracle_size > 1:
            # Partial fleets cannot feed the fused kernel: an
            # oracle-sharded pallas route is a counted fallback to the
            # XLA sharded body (docs/FABRIC.md §consensus_impl).
            from svoc_tpu.consensus.dispatch import report_pallas_fallback

            report_pallas_fallback(
                "sharded_unsupported",
                op="sharded_claims_consensus",
                detail=f"mesh {self.spec_str} shards the oracle axis",
                metrics=self._metrics,
            )
            pallas = False
        try:
            out = self._gated_fn(cfg, pallas)(values, ok, claim_mask)
        except Exception as e:  # noqa: BLE001 — counted, then the single-device path re-raises real input errors
            if pallas:
                _batch._pallas_broke(
                    values, cfg, e, self._metrics, "sharded_claims_consensus"
                )
                out = self._gated_fn(cfg, False)(values, ok, claim_mask)
            else:
                self._fallback("shard_error", detail=f"{type(e).__name__}: {e}")
                return _batch.claims_consensus_gated(
                    values,
                    ok,
                    claim_mask,
                    cfg,
                    consensus_impl="xla",
                    metrics=self._metrics,
                )
        self._metrics.counter(SHARD_DISPATCH_COUNTER).add(1)
        return out

    def dispatch_sanitized(
        self, values, claim_mask, cfg: ConsensusConfig, lo, hi
    ):
        """Mesh-sharded gate+consensus fusion
        (:func:`sharded_claims_sanitized_fn`) — the serving tier's
        dispatch shape.  Returns ``(ConsensusOutput, ok)`` device
        arrays, no sync.  Falls back (counted) to the single-device
        :func:`claims_consensus_sanitized` when the cube does not fit
        the mesh.  A pallas route composes as in
        :func:`claims_consensus_sanitized`: the traced gate's masks
        feed the per-shard fused kernel when the oracle axis is
        unsharded, else ``sharded_unsupported``."""
        from svoc_tpu.consensus import batch as _batch

        values = jnp.asarray(values)
        claim_mask = jnp.asarray(claim_mask)
        c, n, _m = values.shape
        reason = self.shardable(c, n)
        if reason is not None:
            self._fallback(reason, detail=f"cube {c}x{n}")
            return _batch.claims_consensus_sanitized(
                values,
                claim_mask,
                cfg,
                lo,
                hi,
                consensus_impl=self.consensus_impl,
                metrics=self._metrics,
            )
        pallas = _batch._pallas_route(
            values,
            cfg,
            self.consensus_impl,
            self._metrics,
            "sharded_claims_sanitized",
        )
        if pallas and self.oracle_size > 1:
            from svoc_tpu.consensus.dispatch import report_pallas_fallback

            report_pallas_fallback(
                "sharded_unsupported",
                op="sharded_claims_sanitized",
                detail=f"mesh {self.spec_str} shards the oracle axis",
                metrics=self._metrics,
            )
            pallas = False
        try:
            if pallas:
                ok = _batch._quarantine_claims_jit(values, lo, hi)
                out = self._gated_fn(cfg, True)(values, ok, claim_mask)
            else:
                out, ok = self._sanitized_fn(cfg, lo, hi)(
                    values, claim_mask
                )
        except Exception as e:  # noqa: BLE001 — counted, then the single-device path re-raises real input errors
            if pallas:
                _batch._pallas_broke(
                    values, cfg, e, self._metrics, "sharded_claims_sanitized"
                )
                out, ok = self._sanitized_fn(cfg, lo, hi)(
                    values, claim_mask
                )
            else:
                self._fallback("shard_error", detail=f"{type(e).__name__}: {e}")
                return _batch.claims_consensus_sanitized(
                    values,
                    claim_mask,
                    cfg,
                    lo,
                    hi,
                    consensus_impl="xla",
                    metrics=self._metrics,
                )
        self._metrics.counter(SHARD_DISPATCH_COUNTER).add(1)
        return out, ok
