"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context capability absent from the reference (its only sequences
are ≤512-token tokenizer outputs, SURVEY.md §5), built TPU-first: the
sequence axis is sharded over the mesh, each device holds a Q/K/V block,
and K/V blocks rotate around the ring via ``jax.lax.ppermute`` while a
streaming (flash-style) softmax accumulates exact results — O(T/d)
memory per device, compute/communication overlapped by XLA, collectives
riding ICI neighbor links.

The streaming accumulator is the standard online-softmax recurrence: for
each incoming K/V block, rescale the running numerator/denominator by
``exp(m_old − m_new)`` where ``m`` is the running row max.  Exactness
(vs a monolithic softmax) is tested on an 8-device CPU mesh in
``tests/test_ring_attention.py``.

Layout: ``[batch, seq_shard, heads, head_dim]`` blocks, matching the
encoder's attention layout (:mod:`svoc_tpu.models.encoder`).  Key
padding masks travel around the ring with their K/V blocks.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from svoc_tpu.parallel.sharded import shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, kmask, scale):
    """Scores + masked exp-stats for one K/V block.

    Returns ``(m_blk [B,H,Tq], p [B,H,Tq,Tk], pv [B,Tq,H,D])`` where
    ``p`` is un-normalized exp(scores − m_blk)."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    scores = jnp.where(kmask[:, None, None, :] > 0, scores, NEG_INF)
    m_blk = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m_blk[..., None])
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return m_blk, p, pv


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kmask: Optional[jnp.ndarray] = None,
    *,
    axis_name: str,
    block_impl: str = "dense",
) -> jnp.ndarray:
    """Exact non-causal attention with K/V rotating over ``axis_name``.

    Call inside ``shard_map``: every argument is the device-local block
    ``q/k/v [B, T_local, H, D]``, ``kmask [B, T_local]`` (1 = real
    token).  Returns the local output block ``[B, T_local, H, D]``.

    ``block_impl`` picks the per-hop attention over the resident Q block
    and the rotating K/V block:

    - ``"dense"`` — XLA einsum chain; materializes a
      ``[B,H,T_local,T_local]`` score block per hop.  Right choice for
      short local blocks.
    - ``"flash"`` — the Pallas online-softmax kernel
      (:func:`svoc_tpu.ops.pallas_attention.flash_attention`) with
      ``return_lse``; hop outputs merge via log-sum-exp.  At long local
      blocks this avoids the per-hop score materialization entirely
      (honest probe: 49× vs dense at T=8192, ``FLASH_PROBE.json``) —
      the ring-outer/flash-inner long-context composition.
    """
    if kmask is None:
        kmask = jnp.ones(k.shape[:2], dtype=jnp.int32)
    n_dev = jax.lax.psum(1, axis_name)
    b, t_local, h, d = q.shape
    scale = jnp.float32(1.0) / jnp.sqrt(jnp.float32(d))

    def run_ring(accumulate, carry0):
        """The ring protocol: local block first, then n_dev−1 rotations
        of K/V (+ padding mask) — no discarded final hop.  One driver
        for every block_impl so the rotation can never diverge."""
        carry = accumulate(k, v, kmask, carry0)

        def step(i, state):
            k_blk, v_blk, mask_blk, carry = state
            perm = [(s, (s + 1) % n_dev) for s in range(n_dev)]
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
            mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
            return (k_blk, v_blk, mask_blk, accumulate(k_blk, v_blk, mask_blk, carry))

        state = jax.lax.fori_loop(0, n_dev - 1, step, (k, v, kmask, carry))
        return state[3]

    if block_impl == "flash":
        from svoc_tpu.ops.pallas_attention import flash_attention

        def accumulate_flash(k_blk, v_blk, mask_blk, carry):
            o, lse = carry
            o_b, lse_b = flash_attention(
                q, k_blk, v_blk, mask_blk, return_lse=True
            )  # o_b [B,T,H,D], lse_b [B,T,H]; fully-masked rows: 0/-inf
            lse_new = jnp.logaddexp(lse, lse_b)
            # Guard the all--inf case (every key so far is padding):
            # exp(-inf − -inf) would be NaN.  Double-where so the
            # untaken branch never materializes the NaN either — a bare
            # outer where would still poison gradients through its
            # cotangent if this path is ever differentiated.
            dead = jnp.isneginf(lse_new)
            d_old = jnp.where(dead, 0.0, lse - lse_new)
            d_new = jnp.where(dead, 0.0, lse_b - lse_new)
            w_old = jnp.where(dead, 0.0, jnp.exp(d_old))[..., None]
            w_new = jnp.where(dead, 0.0, jnp.exp(d_new))[..., None]
            return o * w_old + o_b.astype(jnp.float32) * w_new, lse_new

        o, _lse = run_ring(
            accumulate_flash,
            (
                jnp.zeros((b, t_local, h, d), jnp.float32),
                jnp.full((b, t_local, h), -jnp.inf, jnp.float32),
            ),
        )
        return o.astype(q.dtype)
    if block_impl != "dense":
        raise ValueError(f"unknown block_impl {block_impl!r}")

    def accumulate_dense(k_blk, v_blk, mask_blk, carry):
        m, l, o = carry
        m_blk, p, pv = _block_attn(q, k_blk, v_blk, mask_blk, scale)
        m_new = jnp.maximum(m, m_blk)
        corr = jnp.exp(m - m_new)
        corr_blk = jnp.exp(m_blk - m_new)
        l = l * corr + jnp.sum(p, axis=-1) * corr_blk
        # corr is [B,H,Tq] — broadcast onto the [B,Tq,H,D] accumulator.
        corr_o = jnp.transpose(corr, (0, 2, 1))[..., None]
        corr_pv = jnp.transpose(corr_blk, (0, 2, 1))[..., None]
        o = o * corr_o + pv.astype(jnp.float32) * corr_pv
        return m_new, l, o

    # Running stats: row max m, denominator l, numerator o.
    m, l, o = run_ring(
        accumulate_dense,
        (
            jnp.full((b, h, t_local), NEG_INF, jnp.float32),
            jnp.zeros((b, h, t_local), jnp.float32),
            jnp.zeros((b, t_local, h, d), jnp.float32),
        ),
    )
    l_t = jnp.transpose(l, (0, 2, 1))[..., None]  # [B,Tq,H,1]
    return (o / jnp.maximum(l_t, 1e-30)).astype(q.dtype)


def ring_attention_fn(
    mesh: Mesh, seq_axis: str = "seq", block_impl: str = "dense"
) -> Callable[..., jnp.ndarray]:
    """Jitted ``(q, k, v, kmask) → out`` with the sequence dimension
    sharded over ``seq_axis`` (batch/head dims replicated; compose with
    data sharding by passing a multi-axis mesh and sharded inputs).
    ``block_impl="flash"`` uses the Pallas kernel per hop (long-context
    composition — see :func:`ring_attention`)."""
    spec = P(None, seq_axis, None, None)
    mask_spec = P(None, seq_axis)

    def body(q, k, v, kmask):
        return ring_attention(
            q, k, v, kmask, axis_name=seq_axis, block_impl=block_impl
        )

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec, mask_spec),
        out_specs=spec,
        check_rep=False,
    )
    return jax.jit(mapped)


def dense_attention_reference(q, k, v, kmask=None):
    """Monolithic-softmax reference for equivalence tests (the encoder's
    attention math, :class:`svoc_tpu.models.encoder.SelfAttention`)."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if kmask is not None:
        scores = jnp.where(kmask[:, None, None, :] > 0, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
