"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context capability absent from the reference (its only sequences
are ≤512-token tokenizer outputs, SURVEY.md §5), built TPU-first: the
sequence axis is sharded over the mesh, each device holds a Q/K/V block,
and K/V blocks rotate around the ring via ``jax.lax.ppermute`` while a
streaming (flash-style) softmax accumulates exact results — O(T/d)
memory per device, compute/communication overlapped by XLA, collectives
riding ICI neighbor links.

The streaming accumulator is the standard online-softmax recurrence: for
each incoming K/V block, rescale the running numerator/denominator by
``exp(m_old − m_new)`` where ``m`` is the running row max.  Exactness
(vs a monolithic softmax) is tested on an 8-device CPU mesh in
``tests/test_ring_attention.py``.

Layout: ``[batch, seq_shard, heads, head_dim]`` blocks, matching the
encoder's attention layout (:mod:`svoc_tpu.models.encoder`).  Key
padding masks travel around the ring with their K/V blocks.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from svoc_tpu.parallel.sharded import shard_map

NEG_INF = -1e30


def _ring_protocol(axis_name, n_dev, rotating, carry, update):
    """THE ring rotation driver — the single place the permutation
    lives, so forward, forward-with-stats, and the two-pass backward can
    never diverge.  ``update(rotating, carry) → (rotating, carry)`` is
    applied to the local blocks first, then after each of the
    ``n_dev − 1`` rotations of every array in ``rotating`` (a pytree;
    the backward rotates its dk/dv accumulators alongside the K/V
    blocks by returning them updated from ``update``)."""
    rotating, carry = update(rotating, carry)

    def step(i, state):
        rot, c = state
        rot = ring_rotate(rot, axis_name, n_dev)
        return update(rot, c)

    return jax.lax.fori_loop(0, n_dev - 1, step, (rotating, carry))


def ring_rotate(tree, axis_name, n_dev):
    """One forward rotation (shard s → s+1) of every array in ``tree``."""
    perm = [(s, (s + 1) % n_dev) for s in range(n_dev)]
    return jax.tree_util.tree_map(
        lambda a: jax.lax.ppermute(a, axis_name, perm), tree
    )


def _block_attn(q, k, v, kmask, scale):
    """Scores + masked exp-stats for one K/V block.

    Returns ``(m_blk [B,H,Tq], p [B,H,Tq,Tk], pv [B,Tq,H,D])`` where
    ``p`` is un-normalized exp(scores − m_blk)."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    scores = jnp.where(kmask[:, None, None, :] > 0, scores, NEG_INF)
    m_blk = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m_blk[..., None])
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return m_blk, p, pv


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kmask: Optional[jnp.ndarray] = None,
    *,
    axis_name: str,
    block_impl: str = "dense",
) -> jnp.ndarray:
    """Exact non-causal attention with K/V rotating over ``axis_name``.

    Call inside ``shard_map``: every argument is the device-local block
    ``q/k/v [B, T_local, H, D]``, ``kmask [B, T_local]`` (1 = real
    token).  Returns the local output block ``[B, T_local, H, D]``.

    ``block_impl`` picks the per-hop attention over the resident Q block
    and the rotating K/V block:

    - ``"dense"`` — XLA einsum chain; materializes a
      ``[B,H,T_local,T_local]`` score block per hop.  Right choice for
      short local blocks.
    - ``"flash"`` — the Pallas online-softmax kernel
      (:func:`svoc_tpu.ops.pallas_attention.flash_attention`) with
      ``return_lse``; hop outputs merge via log-sum-exp.  At long local
      blocks this avoids the per-hop score materialization entirely
      (honest probe: 49× vs dense at T=8192, ``FLASH_PROBE.json``) —
      the ring-outer/flash-inner long-context composition.
    """
    if kmask is None:
        kmask = jnp.ones(k.shape[:2], dtype=jnp.int32)
    n_dev = jax.lax.psum(1, axis_name)
    b, t_local, h, d = q.shape

    def run_ring(accumulate, carry0):
        """Forward-style ring over ``(k, v, kmask)``: rotating state is
        read-only, only the carry accumulates."""
        _, carry = _ring_protocol(
            axis_name,
            n_dev,
            (k, v, kmask),
            carry0,
            lambda rot, c: (rot, accumulate(*rot, c)),
        )
        return carry

    if block_impl == "flash":
        from svoc_tpu.ops.pallas_attention import flash_attention

        def accumulate_flash(k_blk, v_blk, mask_blk, carry):
            o, lse = carry
            o_b, lse_b = flash_attention(
                q, k_blk, v_blk, mask_blk, return_lse=True
            )  # o_b [B,T,H,D], lse_b [B,T,H]; fully-masked rows: 0/-inf
            lse_new = jnp.logaddexp(lse, lse_b)
            # Guard the all--inf case (every key so far is padding):
            # exp(-inf − -inf) would be NaN.  Double-where so the
            # untaken branch never materializes the NaN either — a bare
            # outer where would still poison gradients through its
            # cotangent if this path is ever differentiated.
            dead = jnp.isneginf(lse_new)
            d_old = jnp.where(dead, 0.0, lse - lse_new)
            d_new = jnp.where(dead, 0.0, lse_b - lse_new)
            w_old = jnp.where(dead, 0.0, jnp.exp(d_old))[..., None]
            w_new = jnp.where(dead, 0.0, jnp.exp(d_new))[..., None]
            return o * w_old + o_b.astype(jnp.float32) * w_new, lse_new

        o, _lse = run_ring(
            accumulate_flash,
            (
                jnp.zeros((b, t_local, h, d), jnp.float32),
                jnp.full((b, t_local, h), -jnp.inf, jnp.float32),
            ),
        )
        return o.astype(q.dtype)
    if block_impl != "dense":
        raise ValueError(f"unknown block_impl {block_impl!r}")
    # Dense inner: the differentiable implementation (custom two-pass
    # ring VJP — reverse-mode through the rotation loop itself would
    # transpose every ppermute and blow up compile).
    return _ring_dense_diff(q, k, v, kmask, axis_name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _ring_dense_diff(q, k, v, kmask, axis_name):
    """Differentiable dense-inner ring attention (two-pass backward)."""
    out, _lse = _ring_dense_fwd_stats(q, k, v, kmask, axis_name)
    return out


def _ring_dense_fwd_stats(q, k, v, kmask, axis_name):
    """Forward with per-row log-sum-exp kept: one ring pass reducing
    (m, l, o); ``lse = m + log l``, −inf where every key is padding."""
    n_dev = jax.lax.psum(1, axis_name)
    b, t_local, h, d = q.shape
    scale = jnp.float32(1.0) / jnp.sqrt(jnp.float32(d))

    def update(rot, carry):
        k_blk, v_blk, mask_blk = rot
        m, l, o = carry
        m_blk, p, pv = _block_attn(q, k_blk, v_blk, mask_blk, scale)
        m_new = jnp.maximum(m, m_blk)
        corr = jnp.exp(m - m_new)
        corr_blk = jnp.exp(m_blk - m_new)
        l = l * corr + jnp.sum(p, axis=-1) * corr_blk
        corr_o = jnp.transpose(corr, (0, 2, 1))[..., None]
        corr_pv = jnp.transpose(corr_blk, (0, 2, 1))[..., None]
        o = o * corr_o + pv.astype(jnp.float32) * corr_pv
        return rot, (m_new, l, o)

    carry0 = (
        jnp.full((b, h, t_local), NEG_INF, jnp.float32),
        jnp.zeros((b, h, t_local), jnp.float32),
        jnp.zeros((b, t_local, h, d), jnp.float32),
    )
    _, (m, l, o) = _ring_protocol(
        axis_name, n_dev, (k, v, kmask), carry0, update
    )
    l_t = jnp.transpose(l, (0, 2, 1))[..., None]
    out = (o / jnp.maximum(l_t, 1e-30)).astype(q.dtype)
    dead = m <= NEG_INF / 2  # no real key anywhere in the ring
    # Dead rows (every key padding) return EXACTLY 0, the same
    # convention as the flash path — and the one that makes the
    # two-pass VJP's zero gradient for them exact (the dense softmax's
    # degenerate uniform average would depend on v with dv = 0 here).
    dead_rows = jnp.transpose(dead, (0, 2, 1))[..., None]  # [B,Tq,H,1]
    out = jnp.where(dead_rows, jnp.zeros_like(out), out)
    lse = jnp.where(dead, -jnp.inf, m + jnp.log(jnp.maximum(l, 1e-30)))
    return out, lse


def _ring_dense_diff_fwd(q, k, v, kmask, axis_name):
    out, lse = _ring_dense_fwd_stats(q, k, v, kmask, axis_name)
    return out, (q, k, v, kmask, out, lse)


def _ring_dense_diff_bwd(axis_name, res, dout):
    """Second ring pass: dk/dv accumulators TRAVEL with their rotating
    K/V block (same permutation as the forward), so after the n_dev−1
    processing hops one final rotation delivers them home."""
    import numpy as np

    q, k, v, kmask, out, lse = res
    n_dev = jax.lax.psum(1, axis_name)
    b, t_local, h, d = q.shape
    scale = jnp.float32(1.0) / jnp.sqrt(jnp.float32(d))
    dout_f = dout.astype(jnp.float32)
    # delta = rowsum(dO · O) per query row, aligned [B, H, Tq].
    delta = jnp.transpose(
        jnp.sum(dout_f * out.astype(jnp.float32), axis=-1), (0, 2, 1)
    )
    qf = q.astype(jnp.float32)
    finite = jnp.isfinite(lse)[..., None]  # [B, H, Tq, 1]

    def contrib(k_blk, v_blk, mask_blk):
        s = (
            jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
            * scale
        )
        s = jnp.where(mask_blk[:, None, None, :] > 0, s, NEG_INF)
        p = jnp.where(finite, jnp.exp(s - lse[..., None]), 0.0)
        p = jnp.where(mask_blk[:, None, None, :] > 0, p, 0.0)  # exact zero
        dp = jnp.einsum("bqhd,bkhd->bhqk", dout_f, v_blk.astype(jnp.float32))
        ds = p * (dp - delta[..., None])
        dq_c = jnp.einsum("bhqk,bkhd->bqhd", ds, k_blk.astype(jnp.float32)) * scale
        dk_c = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
        dv_c = jnp.einsum("bhqk,bqhd->bkhd", p, dout_f)
        return dq_c, dk_c, dv_c

    def update(rot, dq):
        # The dk/dv accumulators live in `rot` so they rotate WITH their
        # K/V block; each hop adds this device's contribution to them.
        k_blk, v_blk, mask_blk, dk_acc, dv_acc = rot
        dq_c, dk_c, dv_c = contrib(k_blk, v_blk, mask_blk)
        rot = (k_blk, v_blk, mask_blk, dk_acc + dk_c, dv_acc + dv_c)
        return rot, dq + dq_c

    zeros_kd = jnp.zeros(k.shape, jnp.float32)
    rot, dq = _ring_protocol(
        axis_name,
        n_dev,
        (k, v, kmask, zeros_kd, zeros_kd),
        jnp.zeros(q.shape, jnp.float32),
        update,
    )
    _, _, _, dk_acc, dv_acc = rot
    # Blocks sit one hop short of home after n_dev−1 rotations.
    dk_home, dv_home = ring_rotate((dk_acc, dv_acc), axis_name, n_dev)
    dmask = np.zeros(kmask.shape, jax.dtypes.float0)
    return (
        dq.astype(q.dtype),
        dk_home.astype(k.dtype),
        dv_home.astype(v.dtype),
        dmask,
    )


_ring_dense_diff.defvjp(_ring_dense_diff_fwd, _ring_dense_diff_bwd)


def ring_attention_fn(
    mesh: Mesh, seq_axis: str = "seq", block_impl: str = "dense"
) -> Callable[..., jnp.ndarray]:
    """Jitted ``(q, k, v, kmask) → out`` with the sequence dimension
    sharded over ``seq_axis`` (batch/head dims replicated; compose with
    data sharding by passing a multi-axis mesh and sharded inputs).
    ``block_impl="flash"`` uses the Pallas kernel per hop (long-context
    composition — see :func:`ring_attention`)."""
    spec = P(None, seq_axis, None, None)
    mask_spec = P(None, seq_axis)

    def body(q, k, v, kmask):
        return ring_attention(
            q, k, v, kmask, axis_name=seq_axis, block_impl=block_impl
        )

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec, mask_spec),
        out_specs=spec,
        check_rep=False,
    )
    return jax.jit(mapped)


def dense_attention_reference(q, k, v, kmask=None):
    """Monolithic-softmax reference for equivalence tests (the encoder's
    attention math, :class:`svoc_tpu.models.encoder.SelfAttention`)."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if kmask is not None:
        scores = jnp.where(kmask[:, None, None, :] > 0, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
