"""Sequence-parallel encoder forward: the full model under shard_map.

Long-context inference path: activations are sharded over a ``"seq"``
mesh axis for the *entire* forward — embeddings, every encoder block,
and the classification head — so per-device activation memory scales as
T/d and sequence length is bounded by the mesh, not one chip's HBM.
Collectives used (all riding ICI):

- one tiny ``all_gather`` of per-shard token counts for the global
  RoBERTa position ids (positions count real tokens across shards),
- ``ppermute`` K/V ring rotations inside each block's attention
  (:func:`svoc_tpu.parallel.ring_attention.ring_attention`),
- one ``psum`` to deliver the CLS (global position 0) vector from
  shard 0 to the replicated classifier head.

The function consumes the exact params tree of
:class:`svoc_tpu.models.encoder.SentimentEncoder` — no separate weight
format — and matches its logits (equivalence-tested on the 8-device
CPU mesh in ``tests/test_sp_encoder.py``).  Dense layers are expressed
directly on the param leaves (``x @ kernel + bias``) because the flax
module applies to full arrays while this path runs on sequence shards.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from svoc_tpu.models.configs import EncoderConfig
from svoc_tpu.parallel.ring_attention import ring_attention
from svoc_tpu.parallel.sharded import shard_map


def _dense(x, p):
    return jnp.einsum("...i,io->...o", x, p["kernel"]) + p["bias"]


def _layernorm(x, p, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mean) ** 2, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return y * p["scale"] + p["bias"]


def _global_position_ids(mask_local, cfg, axis):
    """RoBERTa position ids across sequence shards: every real token's
    position is its global count of preceding real tokens + pad_id + 1
    (``encoder.py`` uses ``cumsum(mask) * mask + pad_id``)."""
    n_dev = jax.lax.psum(1, axis)
    ax = jax.lax.axis_index(axis)
    local_counts = jnp.sum(mask_local, axis=1)  # [B]
    all_counts = jax.lax.all_gather(local_counts, axis)  # [d, B]
    shard_ids = jnp.arange(n_dev)[:, None]
    prefix = jnp.sum(
        jnp.where(shard_ids < ax, all_counts, 0), axis=0
    )  # [B] tokens before this shard
    local_cumsum = jnp.cumsum(mask_local, axis=-1)
    return (prefix[:, None] + local_cumsum) * mask_local + cfg.pad_id


def _block(x, bias_mask_local, params, cfg, axis):
    """One EncoderBlock (``encoder.py:54-70``) on sequence shards."""
    h, d = cfg.n_heads, cfg.head_dim
    b, t_local, _ = x.shape

    ap = params["attention"]
    q = _dense(x, ap["query"]).reshape(b, t_local, h, d)
    k = _dense(x, ap["key"]).reshape(b, t_local, h, d)
    v = _dense(x, ap["value"]).reshape(b, t_local, h, d)
    # cfg.attention selects the per-hop block impl: "flash" runs the
    # Pallas kernel inside every ring hop (long-context composition).
    ctx = ring_attention(
        q, k, v, bias_mask_local, axis_name=axis, block_impl=cfg.attention
    )
    a = _dense(ctx.reshape(b, t_local, cfg.hidden), ap["out"])

    x = _layernorm(x + a, params["ln_attn"], cfg.ln_eps).astype(cfg.dtype)
    f = _dense(x, params["ffn_in"])
    f = jax.nn.gelu(f, approximate=False)
    f = _dense(f, params["ffn_out"])
    return _layernorm(x + f, params["ln_ffn"], cfg.ln_eps).astype(cfg.dtype)


def sequence_parallel_forward_fn(
    mesh: Mesh, cfg: EncoderConfig, seq_axis: str = "seq"
) -> Callable:
    """Jitted ``(params, ids [B, T], mask [B, T]) → logits [B, n_labels]``
    with ``T`` sharded over ``seq_axis`` (``T`` divisible by the axis
    size); params and logits replicated."""

    def body(params, ids_local, mask_local):
        p = params["params"]
        ax_idx = jax.lax.axis_index(seq_axis)

        pos_ids = _global_position_ids(mask_local, cfg, seq_axis)
        tok = jnp.take(p["tok_emb"]["embedding"], ids_local, axis=0)
        pos = jnp.take(p["pos_emb"]["embedding"], pos_ids, axis=0)
        x = _layernorm(tok + pos, p["ln_emb"], cfg.ln_eps).astype(cfg.dtype)

        for i in range(cfg.n_layers):
            x = _block(x, mask_local, p[f"block_{i}"], cfg, seq_axis)

        # CLS pooling: global token 0 lives on shard 0; psum broadcasts
        # it so the (replicated) head computes identically everywhere.
        cls_local = jnp.where(ax_idx == 0, x[:, 0, :], 0.0)
        cls = jax.lax.psum(cls_local, seq_axis)
        cls = jnp.tanh(_dense(cls, p["head_dense"]))
        return _dense(cls.astype(jnp.float32), p["head_out"])

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(None, seq_axis), P(None, seq_axis)),
        out_specs=P(),
        check_rep=False,
    )
    return jax.jit(mapped)
