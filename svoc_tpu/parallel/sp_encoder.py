"""Sequence-parallel encoder forward: the full model under shard_map.

Long-context inference path: activations are sharded over a ``"seq"``
mesh axis for the *entire* forward — embeddings, every encoder block,
and the classification head — so per-device activation memory scales as
T/d and sequence length is bounded by the mesh, not one chip's HBM.
Collectives used (all riding ICI):

- one tiny ``all_gather`` of per-shard token counts for the global
  RoBERTa position ids (positions count real tokens across shards),
- ``ppermute`` K/V ring rotations inside each block's attention
  (:func:`svoc_tpu.parallel.ring_attention.ring_attention`),
- one ``psum`` to deliver the CLS (global position 0) vector from
  shard 0 to the replicated classifier head.

The function consumes the exact params tree of
:class:`svoc_tpu.models.encoder.SentimentEncoder` — no separate weight
format — and matches its logits (equivalence-tested on the 8-device
CPU mesh in ``tests/test_sp_encoder.py``).  Dense layers are expressed
directly on the param leaves (``x @ kernel + bias``) because the flax
module applies to full arrays while this path runs on sequence shards.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from svoc_tpu.models.configs import EncoderConfig
from svoc_tpu.parallel.encoder_math import (
    cls_head,
    embed_tokens,
    encoder_block,
)
from svoc_tpu.parallel.ring_attention import ring_attention
from svoc_tpu.parallel.sharded import shard_map


def _global_position_ids(mask_local, cfg, axis):
    """RoBERTa position ids across sequence shards: every real token's
    position is its global count of preceding real tokens + pad_id + 1
    (``encoder.py`` uses ``cumsum(mask) * mask + pad_id``)."""
    n_dev = jax.lax.psum(1, axis)
    ax = jax.lax.axis_index(axis)
    local_counts = jnp.sum(mask_local, axis=1)  # [B]
    all_counts = jax.lax.all_gather(local_counts, axis)  # [d, B]
    shard_ids = jnp.arange(n_dev)[:, None]
    prefix = jnp.sum(
        jnp.where(shard_ids < ax, all_counts, 0), axis=0
    )  # [B] tokens before this shard
    local_cumsum = jnp.cumsum(mask_local, axis=-1)
    return (prefix[:, None] + local_cumsum) * mask_local + cfg.pad_id


def _block(x, bias_mask_local, params, cfg, axis):
    """One EncoderBlock (``encoder.py:54-70``) on sequence shards —
    the shared :func:`encoder_block` math with the ring as the
    attention impl (``cfg.attention`` selects the per-hop block impl:
    "flash" runs the Pallas kernel inside every ring hop)."""

    def ring(q, k, v, kmask):
        return ring_attention(
            q, k, v, kmask, axis_name=axis, block_impl=cfg.attention
        )

    return encoder_block(x, bias_mask_local, params, cfg, attention_fn=ring)


def sequence_parallel_forward_fn(
    mesh: Mesh, cfg: EncoderConfig, seq_axis: str = "seq"
) -> Callable:
    """Jitted ``(params, ids [B, T], mask [B, T]) → logits [B, n_labels]``
    with ``T`` sharded over ``seq_axis`` (``T`` divisible by the axis
    size); params and logits replicated."""

    def body(params, ids_local, mask_local):
        p = params["params"]
        ax_idx = jax.lax.axis_index(seq_axis)

        pos_ids = _global_position_ids(mask_local, cfg, seq_axis)
        x = embed_tokens(ids_local, pos_ids, p, cfg)

        for i in range(cfg.n_layers):
            x = _block(x, mask_local, p[f"block_{i}"], cfg, seq_axis)

        # CLS pooling: global token 0 lives on shard 0; psum broadcasts
        # it so the (replicated) head computes identically everywhere.
        cls_local = jnp.where(ax_idx == 0, x[:, 0, :], 0.0)
        cls = jax.lax.psum(cls_local, seq_axis)
        return cls_head(cls.astype(cfg.dtype), p, cfg)

    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(None, seq_axis), P(None, seq_axis)),
        out_specs=P(),
        check_rep=False,
    )
    return jax.jit(mapped)
