"""Device-mesh parallelism: the capability layer the reference lacks.

The reference simulates N oracles with a host Python loop
(``client/oracle_scheduler.py:73-92``) and aggregates them on a
blockchain; here the oracle fleet lives on a `jax.sharding.Mesh` and the
consensus reductions are XLA collectives over ICI (SURVEY.md §2.5, §7.6).
"""

from svoc_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    best_mesh,
    make_mesh,
)
from svoc_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_forward_fn,
    stack_block_params,
)
from svoc_tpu.parallel.serving import (  # noqa: F401
    batch_sharding,
    dp_serving_step_fn,
    serving_mesh,
)
from svoc_tpu.parallel.sharded import (  # noqa: F401
    sharded_consensus_fn,
    sharded_fleet_step_fn,
)
