"""Functional encoder math shared by the sharded forwards.

``parallel/sp_encoder.py`` (sequence parallel) and
``parallel/pipeline.py`` (pipeline parallel) re-run the
:class:`svoc_tpu.models.encoder.SentimentEncoder` math on raw param
trees inside ``shard_map`` (flax modules don't trace through collective
axes).  This module is the single home for that math so the three
implementations cannot drift, with the SAME dtype semantics as the flax
modules: matmuls in ``cfg.dtype`` (kernels cast — the MXU path),
layernorm/softmax accumulation in float32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from svoc_tpu.models.configs import EncoderConfig


def dense(x, p, dtype):
    """``nn.Dense(dtype=dtype)`` semantics: inputs, kernel and bias all
    cast to ``dtype`` before the matmul."""
    return (
        jnp.einsum(
            "...i,io->...o", x.astype(dtype), p["kernel"].astype(dtype)
        )
        + p["bias"].astype(dtype)
    )


def layernorm(x, p, eps):
    """``nn.LayerNorm(dtype=float32)`` semantics (f32 accumulation)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mean) ** 2, axis=-1, keepdims=True)
    return (x32 - mean) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def embed_tokens(ids, pos_ids, rest, cfg: EncoderConfig):
    """Token + position embedding + embedding layernorm
    (``encoder.py:82-95``); ``pos_ids`` supplied by the caller (local
    cumsum for the pipeline, cross-shard prefix sum for sp).

    Bit-parity note: ``nn.Embed(dtype=cfg.dtype)`` gathers from a
    dtype-cast table, so the rows are cast BEFORE the add — at bf16 the
    rounding order is observable."""
    tok = jnp.take(rest["tok_emb"]["embedding"], ids, axis=0).astype(cfg.dtype)
    pos = jnp.take(rest["pos_emb"]["embedding"], pos_ids, axis=0).astype(
        cfg.dtype
    )
    return layernorm(tok + pos, rest["ln_emb"], cfg.ln_eps).astype(cfg.dtype)


def local_position_ids(mask, cfg: EncoderConfig):
    """RoBERTa position ids within one (unsharded) sequence block
    (``encoder.py:87``)."""
    return jnp.cumsum(mask, axis=-1) * mask + cfg.pad_id


def cls_head(cls_vec, rest, cfg: EncoderConfig):
    """First-token classification head (``encoder.py:105-107``)."""
    cls = jnp.tanh(dense(cls_vec, rest["head_dense"], cfg.dtype))
    return dense(cls.astype(jnp.float32), rest["head_out"], jnp.float32)


def local_attention(q, k, v, kmask, cfg: EncoderConfig):
    """Full-sequence attention over device-local blocks, honoring
    ``cfg.attention`` exactly like the flax encoder (``encoder.py:
    46-60``): dense einsum chain or the Pallas flash kernel.

    The dense branch mirrors ``SelfAttention`` op for op (scale
    multiply in ``cfg.dtype`` BEFORE the f32 cast, additive −1e9 key
    bias, probs cast back to ``cfg.dtype``) so bf16 configs are
    logit-exact with the flax module."""
    if cfg.attention == "flash":
        from svoc_tpu.ops.pallas_attention import flash_attention

        return flash_attention(q, k, v, kmask)
    d = q.shape[-1]
    scale = jnp.asarray(1.0 / jnp.sqrt(jnp.float32(d)), cfg.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    bias = jnp.where(kmask[:, None, None, :] > 0, 0.0, -1e9).astype(
        jnp.float32
    )
    probs = jax.nn.softmax(scores.astype(jnp.float32) + bias, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(cfg.dtype), v)


def encoder_block(x, kmask, bp, cfg: EncoderConfig, attention_fn=None, *, dense_fn=None):
    """One :class:`EncoderBlock` (``encoder.py:54-70``) from a raw
    params dict.  ``attention_fn(q, k, v, kmask) → ctx`` defaults to
    :func:`local_attention`; sp passes the ring.  ``dense_fn(x, p,
    dtype)`` defaults to :func:`dense`; the int8 path
    (:mod:`svoc_tpu.models.quant`) passes its quantized matmul so the
    block wiring is defined exactly once."""
    if dense_fn is None:
        dense_fn = dense
    b, t, _ = x.shape
    h, d = cfg.n_heads, cfg.head_dim
    ap = bp["attention"]
    q = dense_fn(x, ap["query"], cfg.dtype).reshape(b, t, h, d)
    k = dense_fn(x, ap["key"], cfg.dtype).reshape(b, t, h, d)
    v = dense_fn(x, ap["value"], cfg.dtype).reshape(b, t, h, d)
    if attention_fn is None:
        ctx = local_attention(q, k, v, kmask, cfg)
    else:
        ctx = attention_fn(q, k, v, kmask)
    a = dense_fn(ctx.reshape(b, t, cfg.hidden), ap["out"], cfg.dtype)
    x = layernorm(x + a, bp["ln_attn"], cfg.ln_eps).astype(cfg.dtype)
    f = jax.nn.gelu(dense_fn(x, bp["ffn_in"], cfg.dtype), approximate=False)
    f = dense_fn(f, bp["ffn_out"], cfg.dtype)
    return layernorm(x + f, bp["ln_ffn"], cfg.ln_eps).astype(cfg.dtype)
