"""Pipeline parallelism: encoder layers sharded over a ``stage`` axis.

The fourth parallelism axis (after data/tensor/sequence): the
transformer's layer stack is split into ``S`` contiguous stages, one per
device along ``stage``, and ``M`` microbatches flow through the ring of
stages GPipe-style — device ``s`` processes microbatch ``t − s`` at step
``t``, activations hop to the next stage via ``jax.lax.ppermute`` over
ICI, and the schedule drains in ``S + M − 1`` steps (pipeline bubble
``(S−1)/(S+M−1)``).

TPU-first construction: ONE shard_map program for every stage (no
per-stage code or host RPC — the reference framework pattern of a
scheduler process per stage becomes a single SPMD program), layer
params stacked on a leading axis and sharded ``P("stage")`` so each
device materializes only its own ``n_layers/S`` layers, and the whole
schedule is a ``lax.fori_loop`` with fixed shapes.

Composability: add a ``data`` axis to the mesh and shard the batch over
it — each data-row runs an independent pipeline replica (pp × dp), the
way ``dryrun_multichip`` exercises it.

Scope: forward/serving pipeline (the inference hot path).  A 1F1B
training schedule would reuse the same stage layout; the fine-tune path
currently scales via data × tensor parallelism (`train/trainer.py`).

Every stage redundantly computes the embedding and head for the
microbatch it does not own (masked out by ``where``) — that is the
standard SPMD trade: a few percent of FLOPs for zero control-flow
divergence.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from svoc_tpu.models.configs import EncoderConfig
from svoc_tpu.parallel.encoder_math import (
    cls_head,
    embed_tokens,
    encoder_block,
    local_position_ids,
)
from svoc_tpu.parallel.sharded import shard_map


def stack_block_params(params: dict, cfg: EncoderConfig) -> Tuple[dict, dict]:
    """Split a :class:`SentimentEncoder` params tree into
    ``(stacked_blocks, rest)`` where every block leaf gains a leading
    ``[n_layers]`` axis (the axis the ``stage`` mesh dimension shards).
    """
    p = params["params"]
    blocks = [p[f"block_{i}"] for i in range(cfg.n_layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
    rest = {k: v for k, v in p.items() if not k.startswith("block_")}
    return stacked, rest


def pipeline_forward_fn(
    mesh: Mesh,
    cfg: EncoderConfig,
    n_microbatches: int,
    stage_axis: str = "stage",
    data_axis: str | None = None,
) -> Callable:
    """Jitted ``(params, ids [B, T], mask [B, T]) → logits [B, n_labels]``
    with layers pipelined over ``stage_axis``.

    ``params`` is the unmodified :class:`SentimentEncoder` tree (the
    stage split happens inside via :func:`stack_block_params`).  ``B``
    must divide by ``n_microbatches`` (× the ``data_axis`` size when a
    data axis shards the batch).  Logit parity with the dense encoder
    is pinned in ``tests/test_pipeline_parallel.py``.
    """
    n_stages = mesh.shape[stage_axis]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by {n_stages} stages"
        )
    layers_per_stage = cfg.n_layers // n_stages
    m = n_microbatches

    def body(stacked_local, rest, ids, mask):
        s = jax.lax.axis_index(stage_axis)
        b, t = ids.shape
        if b % m:
            raise ValueError(f"local batch {b} not divisible by {m} microbatches")
        mb = b // m
        ids_m = ids.reshape(m, mb, t)
        mask_m = mask.reshape(m, mb, t)

        def embed(mids, mmask):
            return embed_tokens(
                mids, local_position_ids(mmask, cfg), rest, cfg
            )

        def run_stage(x, mmask):
            # encoder_block honors cfg.attention (dense or flash) like
            # the flax encoder and the sp forward.
            for i in range(layers_per_stage):
                bp = jax.tree_util.tree_map(lambda a, i=i: a[i], stacked_local)
                x = encoder_block(x, mmask, bp, cfg)
            return x

        def head(x):
            return cls_head(x[:, 0, :].astype(cfg.dtype), rest, cfg)

        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def step(tstep, carry):
            act, act_mask, outs = carry
            # activations (+ their padding masks) hop one stage forward
            act_in = jax.lax.ppermute(act, stage_axis, perm)
            mask_in = jax.lax.ppermute(act_mask, stage_axis, perm)
            # stage 0 injects microbatch `tstep` (clamped when draining)
            inj = jnp.clip(tstep, 0, m - 1)
            mids = jax.lax.dynamic_index_in_dim(ids_m, inj, keepdims=False)
            mmask = jax.lax.dynamic_index_in_dim(mask_m, inj, keepdims=False)
            first = jnp.logical_and(s == 0, tstep < m)
            x = jnp.where(first, embed(mids, mmask), act_in)
            xm = jnp.where(first, mmask, mask_in)
            y = run_stage(x, xm)
            # the last stage finishes microbatch `tstep − (S−1)`
            done = tstep - (n_stages - 1)
            is_done = jnp.logical_and(
                s == n_stages - 1, jnp.logical_and(done >= 0, done < m)
            )
            logits = head(y)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(
                    is_done,
                    logits,
                    jax.lax.dynamic_index_in_dim(
                        outs, jnp.clip(done, 0, m - 1), keepdims=False
                    ),
                ),
                jnp.clip(done, 0, m - 1),
                axis=0,
            )
            return y, xm, outs

        act0 = jnp.zeros((mb, t, cfg.hidden), cfg.dtype)
        mask0 = jnp.zeros((mb, t), mask.dtype)
        outs0 = jnp.zeros((m, mb, cfg.n_labels), jnp.float32)
        _, _, outs = jax.lax.fori_loop(
            0, n_stages + m - 1, step, (act0, mask0, outs0)
        )
        # only the last stage holds real logits — broadcast to all
        outs = jax.lax.psum(
            jnp.where(s == n_stages - 1, outs, 0.0), stage_axis
        )
        return outs.reshape(b, cfg.n_labels)

    batch_spec = P(data_axis, None) if data_axis else P(None, None)

    mapped = shard_map(
        body,
        mesh=mesh,
        # P(stage_axis) is a pytree prefix: every stacked-block leaf
        # shards its leading [n_layers] axis over the stages.
        in_specs=(P(stage_axis), P(), batch_spec, batch_spec),
        out_specs=batch_spec,
        check_rep=False,
    )

    dispatch = jax.jit(mapped)

    def forward(params, ids, mask):
        # The stack happens EAGERLY, outside the jitted program: on a
        # stage×data mesh (both axes > 1), GSPMD mispartitions an
        # in-jit concatenate feeding the shard_map manual region and
        # every logit comes out O(1) wrong — jax 0.4.x, CPU and TPU
        # lowerings alike.  Keeping the jitted program all-manual
        # sidesteps the partitioner entirely; the eager stack is a few
        # small concats per call, amortized by the dispatch underneath.
        stacked, rest = stack_block_params(params, cfg)
        return dispatch(stacked, rest, ids, mask)

    return forward

