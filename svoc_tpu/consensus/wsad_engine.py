"""Bit-faithful wsad (i128×1e-6) consensus engine — the golden model.

Literal, arbitrary-precision-integer reimplementation of the statistical
core of the reference Cairo contract (``contract/src/math.cairo`` +
``contract/src/contract.cairo:370-503``), used to

1. verify the TPU float kernel (:mod:`svoc_tpu.consensus.kernel`)
   against the exact on-chain arithmetic (integer truncation, rounded
   wsad mul/div, Newton sqrt with a 50-iteration cap, merge-sort tie
   order), and
2. drive the stateful contract simulator
   (:mod:`svoc_tpu.consensus.state`) that replaces the reference's
   Starknet-test-VM harness.

Python ints are exact, so there is no i128 overflow concern; every
division goes through :func:`svoc_tpu.ops.fixedpoint.div_trunc` to get
Cairo's truncate-toward-zero semantics.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from svoc_tpu.ops.fixedpoint import (
    WSAD,
    div_trunc,
    wsad_div,
    wsad_mul,
    wsad_sqrt,
)
from svoc_tpu.ops.sort import indexed_sort_host


class IntervalError(AssertionError):
    """Raised where the contract panics with 'interval error'
    (``math.cairo:294-310``)."""


def interval_check(value: int) -> None:
    if not (0 <= value <= WSAD):
        raise IntervalError(f"interval error: {value}")


def nd_interval_check(vector: Sequence[int]) -> None:
    for v in vector:
        interval_check(v)


def smooth_median(values: Sequence[int]) -> int:
    """``math.cairo:113-126`` — including the dead odd-length branch:
    ``(len & 2) == 1`` can never hold, so the result is always the mean
    of the two sorted values around ``len/2``."""
    sorted_vals = sorted(values)
    mid = len(values) // 2
    a, b = sorted_vals[mid - 1], sorted_vals[mid]
    return div_trunc(a + b, 2)


def median(values: Sequence[int]) -> int:
    """Upper median (``math.cairo:102-110``)."""
    return sorted(values)[len(values) // 2]


def nd_smooth_median(values: Sequence[Sequence[int]]) -> List[int]:
    """Component-wise smooth median (``math.cairo:152-165``)."""
    dim = len(values[0])
    return [smooth_median([v[i] for v in values]) for i in range(dim)]


def nd_median(values: Sequence[Sequence[int]]) -> List[int]:
    dim = len(values[0])
    return [median([v[i] for v in values]) for i in range(dim)]


def quadratic_deviation(a: int, b: int) -> int:
    x = a - b
    return wsad_mul(x, x)


def nd_quadratic_deviation(a: Sequence[int], b: Sequence[int]) -> int:
    return sum(quadratic_deviation(x, y) for x, y in zip(a, b))


def nd_quadratic_risk(
    values: Sequence[Sequence[int]], center: Sequence[int]
) -> List[int]:
    """``math.cairo:225-238``."""
    return [nd_quadratic_deviation(v, center) for v in values]


def average(values: Sequence[int]) -> int:
    """Truncating mean (``math.cairo:240-254``)."""
    return div_trunc(sum(values), len(values))


def nd_average(values: Sequence[Sequence[int]]) -> List[int]:
    dim = len(values[0])
    return [average([v[i] for v in values]) for i in range(dim)]


def nd_component_wise_variance(
    values: Sequence[Sequence[int]], center: Sequence[int]
) -> List[int]:
    """``math.cairo:208-222`` — biased variance, truncating mean."""
    dim = len(values[0])
    return [
        average([quadratic_deviation(v[i], center[i]) for v in values])
        for i in range(dim)
    ]


def skewness(values: Sequence[int], mean: int, variance: int) -> int:
    """``math.cairo:320-338``."""
    n = len(values)
    std = wsad_sqrt(variance)
    skew = 0
    for v in values:
        diff = wsad_div(v - mean, std)
        skew += wsad_mul(wsad_mul(diff, diff), diff)
    return div_trunc(skew * n, (n - 1) * (n - 2))


def kurtosis(values: Sequence[int], mean: int, variance: int) -> int:
    """``math.cairo:340-363``."""
    n = len(values)
    std = wsad_sqrt(variance)
    kurt = 0
    for v in values:
        diff = wsad_div(v - mean, std)
        d2 = wsad_mul(diff, diff)
        kurt += wsad_mul(d2, d2)
    term1 = div_trunc(kurt * n * (n + 1), n - 1)
    term2 = 3 * WSAD * (n - 1) * (n - 1)
    return div_trunc(term1 - term2, (n - 2) * (n - 3))


def nd_skewness(values, means, variances) -> List[int]:
    dim = len(values[0])
    return [
        skewness([v[i] for v in values], means[i], variances[i]) for i in range(dim)
    ]


def nd_kurtosis(values, means, variances) -> List[int]:
    dim = len(values[0])
    return [
        kurtosis([v[i] for v in values], means[i], variances[i]) for i in range(dim)
    ]


# ---------------------------------------------------------------------------
# Two-pass consensus (contract.cairo:370-503), pure function over a block.
# ---------------------------------------------------------------------------


def two_pass_consensus(
    values: Sequence[Sequence[int]],
    *,
    constrained: bool,
    n_failing: int,
    max_spread: int = 0,
    strict_interval: bool = True,
) -> Dict:
    """Run both passes on a complete oracle block of wsad vectors.

    Returns a dict with wsad-int fields mirroring the contract storage
    after an ``update_*_consensus`` call: ``essence``,
    ``reliability_first_pass``, ``reliability_second_pass``,
    ``reliable`` (per original oracle index), ``skewness``,
    ``kurtosis``, plus ``essence_first_pass`` and ``quadratic_risk``.
    """
    n = len(values)
    dim = len(values[0])

    def reliability(mean_qr_or_std: int) -> int:
        if constrained:
            # contract.cairo:436-439 — argument is mean(qr)
            return WSAD - wsad_sqrt(div_trunc(mean_qr_or_std, dim)) * 2
        # contract.cairo:365-368 — argument is sqrt(mean(qr))
        return WSAD - wsad_div(min(max_spread, mean_qr_or_std), max_spread)

    # FIRST PASS
    essence1 = nd_smooth_median(values)
    qr = nd_quadratic_risk(values, essence1)
    if constrained:
        rel1 = reliability(average(qr))
    else:
        rel1 = reliability(wsad_sqrt(average(qr)))
    if strict_interval:
        interval_check(rel1)
    else:
        rel1 = min(max(rel1, 0), WSAD)

    ordered = indexed_sort_host(qr)  # (index, risk) ascending, Cairo tie order
    threshold = n - n_failing
    reliable = [False] * n
    for rank, (idx, _risk) in enumerate(ordered):
        reliable[idx] = rank < threshold

    reliable_values = [v for v, ok in zip(values, reliable) if ok]

    # SECOND PASS
    if constrained:
        essence = nd_smooth_median(reliable_values)
    else:
        essence = nd_average(reliable_values)
    qr2 = nd_quadratic_risk(reliable_values, essence1)  # centered on essence₁
    if constrained:
        rel2 = reliability(average(qr2))
    else:
        rel2 = reliability(wsad_sqrt(average(qr2)))
    if strict_interval:
        interval_check(rel2)
    else:
        rel2 = min(max(rel2, 0), WSAD)

    # MOMENTS
    means = nd_average(reliable_values)
    variances = nd_component_wise_variance(reliable_values, means)
    skew = nd_skewness(reliable_values, means, variances)
    kurt = nd_kurtosis(reliable_values, means, variances)

    return {
        "essence": essence,
        "essence_first_pass": essence1,
        "reliability_first_pass": rel1,
        "reliability_second_pass": rel2,
        "reliable": reliable,
        "quadratic_risk": qr,
        "skewness": skew,
        "kurtosis": kurt,
    }
