"""Consensus-implementation routing and pallas-fallback accounting.

The fabric/serving hot path dispatches every claim micro-batch through
ONE of two parity-tested consensus implementations
(``docs/FABRIC.md`` §consensus_impl):

- ``"xla"`` — the stitched XLA graph
  (:func:`svoc_tpu.consensus.kernel.consensus_step_gated_claims`), the
  parity oracle and the committed default;
- ``"pallas"`` — the fused VMEM-resident claim-cube kernel
  (:func:`svoc_tpu.ops.pallas_consensus.fused_consensus_gated_claims`).

The choice resolves exactly like the flagship variant routing in
``bench.py``: ``SVOC_CONSENSUS_IMPL`` env override > the committed
``PERF_DECISIONS.json`` record (written by ``tools/decide_perf.py``
from measured on-chip A/Bs, never at runtime) > the ``"xla"`` default.
Both candidates are lossless (identical consensus up to float
tolerance, ``make pallas-parity``), so the record only picks the
execution strategy — semantics never change with it.

Every time a pallas-routed dispatch has to fall back to XLA (fleet
over the oracle cap, non-TPU backend without the interpret opt-in, a
Mosaic lowering failure) the fallback is COUNTED in
``consensus_pallas_fallback{reason=}`` and logged once per reason —
before this module, the config-6 bench subprocess was the only place a
fallback was visible, and a production box could silently serve the
slow path forever.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional, Tuple

from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry

#: Repo root (the directory holding ``bench.py`` and the committed
#: decision record) — dispatch.py lives at svoc_tpu/consensus/.
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
PERF_DECISIONS_PATH = os.path.join(_REPO_ROOT, "PERF_DECISIONS.json")

ALLOWED_CONSENSUS_IMPLS = ("xla", "pallas")
CONSENSUS_IMPL_ENV = "SVOC_CONSENSUS_IMPL"
#: Opt-in that lets a pallas-routed dispatch run the kernel in
#: interpreter mode on a non-TPU backend (tests, ``make
#: pallas-parity``).  Without it a non-TPU pallas route falls back to
#: XLA and counts ``reason="non_tpu"`` — interpret mode is a parity
#: tool, not a serving path.
PALLAS_INTERPRET_ENV = "SVOC_PALLAS_INTERPRET"


class ConsensusImplError(ValueError):
    """An unknown consensus implementation was requested (env override
    or a corrupt committed record)."""


class PallasConfigError(ValueError):
    """A ``SVOC_PALLAS_*`` env knob failed validation.  Raised at first
    USE of the knob (never at import) with the variable name, the bad
    value, and the expected form in the message."""


def env_int(name: str, default: int, minimum: Optional[int] = None) -> int:
    """``int(os.environ[name])`` with a typed, actionable error."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = int(raw)
    except ValueError:
        raise PallasConfigError(
            f"{name}={raw!r} is not an integer (expected e.g. "
            f"{name}={default}); unset it to use the default"
        ) from None
    if minimum is not None and value < minimum:
        raise PallasConfigError(
            f"{name}={value} is below the minimum {minimum}; unset it "
            f"to use the default {default}"
        )
    return value


def env_float(
    name: str, default: float, minimum: Optional[float] = None
) -> float:
    """``float(os.environ[name])`` with a typed, actionable error."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = float(raw)
    except ValueError:
        raise PallasConfigError(
            f"{name}={raw!r} is not a number (expected e.g. "
            f"{name}={default}); unset it to use the default"
        ) from None
    if minimum is not None and value < minimum:
        raise PallasConfigError(
            f"{name}={value} is below the minimum {minimum}; unset it "
            f"to use the default {default}"
        )
    return value


def perf_decision(
    key: str, default: str, env_var: str, path: Optional[str] = None
) -> Tuple[str, str]:
    """Resolve a routing decision to ``(value, source)``: env override
    > the committed PERF_DECISIONS.json record > ``default``.  The
    library twin of ``bench.perf_decision`` (same precedence, same
    never-raises-on-a-bad-record contract), parameterized on the record
    path so tests can redirect it."""
    value = os.environ.get(env_var)
    source = f"env:{env_var}"
    if not value:
        try:
            with open(path or PERF_DECISIONS_PATH) as f:
                data = json.load(f)
            # A JSON-valid non-object record degrades like a missing
            # one — this resolver never raises on a bad record.
            value = data.get(key) if isinstance(data, dict) else None
            source = "PERF_DECISIONS.json"
        except (OSError, ValueError):
            value = None
    if not value:
        value, source = default, "default"
    return value, source


def validate_consensus_impl(impl: str, source: str = "caller") -> str:
    """Reject anything outside :data:`ALLOWED_CONSENSUS_IMPLS` with a
    message naming the allowed values AND the deciding env var."""
    if impl not in ALLOWED_CONSENSUS_IMPLS:
        allowed = ", ".join(repr(v) for v in ALLOWED_CONSENSUS_IMPLS)
        raise ConsensusImplError(
            f"consensus_impl {impl!r} (from {source}) is not a known "
            f"consensus implementation: allowed values are {allowed}; "
            f"set {CONSENSUS_IMPL_ENV} to override the committed "
            "PERF_DECISIONS.json record"
        )
    return impl


def resolve_consensus_impl(path: Optional[str] = None) -> str:
    """The production consensus-impl routing: env > committed record >
    ``"xla"``, validated.  Resolved ONCE per :class:`ClaimRouter` (the
    impl choice is part of a seeded replay's config — docs/FABRIC.md
    §replay), so the file read never sits on the per-step hot path."""
    impl, source = perf_decision(
        "consensus_impl", "xla", CONSENSUS_IMPL_ENV, path=path
    )
    return validate_consensus_impl(impl, source)


def pallas_interpret_opt_in() -> bool:
    return os.environ.get(PALLAS_INTERPRET_ENV) == "1"


#: ``SVOC_MESH=<claims>x<oracles>`` — operator override for the claim
#: mesh (kept in sync with ``svoc_tpu.parallel.mesh.CLAIM_MESH_ENV``;
#: duplicated literal so this resolver keeps importing no jax).
CLAIM_MESH_ENV = "SVOC_MESH"


def resolve_claim_mesh(path: Optional[str] = None) -> Optional[str]:
    """The claim-cube MESH routing twin of
    :func:`resolve_consensus_impl`: ``SVOC_MESH`` env > the committed
    ``PERF_DECISIONS.json`` ``claim_mesh`` record (written by
    ``tools/decide_perf.py`` from a measured ``BENCH_SHARD`` sweep,
    never by hand) > ``None`` (unsharded single-device dispatch).

    Returns the raw ``"<claims>x<oracles>"`` spec string or ``None``;
    :func:`svoc_tpu.parallel.mesh.claim_mesh` validates and builds the
    mesh.  Resolved ONCE per :class:`ClaimRouter` construction — the
    mesh, like the impl, is part of a seeded replay's config
    (docs/FABRIC.md §mesh) and must not drift mid-run.
    """
    value, _source = perf_decision("claim_mesh", "", CLAIM_MESH_ENV, path=path)
    if not value or str(value).strip().lower() in ("none", "off"):
        return None
    return str(value)


# ---------------------------------------------------------------------------
# Fallback accounting: no silent XLA fallbacks.
# ---------------------------------------------------------------------------

FALLBACK_COUNTER = "consensus_pallas_fallback"

_log = logging.getLogger("svoc_tpu.consensus.pallas")
_log_lock = threading.Lock()
_logged_reasons: set = set()


def report_pallas_fallback(
    reason: str,
    *,
    op: str = "fused_consensus",
    detail: str = "",
    metrics: Optional[MetricsRegistry] = None,
) -> None:
    """Count one pallas→XLA fallback and log the FIRST occurrence of
    each reason (one-shot — a steady-state fallback must not spam the
    log at dispatch rate; the counter carries the rate).

    Reasons: ``fleet_too_large`` (over ``SVOC_PALLAS_MAX_ORACLES``),
    ``unaligned_fleet`` (fleet not a multiple of the rank block),
    ``smooth_mode`` (non-cairo median), ``non_tpu`` (no TPU backend and
    no ``SVOC_PALLAS_INTERPRET=1`` opt-in), ``mosaic_error`` (the
    kernel raised at lowering/compile/run time),
    ``sharded_unsupported`` (a pallas route on a claim mesh whose
    oracle axis is sharded — partial fleets cannot feed the fused
    kernel, the XLA sharded body serves instead;
    :mod:`svoc_tpu.parallel.claim_shard`).
    """
    (metrics or _default_registry).counter(
        FALLBACK_COUNTER, labels={"reason": reason}
    ).add(1)
    with _log_lock:
        if reason in _logged_reasons:
            return
        _logged_reasons.add(reason)
    _log.warning(
        "%s fell back to the XLA consensus kernel (reason=%s%s); "
        "further fallbacks are counted in %s{reason=%s} without logging",
        op,
        reason,
        f": {detail}" if detail else "",
        FALLBACK_COUNTER,
        reason,
    )


def reset_fallback_log() -> None:
    """Re-arm the one-shot log (tests)."""
    with _log_lock:
        _logged_reasons.clear()
