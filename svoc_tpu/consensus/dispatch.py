"""Consensus-implementation routing and pallas-fallback accounting.

The fabric/serving hot path dispatches every claim micro-batch through
ONE of two parity-tested consensus implementations
(``docs/FABRIC.md`` §consensus_impl):

- ``"xla"`` — the stitched XLA graph
  (:func:`svoc_tpu.consensus.kernel.consensus_step_gated_claims`), the
  parity oracle and the committed default;
- ``"pallas"`` — the fused VMEM-resident claim-cube kernel
  (:func:`svoc_tpu.ops.pallas_consensus.fused_consensus_gated_claims`).

The choice resolves exactly like the flagship variant routing in
``bench.py``: ``SVOC_CONSENSUS_IMPL`` env override > the committed
``PERF_DECISIONS.json`` record (written by ``tools/decide_perf.py``
from measured on-chip A/Bs, never at runtime) > the ``"xla"`` default.
Both candidates are lossless (identical consensus up to float
tolerance, ``make pallas-parity``), so the record only picks the
execution strategy — semantics never change with it.

Every time a pallas-routed dispatch has to fall back to XLA (fleet
over the oracle cap, non-TPU backend without the interpret opt-in, a
Mosaic lowering failure) the fallback is COUNTED in
``consensus_pallas_fallback{reason=}`` and logged once per reason —
before this module, the config-6 bench subprocess was the only place a
fallback was visible, and a production box could silently serve the
slow path forever.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional, Tuple

from svoc_tpu.utils.metrics import MetricsRegistry
from svoc_tpu.utils.metrics import registry as _default_registry

#: Repo root (the directory holding ``bench.py`` and the committed
#: decision record) — dispatch.py lives at svoc_tpu/consensus/.
_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
PERF_DECISIONS_PATH = os.path.join(_REPO_ROOT, "PERF_DECISIONS.json")

ALLOWED_CONSENSUS_IMPLS = ("xla", "pallas")
CONSENSUS_IMPL_ENV = "SVOC_CONSENSUS_IMPL"
#: Opt-in that lets a pallas-routed dispatch run the kernel in
#: interpreter mode on a non-TPU backend (tests, ``make
#: pallas-parity``).  Without it a non-TPU pallas route falls back to
#: XLA and counts ``reason="non_tpu"`` — interpret mode is a parity
#: tool, not a serving path.
PALLAS_INTERPRET_ENV = "SVOC_PALLAS_INTERPRET"


class ConsensusImplError(ValueError):
    """An unknown consensus implementation was requested (env override
    or a corrupt committed record)."""


class PallasConfigError(ValueError):
    """A ``SVOC_PALLAS_*`` env knob failed validation.  Raised at first
    USE of the knob (never at import) with the variable name, the bad
    value, and the expected form in the message."""


def env_int(name: str, default: int, minimum: Optional[int] = None) -> int:
    """``int(os.environ[name])`` with a typed, actionable error."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = int(raw)
    except ValueError:
        raise PallasConfigError(
            f"{name}={raw!r} is not an integer (expected e.g. "
            f"{name}={default}); unset it to use the default"
        ) from None
    if minimum is not None and value < minimum:
        raise PallasConfigError(
            f"{name}={value} is below the minimum {minimum}; unset it "
            f"to use the default {default}"
        )
    return value


def env_float(
    name: str, default: float, minimum: Optional[float] = None
) -> float:
    """``float(os.environ[name])`` with a typed, actionable error."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = float(raw)
    except ValueError:
        raise PallasConfigError(
            f"{name}={raw!r} is not a number (expected e.g. "
            f"{name}={default}); unset it to use the default"
        ) from None
    if minimum is not None and value < minimum:
        raise PallasConfigError(
            f"{name}={value} is below the minimum {minimum}; unset it "
            f"to use the default {default}"
        )
    return value


def perf_decision(
    key: str, default: str, env_var: str, path: Optional[str] = None
) -> Tuple[str, str]:
    """Resolve a routing decision to ``(value, source)``: env override
    > the committed PERF_DECISIONS.json record > ``default``.  The
    library twin of ``bench.perf_decision`` (same precedence, same
    never-raises-on-a-bad-record contract), parameterized on the record
    path so tests can redirect it."""
    value = os.environ.get(env_var)
    source = f"env:{env_var}"
    if not value:
        try:
            with open(path or PERF_DECISIONS_PATH) as f:
                data = json.load(f)
            # A JSON-valid non-object record degrades like a missing
            # one — this resolver never raises on a bad record.
            value = data.get(key) if isinstance(data, dict) else None
            source = "PERF_DECISIONS.json"
        except (OSError, ValueError):  # svoclint: disable=SVOC014 -- deliberate: "this resolver never raises on a bad record" is its documented contract; a missing/corrupt PERF_DECISIONS.json resolves to the default, and every consumer logs the resolved (value, source) pair at construction
            value = None
    if not value:
        value, source = default, "default"
    return value, source


def validate_consensus_impl(impl: str, source: str = "caller") -> str:
    """Reject anything outside :data:`ALLOWED_CONSENSUS_IMPLS` with a
    message naming the allowed values AND the deciding env var."""
    if impl not in ALLOWED_CONSENSUS_IMPLS:
        allowed = ", ".join(repr(v) for v in ALLOWED_CONSENSUS_IMPLS)
        raise ConsensusImplError(
            f"consensus_impl {impl!r} (from {source}) is not a known "
            f"consensus implementation: allowed values are {allowed}; "
            f"set {CONSENSUS_IMPL_ENV} to override the committed "
            "PERF_DECISIONS.json record"
        )
    return impl


def resolve_consensus_impl(path: Optional[str] = None) -> str:
    """The production consensus-impl routing: env > committed record >
    ``"xla"``, validated.  Resolved ONCE per :class:`ClaimRouter` (the
    impl choice is part of a seeded replay's config — docs/FABRIC.md
    §replay), so the file read never sits on the per-step hot path."""
    impl, source = perf_decision(
        "consensus_impl", "xla", CONSENSUS_IMPL_ENV, path=path
    )
    return validate_consensus_impl(impl, source)


def pallas_interpret_opt_in() -> bool:
    return os.environ.get(PALLAS_INTERPRET_ENV) == "1"


ALLOWED_COMMIT_MODES = ("per_tx", "batched")
COMMIT_MODE_ENV = "SVOC_COMMIT_MODE"


class CommitModeError(ValueError):
    """An unknown commit-plane mode was requested (env override or a
    corrupt committed record)."""


def validate_commit_mode(mode: str, source: str = "caller") -> str:
    if mode not in ALLOWED_COMMIT_MODES:
        allowed = ", ".join(repr(v) for v in ALLOWED_COMMIT_MODES)
        raise CommitModeError(
            f"commit_mode {mode!r} (from {source}) is not a known commit "
            f"mode: allowed values are {allowed}; set {COMMIT_MODE_ENV} "
            "to override the committed PERF_DECISIONS.json record"
        )
    return mode


def resolve_commit_mode(path: Optional[str] = None) -> str:
    """The commit-plane routing twin of :func:`resolve_consensus_impl`
    (docs/RESILIENCE.md §batched-commits): ``SVOC_COMMIT_MODE`` env >
    the committed ``PERF_DECISIONS.json`` ``commit_mode`` record
    (written by ``tools/decide_perf.py`` from the measured
    ``BENCH_HOTPATH`` host-overhead A/B, never by hand) > ``"per_tx"``.

    ``"batched"`` sends a claim's whole fleet payload as ONE chain RPC
    (:meth:`svoc_tpu.io.chain.ChainAdapter.update_predictions_batched`)
    with a counted, never-silent per-tx fallback
    (``commit_batch_fallback{reason=}``); ``"per_tx"`` keeps the
    reference's one-signed-tx-per-oracle loop.  Both produce identical
    journal events and chain state — the mode only changes the RPC and
    WAL-record granularity, so it must be resolved ONCE per Session
    (the WAL family of a seeded crash replay depends on it)."""
    mode, source = perf_decision(
        "commit_mode", "per_tx", COMMIT_MODE_ENV, path=path
    )
    return validate_commit_mode(mode, source)


ALLOWED_WARMUP_MODES = ("none", "prewarm")
WARMUP_MODE_ENV = "SVOC_WARMUP"

ALLOWED_COMPILATION_CACHES = ("off", "persistent")
COMPILATION_CACHE_ENV = "SVOC_COMPILATION_CACHE"


class CompilePlaneError(ValueError):
    """An unknown warmup mode / compilation-cache mode was requested
    (env override or a corrupt committed record)."""


def resolve_warmup_mode(path: Optional[str] = None) -> str:
    """The compile-plane warmup routing twin of
    :func:`resolve_consensus_impl` (docs/PARALLELISM.md §compile-plane):
    ``SVOC_WARMUP`` env > the committed ``PERF_DECISIONS.json``
    ``warmup_mode`` record (written by ``tools/decide_perf.py`` from
    the measured ``BENCH_COLDSTART`` A/B — host-side evidence, so the
    CPU container qualifies like ``commit_mode``) > ``"none"``.

    ``"prewarm"`` walks the enumerated shape universe through AOT
    ``lower().compile()`` + dispatch priming at startup/recovery
    (:mod:`svoc_tpu.compile.prewarm`); ``"none"`` keeps the historical
    compile-on-first-request behavior.  Warmup NEVER changes numerics
    or journal events (``make coldstart-smoke`` pins fingerprint
    identity), so unlike impl/mesh it is not a fingerprint family —
    but it is still resolved ONCE per router construction (SVOC011):
    a mid-run flip would make cold/warm accounting uninterpretable."""
    mode, source = perf_decision(
        "warmup_mode", "none", WARMUP_MODE_ENV, path=path
    )
    if mode not in ALLOWED_WARMUP_MODES:
        allowed = ", ".join(repr(v) for v in ALLOWED_WARMUP_MODES)
        raise CompilePlaneError(
            f"warmup_mode {mode!r} (from {source}) is not a known "
            f"warmup mode: allowed values are {allowed}; set "
            f"{WARMUP_MODE_ENV} to override the committed record"
        )
    return mode


def resolve_compilation_cache(path: Optional[str] = None) -> str:
    """Persistent-compilation-cache routing
    (docs/RESILIENCE.md §compile-cache): ``SVOC_COMPILATION_CACHE`` env
    > the committed ``PERF_DECISIONS.json`` ``compilation_cache``
    record > ``"off"``.  ``"persistent"`` points
    ``jax_compilation_cache_dir`` under the durability base dir at
    :class:`~svoc_tpu.durability.recovery.RecoveryManager` construction
    (the only place that knows the base dir), so compiled programs
    survive the PR 8 kill/restart cycle.  Purely an execution-cost
    knob — cached and fresh compiles produce identical programs."""
    mode, source = perf_decision(
        "compilation_cache", "off", COMPILATION_CACHE_ENV, path=path
    )
    if mode not in ALLOWED_COMPILATION_CACHES:
        allowed = ", ".join(repr(v) for v in ALLOWED_COMPILATION_CACHES)
        raise CompilePlaneError(
            f"compilation_cache {mode!r} (from {source}) is not a known "
            f"mode: allowed values are {allowed}; set "
            f"{COMPILATION_CACHE_ENV} to override the committed record"
        )
    return mode


#: ``SVOC_MESH=<claims>x<oracles>`` — operator override for the claim
#: mesh (kept in sync with ``svoc_tpu.parallel.mesh.CLAIM_MESH_ENV``;
#: duplicated literal so this resolver keeps importing no jax).
CLAIM_MESH_ENV = "SVOC_MESH"


def resolve_claim_mesh(path: Optional[str] = None) -> Optional[str]:
    """The claim-cube MESH routing twin of
    :func:`resolve_consensus_impl`: ``SVOC_MESH`` env > the committed
    ``PERF_DECISIONS.json`` ``claim_mesh`` record (written by
    ``tools/decide_perf.py`` from a measured ``BENCH_SHARD`` sweep,
    never by hand) > ``None`` (unsharded single-device dispatch).

    Returns the raw ``"<claims>x<oracles>"`` spec string or ``None``;
    :func:`svoc_tpu.parallel.mesh.claim_mesh` validates and builds the
    mesh.  Resolved ONCE per :class:`ClaimRouter` construction — the
    mesh, like the impl, is part of a seeded replay's config
    (docs/FABRIC.md §mesh) and must not drift mid-run.
    """
    value, _source = perf_decision("claim_mesh", "", CLAIM_MESH_ENV, path=path)
    if not value or str(value).strip().lower() in ("none", "off"):
        return None
    return str(value)


# ---------------------------------------------------------------------------
# Fallback accounting: no silent XLA fallbacks.
# ---------------------------------------------------------------------------

FALLBACK_COUNTER = "consensus_pallas_fallback"
BATCH_FALLBACK_COUNTER = "commit_batch_fallback"


class _FallbackReporter:
    """Counted, never-silent fallback accounting with a one-shot log
    per reason (a steady-state fallback must not spam the log at
    dispatch/commit rate; the counter carries the rate).  One
    parameterized instance per fallback family — the pallas→XLA route
    and the batched→per-tx commit plane share the machinery instead of
    duplicating it."""

    def __init__(self, counter: str, logger_name: str, what: str):
        self.counter = counter
        self._log = logging.getLogger(logger_name)
        self._what = what
        self._lock = threading.Lock()
        self._logged_reasons: set = set()

    def report(
        self,
        reason: str,
        *,
        op: str,
        detail: str = "",
        metrics: Optional[MetricsRegistry] = None,
    ) -> None:
        (metrics or _default_registry).counter(
            self.counter, labels={"reason": reason}
        ).add(1)
        with self._lock:
            if reason in self._logged_reasons:
                return
            self._logged_reasons.add(reason)
        self._log.warning(
            "%s fell back to %s (reason=%s%s); further fallbacks are "
            "counted in %s{reason=%s} without logging",
            op,
            self._what,
            reason,
            f": {detail}" if detail else "",
            self.counter,
            reason,
        )

    def reset(self) -> None:
        with self._lock:
            self._logged_reasons.clear()


_pallas_reporter = _FallbackReporter(
    FALLBACK_COUNTER,
    "svoc_tpu.consensus.pallas",
    "the XLA consensus kernel",
)
_batch_reporter = _FallbackReporter(
    BATCH_FALLBACK_COUNTER,
    "svoc_tpu.io.chain.batch",
    "the per-tx loop",
)


def report_pallas_fallback(
    reason: str,
    *,
    op: str = "fused_consensus",
    detail: str = "",
    metrics: Optional[MetricsRegistry] = None,
) -> None:
    """Count one pallas→XLA fallback and log the FIRST occurrence of
    each reason.

    Reasons: ``fleet_too_large`` (over ``SVOC_PALLAS_MAX_ORACLES``),
    ``unaligned_fleet`` (fleet not a multiple of the rank block),
    ``smooth_mode`` (non-cairo median), ``non_tpu`` (no TPU backend and
    no ``SVOC_PALLAS_INTERPRET=1`` opt-in), ``mosaic_error`` (the
    kernel raised at lowering/compile/run time),
    ``sharded_unsupported`` (a pallas route on a claim mesh whose
    oracle axis is sharded — partial fleets cannot feed the fused
    kernel, the XLA sharded body serves instead;
    :mod:`svoc_tpu.parallel.claim_shard`).
    """
    _pallas_reporter.report(reason, op=op, detail=detail, metrics=metrics)


def reset_fallback_log() -> None:
    """Re-arm the one-shot pallas log (tests)."""
    _pallas_reporter.reset()


def report_batch_fallback(
    reason: str,
    *,
    detail: str = "",
    metrics: Optional[MetricsRegistry] = None,
) -> None:
    """Count one batched-commit → per-tx fallback and log the FIRST
    occurrence of each reason — the commit plane's twin of
    :func:`report_pallas_fallback` (no silent mode degradation:
    docs/RESILIENCE.md §batched-commits).

    Reasons: ``unsupported`` (the backend has no batched entrypoint —
    Sepolia, chaos wrappers), ``skip_slots`` (quarantine refusals force
    tx granularity: the batched entrypoint commits a contiguous caller
    range), ``batch_error`` (the single batched RPC failed mid-fleet;
    the resume loop re-sends the stranded suffix per tx),
    ``uncertified`` (a raise-mode backend declined before mutation).
    """
    _batch_reporter.report(
        reason, op="batched fleet commit", detail=detail, metrics=metrics
    )


def reset_batch_fallback_log() -> None:
    """Re-arm the one-shot batched-commit log (tests)."""
    _batch_reporter.reset()
