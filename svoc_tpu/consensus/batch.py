"""Fleet-scale batched prediction commit — device-certified, golden-exact.

The reference commits a fleet by looping one signed tx per oracle
(``client/contract.py:200-208``); after activation every tx triggers a
full on-chain consensus recompute (``contract.cairo:331-343`` +
``:447-449``).  The faithful simulator does the same with the exact
big-int engine, which is O(N·(N log N + N·M)) host work per fleet cycle
— minutes at N=1024 against ~1 ms of device time.

The batched path keeps bit-exact final state at O(1) golden recomputes:

1. Intermediate recomputes (txs 1..T-1 after activation) write ONLY
   derived state that the next recompute overwrites, so they are
   unobservable from outside the batch — **except when they panic**,
   which reverts that tx and stops the commit loop.
2. The exact engine's complete panic surface is known
   (:mod:`svoc_tpu.ops.fixedpoint` / ``math.cairo``):
   - ``interval_check`` on either reliability (< 0, constrained only;
     ``contract.cairo:396,419,467,488``),
   - ``wsad_sqrt(1)`` — Newton's first guess is ``1//2 = 0`` and the
     next iterate divides by it (``math.cairo:277-285``),
   - zero/one variance in skewness/kurtosis — ``std == 0`` divides by
     zero (``math.cairo:320-343``),
   - an ``unconstrained_max_spread`` of 0 (``contract.cairo:365-368``).
3. A vmapped float sweep over all intermediate prefix states
   (:func:`prefix_margins`, one fused XLA computation on the
   accelerator) certifies every recompute sits OUTSIDE those surfaces
   by a guard band ≫ float error.  Certified ⇒ apply all txs and run
   the golden engine once on the final block.  Not certified (or
   duplicate callers) ⇒ exact sequential fallback.

Float-vs-int divergence cannot break this: margins are ≥ 0.4 wsad
units against an f32 error ≤ ~0.1 on [0,1]-bounded inputs, and a
near-tie at the reliability boundary (where the float and Cairo orders
could pick different reliable SETS) independently fails certification
via the ``boundary_gap`` margin.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from svoc_tpu.consensus.dispatch import (
    pallas_interpret_opt_in,
    report_pallas_fallback,
    resolve_consensus_impl,
    validate_consensus_impl,
)
from svoc_tpu.consensus.kernel import (
    ConsensusConfig,
    ConsensusOutput,
    _reliability,
    consensus_step_claims,
    consensus_step_gated_claims,
)
from svoc_tpu.ops import pallas_consensus as pallas_ops
from svoc_tpu.ops import sort as sort_ops
from svoc_tpu.ops import stats
from svoc_tpu.ops.fixedpoint import WSAD
from svoc_tpu.robustness.sanitize import quarantine_mask_claims


class PrefixMargins(NamedTuple):
    """Per-prefix distances to the exact engine's panic surfaces."""

    rel1: jnp.ndarray  # [K] first-pass reliability (float)
    rel2: jnp.ndarray  # [K] second-pass reliability (float)
    sqrt_arg1: jnp.ndarray  # [K] first-pass sqrt input (real units)
    sqrt_arg2: jnp.ndarray  # [K] second-pass sqrt input (real units)
    min_variance: jnp.ndarray  # [K] smallest reliable-subset variance
    boundary_gap: jnp.ndarray  # [K] qr gap around the reliability cut


def _one_prefix_margins(values: jnp.ndarray, cfg: ConsensusConfig) -> PrefixMargins:
    n, dim = values.shape
    all_mask = jnp.ones(n, dtype=bool)
    essence1 = stats.masked_smooth_median(values, all_mask, cfg.smooth_mode)
    qr = stats.quadratic_risk(values, essence1)
    mean_qr1 = jnp.mean(qr)
    rel1 = _reliability(cfg, mean_qr1, dim)

    reliable = sort_ops.reliability_mask(qr, cfg.n_failing)
    sorted_qr = jnp.sort(qr)
    thr = n - cfg.n_failing
    # Exact-int ties at the cut can order differently than float argsort;
    # a healthy gap certifies both worlds select the same reliable set.
    if 0 < cfg.n_failing:
        gap = sorted_qr[min(thr, n - 1)] - sorted_qr[thr - 1]
    else:
        gap = jnp.asarray(jnp.inf, dtype=values.dtype)  # no cut, no ties

    mean_qr2 = stats.masked_scalar_mean(qr, reliable)
    rel2 = _reliability(cfg, mean_qr2, dim)

    means = stats.masked_mean(values, reliable)
    variances = stats.masked_component_variance(values, reliable, means)

    if cfg.constrained:
        a1, a2 = mean_qr1 / dim, mean_qr2 / dim
    else:
        a1, a2 = mean_qr1, mean_qr2
    return PrefixMargins(rel1, rel2, a1, a2, jnp.min(variances), gap)


# static_argnames (not argnums): audited against the call sites —
# ``cfg`` is the only non-array argument, the name survives signature
# refactors that renumber positions, and JAX resolves it for positional
# callers too (consensus/state.py calls positionally).  ``ks`` stays a
# DYNAMIC array: its *values* never shape the program, only its length
# does, and callers bucket that length (state.py pads to a power of
# two) so distinct commit-batch sizes don't each pay a fresh compile.
@partial(jax.jit, static_argnames=("cfg",))
def prefix_margins_sweep(
    old_values: jnp.ndarray,  # [N, M] block before the batch
    new_values: jnp.ndarray,  # [N, M] block after every tx applied
    positions: jnp.ndarray,  # [N] int32 — tx index of oracle i (≥ T: absent)
    cfg: ConsensusConfig,
    ks: jnp.ndarray,  # [K] int32 prefix lengths to evaluate
) -> PrefixMargins:
    """Margins for every prefix state ``V_k`` (``V_k[i]`` is the new
    value iff oracle ``i``'s tx index is < ``k``) in one fused vmap."""

    def at_prefix(k):
        v = jnp.where((positions < k)[:, None], new_values, old_values)
        return _one_prefix_margins(v, cfg)

    return jax.vmap(at_prefix)(ks)


@dataclasses.dataclass(frozen=True)
class CertifyMargins:
    """Guard bands (real units) around the exact panic surfaces.

    f32 absolute error on these [0,1]-bounded reductions is ≲ 1e-7
    (≈ 0.1 wsad units); every band below clears that by ≥ 4×.
    """

    #: interval_check distance: reliabilities must clear 0 by this.
    rel: float = 1e-3
    #: ``wsad_sqrt`` panics exactly on int input 1 (i.e. [1, 2) wsad
    #: units): inputs must avoid [lo, hi] wsad units.
    sqrt_band_lo: float = 0.6
    sqrt_band_hi: float = 2.4
    #: variances feed sqrt AND the std divisor: int value must be ≥ 2,
    #: certified by clearing this many wsad units.
    variance: float = 2.4
    #: reliable-set agreement between float and Cairo tie order.
    boundary_gap: float = 1e-5


def certify(
    m: PrefixMargins, cfg: ConsensusConfig, strict_interval: bool,
    bands: CertifyMargins = CertifyMargins(),
    lineage=None,
) -> np.ndarray:
    """Per-prefix bool: ``True`` ⇒ the exact engine provably completes
    this recompute without a panic (within the guard bands).
    ``lineage`` tags the certification span with the committing block's
    lineage id (``svoc_tpu.utils.events``); under a lineage-annotated
    ``commit`` span it is inherited automatically."""
    from svoc_tpu.utils.metrics import stage_span

    with stage_span("consensus_certify", lineage=lineage):
        return _certify(m, cfg, strict_interval, bands)


def _certify(
    m: PrefixMargins, cfg: ConsensusConfig, strict_interval: bool,
    bands: CertifyMargins,
) -> np.ndarray:
    # The np.asarray calls below ARE the host fetch of the margin sweep
    # (jit-dispatched by the caller) — the span covers device wait +
    # the band checks without adding a sync of its own.
    rel1 = np.asarray(m.rel1, dtype=np.float64)
    rel2 = np.asarray(m.rel2, dtype=np.float64)
    a1 = np.asarray(m.sqrt_arg1, dtype=np.float64) * WSAD
    a2 = np.asarray(m.sqrt_arg2, dtype=np.float64) * WSAD
    min_var = np.asarray(m.min_variance, dtype=np.float64) * WSAD
    gap = np.asarray(m.boundary_gap, dtype=np.float64)

    def sqrt_safe(a):
        return (a < bands.sqrt_band_lo) | (a > bands.sqrt_band_hi)

    ok = (
        sqrt_safe(a1)
        & sqrt_safe(a2)
        & (min_var > bands.variance)
        & (gap > bands.boundary_gap)
    )
    if strict_interval and cfg.constrained:
        # Constrained reliabilities are ≤ 1 by construction; only the
        # lower bound can panic.  Unconstrained ones are in [0,1] by
        # construction (min/ms ratio) — nothing to certify.
        ok &= (rel1 > bands.rel) & (rel2 > bands.rel)
    if not cfg.constrained and cfg.max_spread <= 0.0:
        # max_spread 0 divides by zero on every recompute.
        ok &= False
    return ok


# ---------------------------------------------------------------------------
# Claim micro-batches (docs/FABRIC.md): the fabric's one-dispatch
# consensus over a padded claim cube.
# ---------------------------------------------------------------------------


def pow2_bucket(n: int, floor: int = 1, multiple_of: int = 1) -> int:
    """Smallest power of two ≥ ``n`` (and ≥ ``floor``) — the claim
    router's micro-batch bucketing.  Claim counts change every
    scheduling tick (claims pause, registries grow); jitting the cube
    at the RAW count would recompile the consensus program per distinct
    count (the svoclint SVOC003 recompile hazard the prefix sweep's
    ``inter_ks`` bucketing already kills) — bucketing pins the compile
    count at log₂(max claims).

    ``multiple_of`` additionally rounds the bucket up to a multiple of
    the claim mesh's claim-axis size (docs/PARALLELISM.md
    §sharded-claims: shard_map needs ``C % mesh_claims == 0``).  It is
    fixed per process (the mesh is pinned at router construction), so
    the bucket set stays pow2-derived and the compile count bounded."""
    if n < 0:
        raise ValueError("n must be >= 0")
    if multiple_of < 1:
        raise ValueError("multiple_of must be >= 1")
    bucket = max(1, int(floor))
    while bucket < n:
        bucket *= 2
    if bucket % multiple_of:
        bucket = ((bucket + multiple_of - 1) // multiple_of) * multiple_of
    return bucket


#: Neutral fill for padding claims: mid-domain, in-range for every gate
#: config, and far from the exact engine's panic surfaces — though a
#: padding claim's outputs are masked out regardless.
_PAD_VALUE = 0.5


def pad_claim_cube(
    values: np.ndarray,
    ok: Optional[np.ndarray] = None,
    floor: int = 1,
    multiple_of: int = 1,
):
    """Pad a claim cube ``[C, N, M]`` (and its admission masks
    ``[C, N]``) to the pow2-bucketed claim count.

    Returns ``(values [B, N, M], ok [B, N], claim_mask [B])`` with
    ``B = pow2_bucket(C, floor, multiple_of)``: padding claims carry
    the neutral fill with all-admitted masks and ``claim_mask=False``
    — the kernel invalidates their outputs (``interval_valid=False``,
    zero essence) so the router can slice the first ``C`` rows and
    never observe filler.  ``multiple_of`` is the mesh claim-axis size
    when the cube dispatches sharded
    (:mod:`svoc_tpu.parallel.claim_shard`); the padded rows ride the
    sharded path through the SAME ``_mask_padded_claims`` the
    single-device kernel applies, so they stay inactive there too
    (pinned in ``tests/test_claim_shard.py``)."""
    values = np.asarray(values, dtype=np.float32)
    if values.ndim != 3:
        raise ValueError(f"claim cube must be [C, N, M], got {values.shape}")
    c, n, _m = values.shape
    if ok is None:
        ok = np.ones((c, n), dtype=bool)
    ok = np.asarray(ok, dtype=bool)
    if ok.shape != (c, n):
        raise ValueError(f"ok must be [C, N]={c, n}, got {ok.shape}")
    bucket = pow2_bucket(c, floor, multiple_of)
    claim_mask = np.zeros(bucket, dtype=bool)
    claim_mask[:c] = True
    if bucket == c:
        return values, ok, claim_mask
    pad_values = np.full(
        (bucket - c, n, values.shape[2]), _PAD_VALUE, dtype=np.float32
    )
    pad_ok = np.ones((bucket - c, n), dtype=bool)
    return (
        np.concatenate([values, pad_values], axis=0),
        np.concatenate([ok, pad_ok], axis=0),
        claim_mask,
    )


# static_argnames: ``cfg`` only (the audited prefix_margins_sweep
# pattern) — claim_mask/ok stay dynamic arrays, and the claim count is
# a SHAPE the caller pow2-buckets, so the compile count is bounded by
# log₂(max claims) per config.
@partial(jax.jit, static_argnames=("cfg",))
def _claims_consensus_xla(
    values: jnp.ndarray,  # [C, N, M] padded claim cube
    claim_mask: jnp.ndarray,  # [C] bool — active claims
    cfg: ConsensusConfig,
) -> ConsensusOutput:
    return consensus_step_claims(values, claim_mask, cfg)


# static_argnames: ``cfg`` only, as above.
@partial(jax.jit, static_argnames=("cfg",))
def _claims_consensus_gated_xla(
    values: jnp.ndarray,  # [C, N, M]
    ok: jnp.ndarray,  # [C, N] admission masks (True = admitted)
    claim_mask: jnp.ndarray,  # [C]
    cfg: ConsensusConfig,
) -> ConsensusOutput:
    return consensus_step_gated_claims(values, ok, claim_mask, cfg)


# The donated twin (docs/PARALLELISM.md §host-overhead): the claim cube
# is by far the largest buffer the fabric moves per cycle, and the
# device-resident router re-uploads it every cycle from a reusable host
# staging buffer — donating the upload lets the allocator recycle its
# device memory for the outputs instead of growing the live set each
# dispatch.  Same traced program as the undonated twin (donation is a
# buffer-aliasing hint, never a numerics change); callers must treat
# the donated array as CONSUMED (SVOC004) — the router rebinds a fresh
# upload every cycle and never re-reads it.
@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _claims_consensus_gated_xla_donated(
    values: jnp.ndarray,  # [C, N, M] — donated
    ok: jnp.ndarray,  # [C, N]
    claim_mask: jnp.ndarray,  # [C]
    cfg: ConsensusConfig,
) -> ConsensusOutput:
    return consensus_step_gated_claims(values, ok, claim_mask, cfg)


# ``lo``/``hi`` are static floats: they come from a SanitizeConfig (one
# or two distinct values per process — the constrained [0,1] gate and
# the unconstrained codec-only gate), not per-request data, so they
# cannot drive a recompile storm; tracing them would instead force the
# range checks through select ops the compiler can no longer fold away
# when a bound is absent (None).
@partial(jax.jit, static_argnames=("cfg", "lo", "hi"))
def _claims_consensus_sanitized_xla(
    values: jnp.ndarray,  # [C, N, M]
    claim_mask: jnp.ndarray,  # [C]
    cfg: ConsensusConfig,
    lo: Optional[float],
    hi: Optional[float],
):
    ok = quarantine_mask_claims(values, lo, hi)
    return consensus_step_gated_claims(values, ok, claim_mask, cfg), ok


# Donated twin of the fused gate+consensus program — the cube feeds the
# in-graph gate AND the kernel inside ONE traced program, so donation
# is safe here exactly because the fusion already removed the second
# consumer (the pallas route keeps the cube alive across two programs
# and therefore never donates).
@partial(jax.jit, static_argnames=("cfg", "lo", "hi"), donate_argnums=(0,))
def _claims_consensus_sanitized_xla_donated(
    values: jnp.ndarray,  # [C, N, M] — donated
    claim_mask: jnp.ndarray,  # [C]
    cfg: ConsensusConfig,
    lo: Optional[float],
    hi: Optional[float],
):
    ok = quarantine_mask_claims(values, lo, hi)
    return consensus_step_gated_claims(values, ok, claim_mask, cfg), ok


# static_argnames: the sanitize bounds only (see the sanitized wrapper
# above) — the pallas route computes the in-graph admission masks with
# the same traced gate, then hands them to the fused kernel's own jit.
@partial(jax.jit, static_argnames=("lo", "hi"))
def _quarantine_claims_jit(values, lo, hi):
    return quarantine_mask_claims(values, lo, hi)


def jit_dispatcher(sanitized: bool, donate: bool):
    """The module-level jitted dispatcher a (kind, donate) route runs —
    the SAME function objects :func:`claims_consensus_gated` /
    :func:`claims_consensus_sanitized` call, exposed so the compile
    plane's AOT prewarmer (:mod:`svoc_tpu.compile.prewarm`) lowers and
    compiles through them: a parallel re-jit of the same body would
    populate a DIFFERENT jit cache and the first real dispatch would
    recompile anyway (the whole point of prewarming lost, silently)."""
    if sanitized:
        return (
            _claims_consensus_sanitized_xla_donated
            if donate
            else _claims_consensus_sanitized_xla
        )
    return (
        _claims_consensus_gated_xla_donated
        if donate
        else _claims_consensus_gated_xla
    )


#: (n_oracles, dim, cfg) triples whose pallas dispatch raised — a
#: Mosaic lowering failure is deterministic per shape/config, so one
#: failure routes that group to XLA for the process lifetime instead of
#: re-raising (and re-catching) on every fabric cycle.  The COUNTER
#: still ticks per skipped dispatch; only the exception handling is
#: one-shot.
_MOSAIC_BROKEN: set = set()
_MOSAIC_LOCK = threading.Lock()


def _pallas_route(
    values: jnp.ndarray, cfg: ConsensusConfig, consensus_impl, metrics, op: str
) -> bool:
    """Whether this claim-cube dispatch should run the fused Pallas
    kernel.  Any "no" that was REQUESTED as pallas (the resolved impl
    said pallas but the dispatch cannot honor it) is a counted
    fallback — the no-silent-fallback contract."""
    impl = (
        validate_consensus_impl(consensus_impl)
        if consensus_impl is not None
        else resolve_consensus_impl()
    )  # svoclint: disable=SVOC011 -- deliberate: the fabric/serving path pins the impl at ClaimRouter construction and passes it in; the None fallback serves one-shot library callers only (docs/FABRIC.md §replay)
    if impl != "pallas":
        return False
    _c, n, dim = values.shape
    reason = pallas_ops.fused_fallback_reason(n, cfg)
    if reason is None and (n, dim, cfg) in _MOSAIC_BROKEN:
        reason = "mosaic_error"
    if reason is None and jax.default_backend() != "tpu":
        if not pallas_interpret_opt_in():  # svoclint: disable=SVOC011 -- deliberate: the interpret opt-in is a parity/test tool toggled per process by the pallas-parity harness; caching it would break the toggle and it is never set in production serving
            # Interpreter mode is a parity tool, not a serving path: a
            # pallas-routed CPU box serves the XLA graph and SAYS so.
            reason = "non_tpu"
    if reason is not None:
        report_pallas_fallback(reason, op=op, metrics=metrics)
        return False
    return True


def _pallas_broke(values, cfg, e: Exception, metrics, op: str) -> None:
    with _MOSAIC_LOCK:
        _MOSAIC_BROKEN.add((values.shape[1], values.shape[2], cfg))
    report_pallas_fallback(
        "mosaic_error",
        op=op,
        detail=f"{type(e).__name__}: {e}",
        metrics=metrics,
    )


def claims_consensus(
    values: jnp.ndarray,  # [C, N, M] padded claim cube
    claim_mask: jnp.ndarray,  # [C] bool — active claims
    cfg: ConsensusConfig,
    consensus_impl: Optional[str] = None,
    metrics=None,
) -> ConsensusOutput:
    """One fused dispatch of the ungated two-pass consensus over every
    claim in a micro-batch (leading claim axis on every output).

    ``consensus_impl`` picks the execution strategy (``"xla"`` |
    ``"pallas"``; ``None`` resolves env > PERF_DECISIONS.json > xla —
    :func:`svoc_tpu.consensus.dispatch.resolve_consensus_impl`).  The
    pallas route runs the gated fused kernel with all-admitted masks —
    documented identical semantics on finite cubes (``ok = ones`` ≡
    ungated, tests/test_robustness.py); non-finite rows additionally
    get the gated kernel's neutral fill instead of XLA's NaN
    propagation.  Every route the resolved pallas impl cannot honor is
    a counted fallback to XLA (``consensus_pallas_fallback{reason=}``).
    """
    if _pallas_route(values, cfg, consensus_impl, metrics, "claims_consensus"):
        ok = jnp.ones(values.shape[:2], dtype=bool)
        try:
            return pallas_ops.fused_consensus_gated_claims(
                values, ok, claim_mask, cfg
            )
        except Exception as e:  # noqa: BLE001 — counted, then XLA re-raises real input errors
            _pallas_broke(values, cfg, e, metrics, "claims_consensus")
    return _claims_consensus_xla(values, claim_mask, cfg)


def claims_consensus_gated(
    values: jnp.ndarray,  # [C, N, M]
    ok: jnp.ndarray,  # [C, N] admission masks (True = admitted)
    claim_mask: jnp.ndarray,  # [C]
    cfg: ConsensusConfig,
    consensus_impl: Optional[str] = None,
    metrics=None,
    donate: bool = False,
) -> ConsensusOutput:
    """One fused dispatch of the GATED two-pass consensus over a claim
    micro-batch with precomputed per-claim admission masks (the host
    gate's verdicts, re-used on device).  ``consensus_impl`` as in
    :func:`claims_consensus`; the XLA graph remains the parity oracle
    (``make pallas-parity``).

    ``donate=True`` routes the XLA path through the donated twin (the
    device-resident router's steady-state dispatch — the caller must
    never re-read ``values`` after this call).  A pallas route ignores
    the hint: its cube is not re-uploaded per cycle the same way, and
    numerics are unaffected either way."""
    if _pallas_route(
        values, cfg, consensus_impl, metrics, "claims_consensus_gated"
    ):
        try:
            return pallas_ops.fused_consensus_gated_claims(
                values, ok, claim_mask, cfg
            )
        except Exception as e:  # noqa: BLE001 — counted, then XLA re-raises real input errors
            _pallas_broke(values, cfg, e, metrics, "claims_consensus_gated")
    if not donate:
        return _claims_consensus_gated_xla(values, ok, claim_mask, cfg)
    return _claims_consensus_gated_xla_donated(values, ok, claim_mask, cfg)


def claims_consensus_sanitized(
    values: jnp.ndarray,  # [C, N, M]
    claim_mask: jnp.ndarray,  # [C]
    cfg: ConsensusConfig,
    lo: Optional[float],
    hi: Optional[float],
    consensus_impl: Optional[str] = None,
    metrics=None,
    donate: bool = False,
):
    """Gate + consensus fused into ONE traced program per micro-batch:
    the vmapped quarantine gate
    (:func:`svoc_tpu.robustness.sanitize.quarantine_mask_claims`)
    computes per-claim admission masks in-graph and the gated kernel
    consumes them without a host round-trip.  Returns ``(output, ok)``
    so the caller can still account per-claim admissions.  The pallas
    route keeps the no-host-round-trip property: the traced gate's
    masks feed the fused kernel's jit directly (two dispatches, zero
    fetches between them).  ``donate=True`` as in
    :func:`claims_consensus_gated` — XLA path only; the pallas route
    feeds the cube to TWO programs (gate jit + fused kernel) and must
    keep it alive."""
    if _pallas_route(
        values, cfg, consensus_impl, metrics, "claims_consensus_sanitized"
    ):
        try:
            ok = _quarantine_claims_jit(values, lo, hi)
            return (
                pallas_ops.fused_consensus_gated_claims(
                    values, ok, claim_mask, cfg
                ),
                ok,
            )
        except Exception as e:  # noqa: BLE001 — counted, then XLA re-raises real input errors
            _pallas_broke(
                values, cfg, e, metrics, "claims_consensus_sanitized"
            )
    if not donate:
        return _claims_consensus_sanitized_xla(
            values, claim_mask, cfg, lo, hi
        )
    return _claims_consensus_sanitized_xla_donated(
        values, claim_mask, cfg, lo, hi
    )
