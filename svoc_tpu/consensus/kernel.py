"""The two-pass consensus as one fused, jittable XLA graph.

Reference semantics: ``update_constrained_consensus``
(``contract/src/contract.cairo:442-503``) and
``update_unconstrained_consensus`` (``contract.cairo:370-434``):

1. FIRST PASS over all N oracles — essence₁ = component-wise smooth
   median; per-oracle quadratic risk vs essence₁; first-pass
   reliability; rank by risk and mark the worst ``n_failing`` oracles
   unreliable (``contract.cairo:345-363``).
2. SECOND PASS over the reliable subset — essence = smooth median
   (constrained) or mean (unconstrained); second-pass reliability with
   risk still centered on **essence₁** (a reference quirk:
   ``contract.cairo:414`` and ``:484``); component-wise skewness and
   kurtosis of the reliable subset (``contract.cairo:491-500``).

Reliability estimators (``documentation/README.md:116-150``):

- constrained: ``1 − 2·sqrt(mean(qr)/M)`` (``contract.cairo:436-439``)
- unconstrained: ``1 − min(ms, sqrt(mean(qr)))/ms`` with max-spread
  ``ms`` (``contract.cairo:365-368``)

The whole computation is fixed-shape: the second pass uses a boolean
reliability mask rather than dynamic filtering, so the graph vmaps over
Monte-Carlo batches and shard_maps over an oracle-sharded device mesh
(:mod:`svoc_tpu.parallel`) unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from svoc_tpu.ops import sort as sort_ops
from svoc_tpu.ops import stats


@dataclasses.dataclass(frozen=True)
class ConsensusConfig:
    """Static consensus parameters (the contract's constructor calldata,
    ``contract.cairo:236-265``, minus admin/oracle identities)."""

    n_failing: int = 2
    constrained: bool = True
    #: Unconstrained max spread ``ms`` in real units (wsad/1e6).
    max_spread: float = 10.0
    #: "cairo" replicates the reference's degenerate smooth median
    #: (mean of sorted[m/2-1], sorted[m/2]); "true" is the proper median.
    smooth_mode: str = "cairo"


class ConsensusOutput(NamedTuple):
    essence: jnp.ndarray  # [M] second-pass consensus value
    essence_first_pass: jnp.ndarray  # [M]
    reliability_first_pass: jnp.ndarray  # scalar
    reliability_second_pass: jnp.ndarray  # scalar
    reliable: jnp.ndarray  # [N] bool — passes the consensus
    quadratic_risk: jnp.ndarray  # [N] first-pass risk vs essence₁
    skewness: jnp.ndarray  # [M]
    kurtosis: jnp.ndarray  # [M]
    interval_valid: jnp.ndarray  # scalar bool — reliabilities ∈ [0,1]


def _reliability(cfg: ConsensusConfig, mean_qr: jnp.ndarray, dim: int) -> jnp.ndarray:
    if cfg.constrained:
        return 1.0 - 2.0 * jnp.sqrt(mean_qr / dim)
    u = jnp.sqrt(mean_qr)
    ms = cfg.max_spread
    return 1.0 - jnp.minimum(ms, u) / ms


def consensus_step(values: jnp.ndarray, cfg: ConsensusConfig) -> ConsensusOutput:
    """Run the full two-pass consensus on an oracle block ``values [N, M]``.

    Assumes every oracle has committed (the contract's activation gate,
    ``contract.cairo:447-449``, lives in the stateful wrapper).
    """
    n, dim = values.shape
    all_mask = jnp.ones(n, dtype=bool)

    # ---- FIRST PASS (contract.cairo:450-470) ----
    essence1 = stats.masked_smooth_median(values, all_mask, cfg.smooth_mode)
    qr = stats.quadratic_risk(values, essence1)
    rel1 = _reliability(cfg, jnp.mean(qr), dim)
    reliable = sort_ops.reliability_mask(qr, cfg.n_failing)

    # ---- SECOND PASS (contract.cairo:476-489) ----
    if cfg.constrained:
        essence2 = stats.masked_smooth_median(values, reliable, cfg.smooth_mode)
    else:
        essence2 = stats.masked_mean(values, reliable)
    # Second-pass risk is centered on essence₁, not essence₂ — reference
    # quirk at contract.cairo:414/:484, reproduced deliberately — so the
    # first-pass risks are reused verbatim, only the mean is re-masked.
    rel2 = _reliability(cfg, stats.masked_scalar_mean(qr, reliable), dim)

    # ---- MOMENTS of the reliable subset (contract.cairo:491-500) ----
    means = stats.masked_mean(values, reliable)
    variances = stats.masked_component_variance(values, reliable, means)
    skew = stats.masked_skewness(values, reliable, means, variances)
    kurt = stats.masked_kurtosis(values, reliable, means, variances)

    valid = jnp.logical_and(stats.interval_ok(rel1), stats.interval_ok(rel2))
    # A "consensus" of fewer than two reliable oracles is no consensus:
    # the smooth median averages sorted[m/2-1] and sorted[m/2], which at
    # m<=1 reads clipped/sentinel rows — the n_failing >= N-1 degenerate
    # block must surface as invalid, never as a confident essence built
    # from +inf sentinels (rel2 even evaluates to a clean 1.0 at m=0:
    # the masked mean of an empty risk set is 0).  n_failing is static,
    # so this folds to a constant in the common case.
    if n - cfg.n_failing < 2:
        valid = jnp.logical_and(valid, False)

    return ConsensusOutput(
        essence=essence2,
        essence_first_pass=essence1,
        reliability_first_pass=rel1,
        reliability_second_pass=rel2,
        reliable=reliable,
        quadratic_risk=qr,
        skewness=skew,
        kurtosis=kurt,
        interval_valid=valid,
    )


def consensus_step_batched(
    values: jnp.ndarray, cfg: ConsensusConfig
) -> ConsensusOutput:
    """vmap of :func:`consensus_step` over a leading batch axis ``[B, N, M]``
    — the Monte-Carlo / multi-window form."""
    return jax.vmap(lambda v: consensus_step(v, cfg))(values)


def consensus_step_gated(
    values: jnp.ndarray, ok: jnp.ndarray, cfg: ConsensusConfig
) -> ConsensusOutput:
    """Two-pass consensus over the ADMITTED subset of an oracle block.

    ``ok [N]`` is the input-integrity quarantine mask from
    :mod:`svoc_tpu.robustness.sanitize` (True = admitted): quarantined
    oracles are excluded from the first-pass median, carry a sentinel
    risk so the reliability ranking always drops them first, and can
    never enter the reliable set — a single NaN/Inf vector therefore
    cannot poison any reduction (the contract gets this for free by
    panicking the offending tx; the jittable kernel must mask instead).
    Fewer than two admitted — or two reliable — oracles flags
    ``interval_valid=False`` (no consensus), mirroring the degenerate
    ``n_failing >= N-1`` guard of :func:`consensus_step`.

    Semantics with ``ok = ones(N)`` are identical to
    :func:`consensus_step` (equivalence-tested in
    ``tests/test_robustness.py``).  This function is also the ONE
    per-claim program of the mesh-sharded claim cube
    (:mod:`svoc_tpu.parallel.claim_shard` vmaps it over the gathered
    block) — sharded-vs-single parity is bitwise because there is one
    implementation, not two that agree; restructuring these ops changes
    XLA's fusion rounding and breaks the 0.0 parity bar.
    """
    n, dim = values.shape
    # Neutral fill: quarantined rows are masked out of every reduction
    # below, but masked reductions multiply by 0 rather than select, and
    # 0 * NaN is NaN — the fill must happen before any arithmetic.
    safe = jnp.where(ok[:, None], values, 0.0)
    safe = jnp.where(jnp.isfinite(safe), safe, 0.0)
    n_ok = jnp.sum(ok.astype(jnp.int32))

    # ---- FIRST PASS over the admitted subset ----
    essence1 = stats.masked_smooth_median(safe, ok, cfg.smooth_mode)
    qr_raw = stats.quadratic_risk(safe, essence1)
    qr_ok = jnp.where(ok, qr_raw, 0.0)
    rel1 = _reliability(cfg, stats.masked_scalar_mean(qr_ok, ok), dim)
    reliable = sort_ops.gated_reliability_mask(qr_raw, ok, n_ok, cfg.n_failing)

    # ---- SECOND PASS (same essence₁-centered risk quirk) ----
    if cfg.constrained:
        essence2 = stats.masked_smooth_median(safe, reliable, cfg.smooth_mode)
    else:
        essence2 = stats.masked_mean(safe, reliable)
    rel2 = _reliability(cfg, stats.masked_scalar_mean(qr_ok, reliable), dim)

    means = stats.masked_mean(safe, reliable)
    variances = stats.masked_component_variance(safe, reliable, means)
    skew = stats.masked_skewness(safe, reliable, means, variances)
    kurt = stats.masked_kurtosis(safe, reliable, means, variances)

    n_rel = jnp.sum(reliable.astype(jnp.int32))
    valid = jnp.logical_and(stats.interval_ok(rel1), stats.interval_ok(rel2))
    valid = jnp.logical_and(valid, n_ok >= 2)
    valid = jnp.logical_and(valid, n_rel >= 2)
    # An all-quarantined (or single-survivor) block reports a FINITE
    # essence alongside its invalid flag — +inf sort sentinels must not
    # leak to callers that render before checking validity.
    essence2 = jnp.where(jnp.isfinite(essence2), essence2, 0.0)
    essence1 = jnp.where(jnp.isfinite(essence1), essence1, 0.0)

    return ConsensusOutput(
        essence=essence2,
        essence_first_pass=essence1,
        reliability_first_pass=rel1,
        reliability_second_pass=rel2,
        reliable=reliable,
        quadratic_risk=qr_raw,
        skewness=skew,
        kurtosis=kurt,
        interval_valid=valid,
    )


def consensus_step_gated_batched(
    values: jnp.ndarray, ok: jnp.ndarray, cfg: ConsensusConfig
) -> ConsensusOutput:
    """vmap of :func:`consensus_step_gated` over ``[B, N, M]`` blocks
    with per-block masks ``[B, N]``."""
    return jax.vmap(lambda v, m: consensus_step_gated(v, m, cfg))(values, ok)


# ---------------------------------------------------------------------------
# Claim as a batch axis (docs/FABRIC.md): the multi-claim fabric runs
# MANY independent markets/stories through one dispatch.  Semantically
# the claim axis is exactly the Monte-Carlo batch axis above — each
# claim is one [N, M] oracle block — plus a per-claim ACTIVITY mask:
# the claim router pads micro-batches to a pow2-bucketed claim count
# (svoclint SVOC003 discipline — distinct claim counts must not each
# pay a fresh compile), and a padding claim's outputs must read as
# "no consensus", never as a confident essence built from filler.
# ---------------------------------------------------------------------------


def _mask_padded_claims(
    out: ConsensusOutput, claim_mask: jnp.ndarray
) -> ConsensusOutput:
    """Invalidate the padding rows of a claim-batched output:
    ``interval_valid`` forced False, essences zeroed (a padding claim's
    filler block can produce arbitrary — even non-finite — values, and
    they must not leak to a caller that renders before checking the
    mask), reliability masks cleared."""
    active = claim_mask.astype(bool)
    row = active[:, None]
    return ConsensusOutput(
        essence=jnp.where(row, out.essence, 0.0),
        essence_first_pass=jnp.where(row, out.essence_first_pass, 0.0),
        reliability_first_pass=jnp.where(
            active, out.reliability_first_pass, 0.0
        ),
        reliability_second_pass=jnp.where(
            active, out.reliability_second_pass, 0.0
        ),
        reliable=jnp.logical_and(out.reliable, row),
        quadratic_risk=jnp.where(row, out.quadratic_risk, 0.0),
        skewness=jnp.where(row, out.skewness, 0.0),
        kurtosis=jnp.where(row, out.kurtosis, 0.0),
        interval_valid=jnp.logical_and(out.interval_valid, active),
    )


def consensus_step_claims(
    values: jnp.ndarray, claim_mask: jnp.ndarray, cfg: ConsensusConfig
) -> ConsensusOutput:
    """Two-pass consensus over a claim cube ``[C, N, M]``.

    Every output field grows a leading claim axis: per-claim essences,
    per-claim reliabilities, per-claim ``reliable`` masks ``[C, N]``
    and per-claim ``interval_valid``.  ``claim_mask [C]`` marks the
    ACTIVE claims (padding rows from the router's pow2 bucketing are
    False — see :func:`svoc_tpu.consensus.batch.pad_claim_cube`).
    Active claims compute exactly :func:`consensus_step_batched`, i.e.
    a vmap of the single-claim kernel — parity-tested against a Python
    loop of :func:`consensus_step` in ``tests/test_fabric.py``.
    """
    return _mask_padded_claims(consensus_step_batched(values, cfg), claim_mask)


def consensus_step_gated_claims(
    values: jnp.ndarray,
    ok: jnp.ndarray,
    claim_mask: jnp.ndarray,
    cfg: ConsensusConfig,
) -> ConsensusOutput:
    """Gated two-pass consensus over a claim cube ``[C, N, M]`` with
    per-claim quarantine masks ``ok [C, N]`` (True = admitted; from
    :func:`svoc_tpu.robustness.sanitize.quarantine_mask_claims`) and an
    activity mask ``claim_mask [C]``.

    Per-claim degenerate handling is inherited from
    :func:`consensus_step_gated`: a claim with fewer than two admitted
    (or two reliable) oracles reports ``interval_valid=False`` with a
    finite essence — one poisoned claim can never invalidate, or leak
    sentinels into, its siblings in the same micro-batch.
    """
    return _mask_padded_claims(
        consensus_step_gated_batched(values, ok, cfg), claim_mask
    )


def jit_consensus(cfg: ConsensusConfig):
    """Return a jitted single-block consensus closure for ``cfg``."""
    return jax.jit(lambda v: consensus_step(v, cfg))


def jit_consensus_gated(cfg: ConsensusConfig):
    """Jitted single-block GATED consensus closure for ``cfg`` — the
    per-claim reference the claim-cube path is parity-tested (and
    benchmarked, ``bench.py --claims``) against."""
    return jax.jit(lambda v, ok: consensus_step_gated(v, ok, cfg))
